"""Continuous-batching scheduler — admission control, slots, load shedding.

Orca/vLLM-style iteration-level scheduling on top of the paged cache: new
requests are admitted into FREE decode slots at step boundaries (never
mid-step — the compiled decode program runs whole batches of static
shape), finished/expired requests are evicted the same way, and the batch
is re-packed purely by rewriting page-table rows.

Robustness is the design center, not an afterthought:

  * **Bounded queue** — ``submit`` beyond ``max_queue`` is rejected
    immediately with a ``retry_after_s`` hint (queue depth x observed
    decode-step time), not buffered until memory or the SLO dies.
  * **SLO shedding** — while the rolling p99 time-to-first-token exceeds
    ``slo_ttft_s``, new submissions are shed: an overloaded server that
    answers some requests inside the SLO beats one that answers all of
    them late (every shed increments ``resilience_shed_total`` /
    ``serve_requests_shed_total``).  TTFT anchors at SUBMISSION, so queue
    wait counts.  NOTE: the p99 is a rank-local wall statistic — on
    coordinated multi-host replicas leave the SLO at 0 (shed at the
    frontend); a divergent shed decision raises ``DesyncError`` loudly
    rather than silently forking the batch (docs/serving.md).
  * **Total accounting** — every submitted request ends in EXACTLY one
    terminal outcome (``completed`` / ``shed`` / ``timed_out`` /
    ``preempted_requeue``); the invariant the serve smoke asserts under
    fault injection ("none lost, none duplicated").

All decisions are deterministic functions of (request stream, step
index, capacity): ``fingerprint()`` digests queue + slot assignment so the
serve loop's cross-rank agreement check catches any divergence before a
divergent batch can decode.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from . import reqtrace
from .kv_cache import PagedKVCache

__all__ = ["Request", "ShedError", "ContinuousBatchingScheduler"]

TERMINAL = ("completed", "shed", "timed_out", "preempted_requeue")


def _safe(name: str) -> str:
    """Metric-name-safe tenant slug (the alerts module's convention)."""
    return "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


def _tenant_weights_from_env() -> Dict[str, float]:
    """Parse ``VESCALE_SERVE_TENANT_WEIGHTS`` — ``"tenant:weight"`` pairs,
    comma-separated (``"paid:3,free:1"``).  Empty/unset means the
    weight-aware admission gate is OFF.  Malformed values raise: a
    silently-dropped SLO class is worse than a crash at construction."""
    from ..analysis import envreg

    raw = envreg.get_str("VESCALE_SERVE_TENANT_WEIGHTS")
    if not raw:
        return {}
    out: Dict[str, float] = {}
    for part in raw.split(","):
        name, sep, w = part.strip().partition(":")
        if not sep or not name:
            raise ValueError(
                f"VESCALE_SERVE_TENANT_WEIGHTS: expected tenant:weight, got {part!r}"
            )
        out[name] = float(w)
    return out


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request.  ``deadline_steps`` is relative to the
    submission step (deterministic — the multi-host rig's unit); a wall
    deadline can ride on top via the loop's ``VESCALE_SERVE_DEADLINE_S``.
    ``eos_id`` stops generation early; ``max_new_tokens`` always bounds
    it.  ``tag`` is an OPAQUE client token echoed verbatim into this
    request's terminal outcome row — the fleet router stamps each
    dispatch attempt with one so a stale ledger row from a prior
    dispatch of the same rid can never be mistaken for the current
    attempt's result (serve/router.py)."""

    rid: int
    prompt: Tuple[int, ...]
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    deadline_steps: Optional[int] = None
    tag: Optional[int] = None
    # SLO class (per-tenant accounting + weight-aware shedding): requests
    # without one land in the "default" class, so single-tenant callers
    # never see the field
    tenant: str = "default"

    def __post_init__(self):
        if not self.prompt:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must be >= 1")
        if not self.tenant:
            raise ValueError(f"request {self.rid}: tenant must be a non-empty string")


class ShedError(RuntimeError):
    """Raised to a *direct* ``submit(..., raise_on_shed=True)`` caller when
    admission control rejects the request; carries the retry hint."""

    def __init__(self, rid: int, reason: str, retry_after_s: float):
        self.rid = rid
        self.reason = reason
        self.retry_after_s = retry_after_s
        super().__init__(
            f"request {rid} shed ({reason}); retry after ~{retry_after_s:.2f}s"
        )


@dataclasses.dataclass
class _InFlight:
    req: Request
    slot: int
    submit_step: int
    admit_step: int
    submit_wall: float = 0.0  # perf_counter at SUBMISSION (TTFT anchor —
    # queue wait is the dominant TTFT term under load; kept across replays)
    admit_wall: float = 0.0  # perf_counter at admission (wall deadlines)
    tokens: List[int] = dataclasses.field(default_factory=list)
    replays: int = 0
    # prompt tokens served from cached prefix pages (prefix_cache.py): the
    # loop prefills only prompt[prefix_hit:]
    prefix_hit: int = 0


class ContinuousBatchingScheduler:
    """Queue + slots + outcome ledger.  The serve loop drives it:
    ``submit`` on arrivals, ``expire`` then ``admit`` at each step
    boundary, ``record_token`` per decoded token, ``complete`` / ``evict``
    / ``requeue_newest`` as decode results come back."""

    def __init__(
        self,
        cache: PagedKVCache,
        *,
        max_queue: Optional[int] = None,
        slo_ttft_s: Optional[float] = None,
        ttft_window: int = 256,
        prefix_cache: Optional["PrefixCache"] = None,
        tenant_weights: Optional[Dict[str, float]] = None,
    ):
        from ..analysis import envreg
        from ..telemetry.registry import Histogram

        self.cache = cache
        # radix-tree prefix cache (prefix_cache.py): admission consults it
        # for page-granular prompt-prefix hits.  Explicit instance wins;
        # otherwise VESCALE_SERVE_PREFIX_CACHE=1 builds one from env so
        # every driver (loop, fleet replica, bench) gets it with zero
        # call-site changes
        if prefix_cache is None and envreg.get_bool("VESCALE_SERVE_PREFIX_CACHE"):
            from .prefix_cache import PrefixCache

            prefix_cache = PrefixCache.from_env(cache)
        self.prefix = prefix_cache
        self.max_queue = (
            max_queue if max_queue is not None else envreg.get_int("VESCALE_SERVE_MAX_QUEUE")
        )
        if slo_ttft_s is None:
            slo_ttft_s = envreg.get_float("VESCALE_SERVE_SLO_TTFT_S")
        self.slo_ttft_s = float(slo_ttft_s) if slo_ttft_s else 0.0
        # (request, submit_step, submit_wall) — the wall stamp anchors TTFT
        self.queue: Deque[Tuple[Request, int, float]] = deque()
        self.active: Dict[int, _InFlight] = {}  # slot -> in-flight
        self.outcomes: Dict[int, Dict[str, Any]] = {}  # rid -> terminal record
        # own rolling histograms: admission control must work with telemetry
        # dormant (the registry classes are plain objects, not the gate)
        self._ttft = Histogram("serve_ttft_seconds", window=ttft_window)
        self._step_time = Histogram("serve_decode_step_seconds", window=ttft_window)
        self._itl = Histogram("serve_itl_seconds", window=ttft_window)
        # cold-start seed for retry_after_s: before any decode step has
        # been observed the 10ms floor wildly underestimates real models —
        # the loop seeds this from the first prefill wall time (see
        # seed_step_time) so the first shed wave's retry hint is honest
        self._step_time_seed: Optional[float] = None
        # goodput vs raw throughput (docs/serving.md): raw counts every
        # sampled token; goodput only tokens of COMPLETED requests
        self.raw_tokens = 0
        self.goodput_tokens = 0
        self.counts = {
            "submitted": 0,
            "admitted": 0,
            "completed": 0,
            "shed": 0,
            "timed_out": 0,
            "evicted": 0,
            "requeued": 0,
            "resubmitted": 0,
        }
        # ---- per-tenant SLO classes.  With weights configured (arg or
        # VESCALE_SERVE_TENANT_WEIGHTS "tenant:weight,..."), admission
        # becomes weight-aware: a tenant whose queued share exceeds its
        # weighted slice of max_queue sheds FIRST, before the global
        # limits touch anyone else.  Unconfigured (None/empty) the gate
        # is entirely off — single-tenant behavior is bit-identical.
        if tenant_weights is None:
            tenant_weights = _tenant_weights_from_env()
        self.tenant_weights: Dict[str, float] = dict(tenant_weights or {})
        for t, w in self.tenant_weights.items():
            if w <= 0:
                raise ValueError(f"tenant {t!r}: weight must be > 0, got {w}")
        # per-tenant accounting exists regardless of weights: counters and
        # a TTFT histogram per observed class (lazily created; the rollup
        # rides the /router v5 feed)
        self.tenant_counts: Dict[str, Dict[str, int]] = {}
        self._tenant_ttft: Dict[str, Any] = {}
        # queue depth per tenant, maintained INCREMENTALLY at every queue
        # mutation: the weight-aware shed check runs per submit and must
        # cost O(1), never a queue scan
        self._tenant_qdepth: Dict[str, int] = {}
        self._tenant_cap_cache: Dict[str, Optional[int]] = {}
        self._ttft_window = ttft_window
        # event-sourced digest: every scheduling decision folds into a
        # running crc so fingerprint() is O(1) per step boundary (the
        # control-plane exchange must cost << a decode step)
        self._digest = 0

    def _fold(self, *ints: int) -> None:
        self._digest = zlib.crc32(
            b"".join((v & 0xFFFFFFFF).to_bytes(4, "little") for v in ints), self._digest
        )

    # ------------------------------------------------------------- tenants
    def _tenant_counts(self, tenant: str) -> Dict[str, int]:
        counts = self.tenant_counts.get(tenant)
        if counts is None:
            counts = self.tenant_counts[tenant] = {
                "submitted": 0, "shed": 0, "completed": 0,
            }
        return counts

    def _tenant_observe_ttft(self, tenant: str, seconds: float) -> None:
        from .. import telemetry as _tel
        from ..telemetry.registry import Histogram

        hist = self._tenant_ttft.get(tenant)
        if hist is None:
            hist = self._tenant_ttft[tenant] = Histogram(
                f"serve_ttft_seconds_tenant_{_safe(tenant)}",
                window=self._ttft_window,
            )
        hist.observe(seconds)
        _tel.observe(f"serve_ttft_seconds_tenant_{_safe(tenant)}", seconds)

    def tenant_queue_depth(self, tenant: str) -> int:
        return self._tenant_qdepth.get(tenant, 0)

    def _tq(self, tenant: str, delta: int) -> None:
        d = self._tenant_qdepth.get(tenant, 0) + delta
        if d:
            self._tenant_qdepth[tenant] = d
        else:
            self._tenant_qdepth.pop(tenant, None)

    def tenant_cap(self, tenant: str) -> Optional[int]:
        """The weighted queue slice a tenant may hold before it sheds
        (None = no weights configured, gate off).  An UNLISTED tenant
        weighs 1.0 against the configured classes — naming only the paid
        class still deprioritizes everyone else deterministically."""
        if not self.tenant_weights or not self.max_queue:
            return None
        if tenant in self._tenant_cap_cache:  # weights are ctor-frozen
            return self._tenant_cap_cache[tenant]
        w = float(self.tenant_weights.get(tenant, 1.0))
        total = sum(self.tenant_weights.values())
        if tenant not in self.tenant_weights:
            total += 1.0
        cap = max(1, int(self.max_queue * w / total))
        self._tenant_cap_cache[tenant] = cap
        return cap

    def _tenant_shed_reason(self, req: Request) -> Optional[str]:
        cap = self.tenant_cap(req.tenant)
        if cap is not None and self.tenant_queue_depth(req.tenant) >= cap:
            return (
                f"tenant {req.tenant} over weighted queue share "
                f"({self.tenant_queue_depth(req.tenant)}/{cap})"
            )
        return None

    def tenant_stats(self) -> Dict[str, Dict[str, Any]]:
        """The per-tenant rollup the `/router` v5 feed carries: counters,
        live queue depth, weighted cap, and the class's own p99 TTFT (the
        burn-rate rules' per-class denominator)."""
        tenants = set(self.tenant_counts) | set(self._tenant_qdepth)
        out: Dict[str, Dict[str, Any]] = {}
        for t in sorted(tenants):
            counts = self._tenant_counts(t)
            hist = self._tenant_ttft.get(t)
            out[t] = {
                "submitted": counts["submitted"],
                "shed": counts["shed"],
                "completed": counts["completed"],
                "queue_depth": self.tenant_queue_depth(t),
                "weight": float(self.tenant_weights.get(t, 1.0)),
                "cap": self.tenant_cap(t),
                "ttft_p99_s": hist.percentile(0.99) if hist is not None else None,
            }
        return out

    # ------------------------------------------------------------- metrics
    def observe_ttft(self, seconds: float, tenant: Optional[str] = None) -> None:
        from .. import telemetry as _tel

        self._ttft.observe(seconds)
        _tel.observe("serve_ttft_seconds", seconds)
        if tenant is not None:
            self._tenant_observe_ttft(tenant, seconds)

    def observe_step_time(self, seconds: float) -> None:
        from .. import telemetry as _tel

        self._step_time.observe(seconds)
        _tel.observe("serve_decode_step_seconds", seconds)

    def observe_itl(self, seconds: float) -> None:
        from .. import telemetry as _tel

        self._itl.observe(seconds)
        _tel.observe("serve_itl_seconds", seconds)

    def ttft_p99(self) -> Optional[float]:
        return self._ttft.percentile(0.99)

    def seed_step_time(self, seconds: float) -> None:
        """Seed the decode-step estimator before any real sample exists
        (the loop passes the first PREFILL wall time — an overestimate of a
        decode step, so the cold retry hint errs conservative instead of
        telling shed clients to hammer a server that has never decoded).
        Ignored once set or once real samples landed."""
        if self._step_time_seed is None and self._step_time.count == 0:
            self._step_time_seed = max(float(seconds), 1e-4)

    def step_time_estimate(self) -> Optional[float]:
        """The scheduler's current best guess at the next decode step's wall
        time (seconds): observed p50, else the cold-start seed, else None.
        The serve loop records it as the per-step cost-audit prediction the
        measured wall time is joined against."""
        p50 = self._step_time.percentile(0.5)
        return p50 if p50 is not None else self._step_time_seed

    def retry_after_s(self) -> float:
        """Backpressure hint: how long until a shed client plausibly finds
        room — queue depth x observed decode-step p50.  Cold start (no
        decode step observed yet) falls back to the seeded estimate
        (seed_step_time: first prefill wall, or the loop's calibration-
        derived guess), then a 10ms floor so an unmeasured server still
        says *something* positive."""
        p50 = self._step_time.percentile(0.5)
        if p50 is None:
            p50 = self._step_time_seed or 0.01
        return max(0.01, (len(self.queue) + 1) * max(p50, 1e-4))

    def currently_shedding(self) -> Optional[str]:
        """The admission-control reason a new submission would be shed
        RIGHT NOW (bounded queue / p99-TTFT SLO breach), or None.  The
        ops endpoints publish it — ``accepting`` in the `/router` v2 feed
        and the ``Retry-After`` header — so a fleet router can spill load
        to a peer replica without paying a rejected round trip."""
        if len(self.queue) >= self.max_queue:
            return f"queue full ({len(self.queue)}/{self.max_queue})"
        if self.slo_ttft_s > 0:
            p99 = self.ttft_p99()
            if p99 is not None and p99 > self.slo_ttft_s:
                return f"p99 TTFT {p99:.3f}s over SLO {self.slo_ttft_s:g}s"
        return None

    # ----------------------------------------------------------- admission
    def submit(self, req: Request, step: int, raise_on_shed: bool = False) -> bool:
        """Enqueue a request at ``step``; returns False (and records the
        terminal ``shed`` outcome) when admission control rejects it."""
        from .. import telemetry as _tel

        if any(r.rid == req.rid for r, _, _ in self.queue) or any(
            f.req.rid == req.rid for f in self.active.values()
        ):
            raise ValueError(f"duplicate request id {req.rid} (still pending)")
        prior = self.outcomes.get(req.rid)
        if prior is not None:
            if prior.get("status") not in TERMINAL:
                raise ValueError(f"duplicate request id {req.rid} (replay pending)")
            # the retry_after_s contract: a shed/timed-out/preempted request
            # MAY come back with the same rid — the new attempt supersedes
            # the prior terminal outcome (ledger_check nets resubmissions)
            self.outcomes.pop(req.rid)
            self.counts["resubmitted"] += 1
            self._fold(17, req.rid, step)
        self.counts["submitted"] += 1
        tcounts = self._tenant_counts(req.tenant)
        tcounts["submitted"] += 1
        _tel.count(f"serve_tenant_{_safe(req.tenant)}_submitted_total")
        reqtrace.submit(req.rid, step, tag=req.tag)
        reason = self.currently_shedding()
        tenant_shed = False
        if reason is None:
            reason = self._tenant_shed_reason(req)
            tenant_shed = reason is not None
        total = len(req.prompt) + req.max_new_tokens
        if reason is None and total > self.cache.max_seq_len:
            reason = (
                f"request needs {total} tokens, "
                f"cache max_seq_len is {self.cache.max_seq_len}"
            )
        if reason is None and self.cache.pages_needed(total) > self.cache.num_pages - 1:
            # could NEVER be admitted even into an empty pool: shedding now
            # beats blocking the FIFO head forever
            reason = (
                f"request needs {self.cache.pages_needed(total)} pages, "
                f"pool holds {self.cache.num_pages - 1}"
            )
        if reason is not None:
            retry = self.retry_after_s()
            self.counts["shed"] += 1
            tcounts["shed"] += 1
            self.outcomes[req.rid] = {
                "status": "shed",
                "reason": reason,
                "retry_after_s": retry,
                "tokens": [],
                "tag": req.tag,
            }
            _tel.count("serve_requests_shed_total")
            _tel.count("resilience_shed_total")
            _tel.count(f"serve_tenant_{_safe(req.tenant)}_shed_total")
            _tel.record_event("serve_shed", rid=req.rid, reason=reason, retry_after_s=retry)
            reqtrace.terminal(req.rid, "shed", 0, reason=reason)
            self._fold(10, req.rid, step)
            if tenant_shed:
                # the weight-aware decision depends on the tenant-weights
                # config: fold it separately so a rank armed with a
                # different weight table desyncs BEFORE batches fork
                self._fold(20, req.rid, step)
            if raise_on_shed:
                raise ShedError(req.rid, reason, retry)
            return False
        self._fold(11, req.rid, step)
        self.queue.append((req, step, time.perf_counter()))
        self._tq(req.tenant, +1)
        _tel.set_gauge("serve_queue_depth", len(self.queue))
        return True

    def admit(self, step: int) -> List[_InFlight]:
        """Fill free slots from the queue head (FIFO — deterministic) at a
        step boundary; returns the newly admitted in-flight records (the
        loop prefills them).  A head request the cache cannot hold yet
        BLOCKS the queue (FIFO fairness: skipping it would starve long
        requests under a stream of short ones)."""
        from .. import telemetry as _tel

        admitted: List[_InFlight] = []
        while self.queue:
            req, submit_step, submit_wall = self.queue[0]
            matched = 0
            if self.prefix is not None:
                # the radix tree decides: matched pages map for free and
                # LRU-unreferenced cached leaves may be evicted to cover
                # the fresh remainder (prefix_cache.try_admit mutates
                # nothing but LRU clocks/evictions on failure)
                got = self.prefix.try_admit(req.prompt, req.max_new_tokens)
                if got is None:
                    break
                slot, matched = got
            else:
                if not self.cache.can_admit(len(req.prompt), req.max_new_tokens):
                    break
                slot = self.cache.alloc(len(req.prompt), req.max_new_tokens)
            self.queue.popleft()
            self._tq(req.tenant, -1)
            inf = _InFlight(req=req, slot=slot, submit_step=submit_step,
                            admit_step=step, submit_wall=submit_wall,
                            prefix_hit=matched)
            prev = self.outcomes.pop(req.rid, None)  # a replayed eviction
            if prev is not None and prev.get("status") not in ("evicted_replay",):
                raise RuntimeError(f"request {req.rid} readmitted after terminal {prev}")
            if prev is not None:
                inf.replays = int(prev.get("replays", 0)) + 1
            self.active[slot] = inf
            self.counts["admitted"] += 1
            admitted.append(inf)
            self._fold(12, req.rid, slot, step)
            if matched:
                # the hit is a scheduling decision: fold it so a rank
                # whose tree diverged desyncs BEFORE the batch decodes
                self._fold(19, req.rid, matched)
                _tel.count("serve_prefix_hits_total")
                _tel.count("serve_prefix_hit_tokens_total", matched)
            _tel.count("serve_requests_admitted_total")
        _tel.set_gauge("serve_queue_depth", len(self.queue))
        _tel.set_gauge("serve_inflight", len(self.active))
        return admitted

    # ------------------------------------------------------------ outcomes
    def _terminal(self, inf: _InFlight, status: str, **extra) -> None:
        self.outcomes[inf.req.rid] = {
            "status": status,
            "tokens": list(inf.tokens),
            "replays": inf.replays,
            "tag": inf.req.tag,  # the request's opaque token, echoed
            **extra,
        }

    def record_token(self, slot: int, token: int) -> None:
        self.active[slot].tokens.append(int(token))
        self.raw_tokens += 1

    def complete(self, slot: int) -> Dict[str, Any]:
        """EOS / token budget reached: the request is done."""
        from .. import telemetry as _tel

        inf = self.active.pop(slot)
        self.cache.free(slot)
        self.counts["completed"] += 1
        self._tenant_counts(inf.req.tenant)["completed"] += 1
        # goodput: only tokens that reached a COMPLETED terminal count
        self.goodput_tokens += len(inf.tokens)
        self._fold(13, inf.req.rid, slot, len(inf.tokens))
        self._terminal(inf, "completed")
        reqtrace.terminal(inf.req.rid, "completed", len(inf.tokens), slot=slot)
        _tel.count("serve_requests_completed_total")
        _tel.count(f"serve_tenant_{_safe(inf.req.tenant)}_completed_total")
        _tel.count("serve_goodput_tokens_total", len(inf.tokens))
        _tel.set_gauge("serve_inflight", len(self.active))
        return self.outcomes[inf.req.rid]

    def timeout(self, slot: int, reason: str = "deadline") -> Dict[str, Any]:
        """Deadline expired mid-flight: cancel, free the slot, record the
        EXPLICIT rejection (partial tokens kept for diagnosis)."""
        from .. import telemetry as _tel

        inf = self.active.pop(slot)
        self.cache.free(slot)
        self.counts["timed_out"] += 1
        self._fold(14, inf.req.rid, slot)
        self._terminal(inf, "timed_out", reason=reason)
        reqtrace.terminal(inf.req.rid, "timed_out", len(inf.tokens),
                          reason=reason, slot=slot)
        _tel.count("serve_requests_timed_out_total")
        _tel.record_event("serve_timeout", rid=inf.req.rid, slot=slot, reason=reason)
        _tel.set_gauge("serve_inflight", len(self.active))
        return self.outcomes[inf.req.rid]

    def timeout_queued(self, step: int) -> List[int]:
        """Expire queued (never admitted) requests whose step deadline
        passed while they waited."""
        from .. import telemetry as _tel

        expired: List[int] = []
        keep: Deque[Tuple[Request, int, float]] = deque()
        for req, submit_step, submit_wall in self.queue:
            d = req.deadline_steps
            if d is not None and step - submit_step > d:
                self.counts["timed_out"] += 1
                self._fold(18, req.rid, step)
                self.outcomes[req.rid] = {
                    "status": "timed_out",
                    "tokens": [],
                    "replays": self._queued_replays(req.rid),
                    "reason": "queued past deadline",
                    "tag": req.tag,
                }
                reqtrace.terminal(req.rid, "timed_out", 0,
                                  reason="queued past deadline")
                _tel.count("serve_requests_timed_out_total")
                _tel.record_event("serve_timeout", rid=req.rid,
                                  reason="queued past deadline")
                expired.append(req.rid)
                self._tq(req.tenant, -1)
            else:
                keep.append((req, submit_step, submit_wall))
        self.queue = keep
        if expired:
            _tel.set_gauge("serve_queue_depth", len(self.queue))
        return expired

    def _queued_replays(self, rid: int) -> int:
        """How many times a still-QUEUED rid has already been evicted and
        requeued — its ``evicted_replay`` transient marker records the
        pre-eviction count (the ledger and the span chain's evict-span
        count must agree even when the replay never gets readmitted)."""
        prev = self.outcomes.get(rid)
        if prev is not None and prev.get("status") == "evicted_replay":
            return int(prev.get("replays", 0)) + 1
        return 0

    def requeue_newest(self, reason: str = "oom") -> Optional[int]:
        """Evict the NEWEST admitted request and replay it from the queue
        head — the mid-batch OOM protocol: the batch survives, the victim
        re-prefills later and (decode being deterministic) regenerates the
        same tokens.  Returns the victim rid, or None with nothing
        in-flight."""
        from .. import telemetry as _tel

        if not self.active:
            return None
        slot = max(self.active, key=lambda s: (self.active[s].admit_step, s))
        inf = self.active.pop(slot)
        self.cache.free(slot)
        self.counts["evicted"] += 1
        self.counts["requeued"] += 1
        self._fold(15, inf.req.rid, slot)
        # transient marker (NOT terminal): admit() consumes it to count
        # replays; generation restarts from the prompt
        self.outcomes[inf.req.rid] = {
            "status": "evicted_replay",
            "tokens": [],
            "replays": inf.replays,
            "reason": reason,
        }
        # the ORIGINAL submit stamps ride along: the replayed request's
        # TTFT honestly includes everything since the client submitted
        self.queue.appendleft((inf.req, inf.submit_step, inf.submit_wall))
        self._tq(inf.req.tenant, +1)
        # the fork marker: this rid's chain re-runs queue-wait -> prefill
        reqtrace.evict(inf.req.rid, slot, reason, replays=inf.replays + 1)
        _tel.count("serve_requests_evicted_total")
        _tel.record_event("serve_evict", rid=inf.req.rid, slot=slot, reason=reason)
        _tel.set_gauge("serve_inflight", len(self.active))
        return inf.req.rid

    def reject_queued(self, reason: str = "preempted") -> List[int]:
        """Drain protocol: every still-queued request is explicitly
        rejected as re-queueable (the client may resubmit verbatim after
        the restart) — never silently dropped."""
        from .. import telemetry as _tel

        rejected = []
        while self.queue:
            req, _, _ = self.queue.popleft()
            self._tq(req.tenant, -1)
            self._fold(16, req.rid)
            self.outcomes[req.rid] = {
                "status": "preempted_requeue",
                "tokens": [],
                "replays": self._queued_replays(req.rid),
                "reason": reason,
                "retry_after_s": self.retry_after_s(),
                "tag": req.tag,
            }
            reqtrace.terminal(req.rid, "preempted_requeue", 0, reason=reason)
            self.counts["shed"] += 1
            _tel.count("serve_requests_shed_total")
            _tel.count("resilience_shed_total")
            rejected.append(req.rid)
        _tel.set_gauge("serve_queue_depth", 0)
        return rejected

    # ------------------------------------------------------------ expiry
    def wall_expired_slots(self, now_s: float, wall_deadline_s: float) -> List[int]:
        """Slots whose request has been in flight longer than the wall
        budget — computed but NOT applied, so the serve loop can OR-agree
        the (rank-local, clock-dependent) verdict across ranks before any
        rank acts on it."""
        if not wall_deadline_s:
            return []
        return [
            slot for slot in sorted(self.active)
            if (now_s - self.active[slot].admit_wall) > wall_deadline_s
        ]

    def expire_active(self, step: int, force_slots: Sequence[int] = (),
                      wall_slots: Sequence[int] = ()) -> List[int]:
        """Timeout cancellation at a step boundary: step-deadline expiry,
        ``wall_slots`` (agreed wall-budget expiries from
        :meth:`wall_expired_slots`) and ``force_slots`` (the faultsim
        ``request_timeout`` kind).  Returns the cancelled rids."""
        out: List[int] = []
        for slot in sorted(self.active):
            inf = self.active[slot]
            d = inf.req.deadline_steps
            forced = slot in force_slots
            step_over = d is not None and step - inf.submit_step > d
            if forced or step_over or slot in wall_slots:
                reason = "injected request_timeout" if forced else (
                    "step deadline" if step_over else "wall deadline"
                )
                self.timeout(slot, reason=reason)
                out.append(inf.req.rid)
        return out

    # ----------------------------------------------------------- agreement
    def fingerprint(self) -> Tuple[int, ...]:
        """Deterministic digest of the full scheduling-decision history
        (every submit/shed/admit/complete/timeout/evict folds into a
        running crc as it happens — O(1) at exchange time) + the cache's
        allocation digest: the serve loop exchanges it so slot-assignment
        divergence raises as a DesyncError BEFORE a divergent batch
        decodes."""
        return (self._digest, len(self.queue), len(self.active)) + self.cache.fingerprint()

    def all_terminal(self) -> bool:
        return not self.queue and not self.active

    def ledger_check(self) -> None:
        """Assert total accounting: every accepted submission ended exactly
        one way (a resubmission supersedes its prior terminal outcome, so
        distinct outcomes == submissions minus resubmissions)."""
        terminal = [r for r in self.outcomes.values() if r.get("status") in TERMINAL]
        if self.queue or self.active:
            raise AssertionError("ledger_check before drain")
        expected = self.counts["submitted"] - self.counts["resubmitted"]
        if len(terminal) != expected:
            raise AssertionError(
                f"{self.counts['submitted']} submitted "
                f"({self.counts['resubmitted']} resubmissions) but "
                f"{len(terminal)} terminal outcomes"
            )
