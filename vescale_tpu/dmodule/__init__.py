from .api import parallelize_module, DModule, PlacementsInterface, pspec_of
