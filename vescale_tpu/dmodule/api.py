"""DModule — plan-driven TP/SP parallelization of flax modules.

Capability parity with the reference DModule (legacy/vescale/dmodule/):
  - ``parallelize_module(module, mesh, {"parameter": ..., "forward": ...})``
    <- dmodule/api.py:33
  - FQN-regex param plans -> param shardings  <- _dmodule.py:133,217
  - forward input/output resharding at module boundaries <- _hook.py:76-259
  - deferred init / materialize only the local shard <- initialize/deferred_init.py

TPU-native design: instead of per-module pre/post hooks issuing NCCL calls,
the plan lowers to

  * ``NamedSharding`` for every parameter (applied at init via jit
    ``out_shardings`` — parameters materialize *already sharded*, the
    deferred-init story, with no torchdistX patch), and
  * ``jax.lax.with_sharding_constraint`` at module boundaries via a flax
    method interceptor (the forward plan).  XLA inserts the collectives the
    reference's hooks performed (all-gather at TP boundaries, the SP
    Shard(seq) <-> Replicate transitions, grad psum in backward — the
    _grad_sync.py machinery is implicit in GSPMD's reverse-mode).

The sharding-plan *format* mirrors the reference examples
(e.g. legacy/examples/nanogpt_4D_finetune/sharding_plan.py): regex FQNs ->
placements.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

import flax.linen as nn

from ..mesh import DeviceMesh
from ..placements import Placement, Replicate, Shard, normalize_placements
from ..spec import DArraySpec, TensorMeta

__all__ = ["parallelize_module", "DModule", "PlacementsInterface", "pspec_of", "keypath_fqn"]


def keypath_fqn(keypath) -> str:
    """Dotted FQN for a jax tree keypath (DictKey/SequenceKey/etc.)."""
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return ".".join(parts)


def pspec_of(placements, ndim: int, mesh: DeviceMesh) -> PartitionSpec:
    """Lower placements to a logical PartitionSpec (Partial -> no constraint
    on that mesh dim; XLA tracks partial sums itself)."""
    placements = normalize_placements(placements, mesh.ndim, ndim)
    names: List[List[str]] = [[] for _ in range(ndim)]
    for i, p in enumerate(placements):
        if type(p) is Shard:
            names[p.dim].append(mesh.dim_name(i))
    return PartitionSpec(*(None if not ns else (ns[0] if len(ns) == 1 else tuple(ns)) for ns in names))


@dataclasses.dataclass
class PlacementsInterface:
    """Input/output resharding hints for one module
    (reference dmodule/placements_interface.py)."""

    input: Optional[Sequence] = None   # per positional arg: placements | None
    output: Optional[Sequence] = None  # per output leaf: placements | None

    @classmethod
    def normalize(cls, v) -> "PlacementsInterface":
        if isinstance(v, PlacementsInterface):
            return v
        if isinstance(v, dict):
            return cls(input=v.get("input"), output=v.get("output"))
        # bare list == input placements
        return cls(input=v)


def _match(plan: Dict[str, Any], fqn: str):
    for pattern, v in plan.items():
        if re.fullmatch(pattern, fqn):
            return v
    return None


def _constrain(x, placements, mesh: DeviceMesh):
    if placements is None or not isinstance(x, (jax.Array, jnp.ndarray)) or np.isscalar(x):
        return x
    spec = pspec_of(placements, x.ndim, mesh)
    # Inside a mesh context whose axis types differ from the plan's mesh
    # (e.g. the compiled pipeline's shard_map with a Manual pp axis), a
    # concrete NamedSharding would not match the context mesh — constrain
    # with the bare PartitionSpec so jax resolves it against the context,
    # dropping axes that are manual there (they're already local).
    ctx = jax.sharding.get_abstract_mesh()
    if ctx is not None and ctx.shape_tuple:  # non-empty context mesh
        manual = {
            n
            for n, t in zip(ctx.axis_names, ctx.axis_types)
            if t == jax.sharding.AxisType.Manual
        }
        def drop_manual(entry):
            if entry is None:
                return None
            if isinstance(entry, tuple):
                kept = tuple(n for n in entry if n not in manual)
                return kept if kept else None
            return None if entry in manual else entry
        spec = PartitionSpec(*(drop_manual(e) for e in spec))
        return jax.lax.with_sharding_constraint(x, spec)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh.jax_mesh, spec))


def _constrain_tree(tree, placements_list, mesh: DeviceMesh):
    leaves = tree if isinstance(tree, (tuple, list)) else (tree,)
    if placements_list is None:
        return tree
    # one placements entry per leaf; a single entry broadcasts
    pl = list(placements_list)
    if len(pl) == 1 and len(leaves) > 1:
        pl = pl * len(leaves)
    out = [
        _constrain(leaf, p, mesh) if p is not None else leaf
        for leaf, p in zip(leaves, pl + [None] * (len(leaves) - len(pl)))
    ]
    if isinstance(tree, tuple):
        return tuple(out)
    if isinstance(tree, list):
        return out
    return out[0]


class DModule:
    """A flax module bound to a mesh + sharding plan.

    Usage (mirrors reference dmodule/api.py:33):

        dmodel = parallelize_module(model, mesh, {"parameter": PARAM_PLAN,
                                                  "forward": FWD_PLAN})
        variables = dmodel.init(key, x)        # params born sharded
        out = dmodel.apply(variables, x)       # boundary resharding applied
    """

    def __init__(self, module: nn.Module, device_mesh: DeviceMesh, sharding_plan: Dict[str, Any]):
        self.module = module
        self.mesh = device_mesh
        plan = sharding_plan or {}
        self.param_plan: Dict[str, Any] = dict(plan.get("parameter", {}))
        self.fwd_plan: Dict[str, PlacementsInterface] = {
            k: PlacementsInterface.normalize(v) for k, v in dict(plan.get("forward", {})).items()
        }
        self.default_input_placements = plan.get("default_input", None)

    # --------------------------------------------------------- param plans
    def param_placements(self, path: str, ndim: int) -> Tuple[Placement, ...]:
        v = _match(self.param_plan, path)
        return normalize_placements(v, self.mesh.ndim, ndim)

    def _path_str(self, keypath) -> str:
        # drop the leading collection name ("params")
        return keypath_fqn(keypath[1:] if len(keypath) > 1 else keypath)

    def variables_shardings(self, abstract_variables):
        """Tree of NamedSharding for a variables pytree (params sharded per
        plan; other collections replicated)."""

        def one(keypath, leaf):
            path = self._path_str(keypath)
            coll = str(keypath[0].key) if hasattr(keypath[0], "key") else ""
            if coll != "params":
                return NamedSharding(self.mesh.jax_mesh, PartitionSpec())
            pl = self.param_placements(path, len(leaf.shape))
            return NamedSharding(self.mesh.jax_mesh, pspec_of(pl, len(leaf.shape), self.mesh))

        return jax.tree_util.tree_map_with_path(one, abstract_variables)

    def param_specs(self, variables):
        """Tree of DArraySpec for the params (used by optimizer/checkpoint)."""

        def one(keypath, leaf):
            path = self._path_str(keypath)
            pl = self.param_placements(path, len(leaf.shape))
            return DArraySpec(self.mesh, pl, TensorMeta(tuple(leaf.shape), leaf.dtype))

        return jax.tree_util.tree_map_with_path(one, variables)

    # ------------------------------------------------------------ init
    def init(self, rngs, *args, **kwargs):
        """Deferred + sharded init: trace init abstractly (eval_shape — the
        torchdistX-free deferred init), compute param shardings from the
        plan, then materialize each shard on its own devices via jit
        out_shardings (reference materialize_dtensor semantics)."""
        abstract = jax.eval_shape(lambda r: self.module.init(r, *args, **kwargs), rngs)
        shardings = self.variables_shardings(abstract)
        init_fn = jax.jit(
            lambda r: self.module.init(r, *args, **kwargs), out_shardings=shardings
        )
        return init_fn(rngs)

    # ------------------------------------------------------------ apply
    def _interceptor(self, next_fun, args, kwargs, context):
        if context.method_name != "__call__":
            return next_fun(*args, **kwargs)
        fqn = ".".join(context.module.path)
        pi = _match(self.fwd_plan, fqn)
        if pi is None:
            return next_fun(*args, **kwargs)
        if pi.input is not None:
            args = tuple(_constrain_tree(list(args), pi.input, self.mesh))
        out = next_fun(*args, **kwargs)
        if pi.output is not None:
            out = _constrain_tree(out, pi.output, self.mesh)
        return out

    def apply(self, variables, *args, **kwargs):
        with nn.intercept_methods(self._interceptor):
            return self.module.apply(variables, *args, **kwargs)

    def __call__(self, variables, *args, **kwargs):
        return self.apply(variables, *args, **kwargs)


def parallelize_module(
    module: nn.Module,
    device_mesh: DeviceMesh,
    sharding_plan: Optional[Dict[str, Any]] = None,
) -> DModule:
    """Reference dmodule/api.py:33 — wrap a module with a sharding plan."""
    return DModule(module, device_mesh, sharding_plan or {})
