"""DModule — plan-driven TP/SP parallelization of flax modules.

Capability parity with the reference DModule (legacy/vescale/dmodule/):
  - ``parallelize_module(module, mesh, {"parameter": ..., "forward": ...})``
    <- dmodule/api.py:33
  - FQN-regex param plans -> param shardings  <- _dmodule.py:133,217
  - forward input/output resharding at module boundaries <- _hook.py:76-259
  - deferred init / materialize only the local shard <- initialize/deferred_init.py

TPU-native design: instead of per-module pre/post hooks issuing NCCL calls,
the plan lowers to

  * ``NamedSharding`` for every parameter (applied at init via jit
    ``out_shardings`` — parameters materialize *already sharded*, the
    deferred-init story, with no torchdistX patch), and
  * ``jax.lax.with_sharding_constraint`` at module boundaries via a flax
    method interceptor (the forward plan).  XLA inserts the collectives the
    reference's hooks performed (all-gather at TP boundaries, the SP
    Shard(seq) <-> Replicate transitions, grad psum in backward — the
    _grad_sync.py machinery is implicit in GSPMD's reverse-mode).

The sharding-plan *format* mirrors the reference examples
(e.g. legacy/examples/nanogpt_4D_finetune/sharding_plan.py): regex FQNs ->
placements.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

import flax.linen as nn

from ..mesh import DeviceMesh
from ..placements import Placement, Replicate, Shard, normalize_placements
from ..spec import DArraySpec, TensorMeta

__all__ = ["parallelize_module", "DModule", "PlacementsInterface", "pspec_of", "keypath_fqn"]


def keypath_fqn(keypath) -> str:
    """Dotted FQN for a jax tree keypath (DictKey/SequenceKey/etc.)."""
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return ".".join(parts)


def pspec_of(placements, ndim: int, mesh: DeviceMesh) -> PartitionSpec:
    """Lower placements to a logical PartitionSpec (Partial -> no constraint
    on that mesh dim; XLA tracks partial sums itself)."""
    placements = normalize_placements(placements, mesh.ndim, ndim)
    names: List[List[str]] = [[] for _ in range(ndim)]
    for i, p in enumerate(placements):
        if type(p) is Shard:
            names[p.dim].append(mesh.dim_name(i))
    return PartitionSpec(*(None if not ns else (ns[0] if len(ns) == 1 else tuple(ns)) for ns in names))


@dataclasses.dataclass
class PlacementsInterface:
    """Input/output resharding hints for one module
    (reference dmodule/placements_interface.py)."""

    input: Optional[Sequence] = None   # per positional arg: placements | None
    output: Optional[Sequence] = None  # per output leaf: placements | None

    @classmethod
    def normalize(cls, v) -> "PlacementsInterface":
        if isinstance(v, PlacementsInterface):
            return v
        if isinstance(v, dict):
            return cls(input=v.get("input"), output=v.get("output"))
        # bare list == input placements
        return cls(input=v)


def _match(plan: Dict[str, Any], fqn: str) -> Tuple[Optional[str], Any]:
    """(pattern, value) of the first plan entry fullmatching ``fqn``."""
    for pattern, v in plan.items():
        if re.fullmatch(pattern, fqn):
            return pattern, v
    return None, None


def _abstract_mesh_ctx():
    """The current abstract-mesh context, or None when there is none.

    jax < 0.5 has no public ``jax.sharding.get_abstract_mesh`` (nor
    ``AxisType``); there no abstract-mesh context can be entered, so the
    concrete NamedSharding path below is always the right one."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is None:
        return None
    ctx = get()
    return ctx if getattr(ctx, "shape_tuple", None) else None


def _legacy_manual_axes():
    """Mesh axes bound as manual in the CURRENT trace on jax < 0.5.

    Pre-rename jax has no abstract-mesh context and no reliable
    partial-manual shard_map (collectives.shard_map drops ``axis_names``
    there), so inside a shard_map body EVERY bound axis is manual.  The
    legacy axis env is the only way to see that from here; empty outside
    shard_map (and on jax >= 0.5, where _abstract_mesh_ctx answers
    instead)."""
    if getattr(jax.sharding, "get_abstract_mesh", None) is not None:
        return frozenset()
    try:
        from jax._src.core import get_axis_env

        return frozenset(get_axis_env().axis_sizes)
    except (ImportError, AttributeError):  # pragma: no cover - other jaxes
        return frozenset()


def _constrain(x, placements, mesh: DeviceMesh):
    if placements is None or not isinstance(x, (jax.Array, jnp.ndarray)) or np.isscalar(x):
        return x
    spec = pspec_of(placements, x.ndim, mesh)
    # Inside a mesh context whose axis types differ from the plan's mesh
    # (e.g. the compiled pipeline's shard_map with a Manual pp axis), a
    # concrete NamedSharding would not match the context mesh — constrain
    # with the bare PartitionSpec so jax resolves it against the context,
    # dropping axes that are manual there (they're already local).
    ctx = _abstract_mesh_ctx()
    if ctx is not None:  # non-empty context mesh
        manual = {
            n
            for n, t in zip(ctx.axis_names, ctx.axis_types)
            if t == jax.sharding.AxisType.Manual
        }
        def drop_manual(entry):
            if entry is None:
                return None
            if isinstance(entry, tuple):
                kept = tuple(n for n in entry if n not in manual)
                return kept if kept else None
            return None if entry in manual else entry
        spec = PartitionSpec(*(drop_manual(e) for e in spec))
        return jax.lax.with_sharding_constraint(x, spec)
    # jax < 0.5 + inside shard_map: all bound axes are manual (no partial-
    # manual there) and a concrete NamedSharding over them raises.  The
    # constraint is a layout hint, never a semantics change — degrade to a
    # no-op, the _constrain_auto precedent (pipe/spmd.py).
    if _legacy_manual_axes():
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh.jax_mesh, spec))


def _constrain_entry(entry, placements, mesh: DeviceMesh):
    """Constrain every array leaf of one top-level entry (an arg, kwarg or
    output element — possibly itself a pytree) with the same placements."""
    if placements is None:
        return entry
    return jax.tree_util.tree_map(lambda leaf: _constrain(leaf, placements, mesh), entry)


def _align_placements(placements_list, n: int):
    pl = list(placements_list)
    if len(pl) == 1 and n > 1:
        pl = pl * n
    return pl + [None] * (n - len(pl))


def _constrain_inputs(args, kwargs, placements_list, mesh: DeviceMesh):
    """Reshard the FULL input tree — positional and keyword args alike
    (reference _hook.py:76 PreHookInput).  Placement entries align with the
    top-level entries in order (args, then kwargs in call order); each entry
    constrains all array leaves of that argument's subtree; a single entry
    broadcasts to every argument."""
    if placements_list is None:
        return args, kwargs
    entries = list(args) + list(kwargs.values())
    pl = _align_placements(placements_list, len(entries))
    out = [_constrain_entry(e, p, mesh) for e, p in zip(entries, pl)]
    return tuple(out[: len(args)]), dict(zip(kwargs.keys(), out[len(args):]))


def _constrain_tree(tree, placements_list, mesh: DeviceMesh):
    """Output resharding: placements align with the top-level elements of a
    tuple/list output (a single non-sequence output is one entry)."""
    if placements_list is None:
        return tree
    entries = list(tree) if isinstance(tree, (tuple, list)) else [tree]
    pl = _align_placements(placements_list, len(entries))
    out = [_constrain_entry(e, p, mesh) for e, p in zip(entries, pl)]
    if isinstance(tree, tuple):
        return tuple(out)
    if isinstance(tree, list):
        return out
    return out[0]


class DModule:
    """A flax module bound to a mesh + sharding plan.

    Usage (mirrors reference dmodule/api.py:33):

        dmodel = parallelize_module(model, mesh, {"parameter": PARAM_PLAN,
                                                  "forward": FWD_PLAN})
        variables = dmodel.init(key, x)        # params born sharded
        out = dmodel.apply(variables, x)       # boundary resharding applied
    """

    def __init__(
        self,
        module: nn.Module,
        device_mesh: DeviceMesh,
        sharding_plan: Dict[str, Any],
        validate_plan: bool = True,
    ):
        self.module = module
        self.mesh = device_mesh
        self.validate_plan = validate_plan
        plan = sharding_plan or {}
        self.param_plan: Dict[str, Any] = dict(plan.get("parameter", {}))
        self.fwd_plan: Dict[str, PlacementsInterface] = {
            k: PlacementsInterface.normalize(v) for k, v in dict(plan.get("forward", {})).items()
        }
        self.default_input_placements = plan.get("default_input", None)
        self._fwd_matched: set = set()
        self._param_matched: set = set()
        self._warned_fwd = False
        # static plan validation (analysis/shardcheck.py VSC107): Partial
        # params, un-normalizable entries.  Mode-gated (VESCALE_SHARDCHECK):
        # warn surfaces one aggregated warning, strict raises before any
        # parameter is materialized wrong
        if validate_plan and self.param_plan:
            from .. import analysis as _analysis

            if _analysis.enabled():
                _analysis.dispatch_report(
                    _analysis.check_param_plan(
                        self.param_plan, device_mesh, name="dmodule parameter plan"
                    ),
                    stacklevel=3,
                )

    # --------------------------------------------------------- param plans
    def param_placements(self, path: str, ndim: int) -> Tuple[Placement, ...]:
        pattern, v = _match(self.param_plan, path)
        if pattern is not None:
            self._param_matched.add(pattern)
        return normalize_placements(v, self.mesh.ndim, ndim)

    def _warn_unmatched(self, plan: Dict[str, Any], matched: set, kind: str) -> None:
        import warnings

        unmatched = [p for p in plan if p not in matched and p != r".*"]
        if unmatched and self.validate_plan:
            warnings.warn(
                f"{kind} plan patterns matched nothing: {unmatched} — "
                "typo'd FQN regexes silently leave params/activations "
                "unconstrained (reference plans are validated the same way)",
                stacklevel=3,
            )

    def _warn_unmatched_fwd_once(self) -> None:
        if self._warned_fwd or not self.fwd_plan:
            return
        self._warned_fwd = True
        # method-scoped entries ("fqn:method") often bind paths the first
        # apply never takes (e.g. decode-only attend) — exclude them
        call_plan = {p: v for p, v in self.fwd_plan.items() if ":" not in p}
        self._warn_unmatched(call_plan, self._fwd_matched, "forward")

    def _path_str(self, keypath) -> str:
        # drop the leading collection name ("params")
        return keypath_fqn(keypath[1:] if len(keypath) > 1 else keypath)

    def variables_shardings(self, abstract_variables):
        """Tree of NamedSharding for a variables pytree (params sharded per
        plan; other collections replicated)."""

        def one(keypath, leaf):
            path = self._path_str(keypath)
            coll = str(keypath[0].key) if hasattr(keypath[0], "key") else ""
            if coll != "params":
                return NamedSharding(self.mesh.jax_mesh, PartitionSpec())
            pl = self.param_placements(path, len(leaf.shape))
            return NamedSharding(self.mesh.jax_mesh, pspec_of(pl, len(leaf.shape), self.mesh))

        return jax.tree_util.tree_map_with_path(one, abstract_variables)

    def param_specs(self, variables):
        """Tree of DArraySpec for the params (used by optimizer/checkpoint)."""

        def one(keypath, leaf):
            path = self._path_str(keypath)
            pl = self.param_placements(path, len(leaf.shape))
            return DArraySpec(self.mesh, pl, TensorMeta(tuple(leaf.shape), leaf.dtype))

        return jax.tree_util.tree_map_with_path(one, variables)

    # ------------------------------------------------------------ init
    def init(self, rngs, *args, **kwargs):
        """Deferred + sharded init: trace init abstractly (eval_shape — the
        torchdistX-free deferred init), compute param shardings from the
        plan, then materialize each shard on its own devices via jit
        out_shardings (reference materialize_dtensor semantics)."""
        abstract = jax.eval_shape(lambda r: self.module.init(r, *args, **kwargs), rngs)
        shardings = self.variables_shardings(abstract)
        if self.param_plan:
            self._warn_unmatched(self.param_plan, self._param_matched, "parameter")
        init_fn = jax.jit(
            lambda r: self.module.init(r, *args, **kwargs), out_shardings=shardings
        )
        return init_fn(rngs)

    # ------------------------------------------------------------ apply
    def _match_fwd(self, fqn: str, method_name: str):
        """Fwd-plan lookup: bare ``fqn`` keys bind ``__call__`` (the
        reference hooks wrap forward); ``fqn:method`` keys bind any other
        intercepted method (e.g. ``emb:attend`` for a tied head)."""
        for pattern, v in self.fwd_plan.items():
            pat_fqn, _, pat_method = pattern.rpartition(":")
            if not pat_fqn:
                pat_fqn, pat_method = pat_method, "__call__"
            if pat_method == method_name and re.fullmatch(pat_fqn, fqn):
                self._fwd_matched.add(pattern)
                return v
        return None

    def _interceptor(self, next_fun, args, kwargs, context):
        fqn = ".".join(context.module.path)
        pi = self._match_fwd(fqn, context.method_name)
        if pi is None:
            return next_fun(*args, **kwargs)
        if pi.input is not None:
            args, kwargs = _constrain_inputs(args, kwargs, pi.input, self.mesh)
        out = next_fun(*args, **kwargs)
        if pi.output is not None:
            out = _constrain_tree(out, pi.output, self.mesh)
        return out

    def apply(self, variables, *args, **kwargs):
        with nn.intercept_methods(self._interceptor):
            out = self.module.apply(variables, *args, **kwargs)
        self._warn_unmatched_fwd_once()
        return out

    def __call__(self, variables, *args, **kwargs):
        return self.apply(variables, *args, **kwargs)


def parallelize_module(
    module: nn.Module,
    device_mesh: DeviceMesh,
    sharding_plan: Optional[Dict[str, Any]] = None,
    validate_plan: bool = True,
) -> DModule:
    """Reference dmodule/api.py:33 — wrap a module with a sharding plan.

    ``validate_plan=False`` silences the matched-nothing warnings — for
    intentionally applying a whole-model plan to one sub-module (e.g. the
    compiled pipeline parallelizes embed/block/head separately)."""
    return DModule(module, device_mesh, sharding_plan or {}, validate_plan=validate_plan)
