"""Placement types — the sharding vocabulary of vescale_tpu.

A *placement* describes how a global (logical) array relates to one mesh
dimension.  A full layout is a tuple of placements, one per mesh dim.

Capability parity with the reference (veScale):
  - ``Shard``            <- legacy/vescale/dtensor/placement_types.py:64
  - ``Replicate``        <- legacy/vescale/dtensor/placement_types.py:225
  - ``Partial``          <- legacy/vescale/dtensor/placement_types.py:249
  - ``InterleavedShard`` <- legacy/vescale/dtensor/placement_types.py:284
  - ``RaggedShard``      <- vescale/dtensor/placement_types.py:46
  - ``StridedRaggedShard``<- vescale/dtensor/placement_types.py:229

TPU-native design: placements do not perform communication themselves (the
reference's placements carry `_shard_tensor`/`_to_replicate_tensor` methods
that issue NCCL calls).  Here they are *declarative*: they lower to
``jax.sharding.PartitionSpec`` / GSPMD annotations (see ``spec.py``), and the
redistribute engine (``redistribute.py``) compiles placement transitions into
XLA collectives.  Eager helpers below only do local, device-free index math
(shard sizing, padding, offsets) used by checkpointing, RNG and tests.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

__all__ = [
    "Placement",
    "Shard",
    "Replicate",
    "Partial",
    "InterleavedShard",
    "RaggedShard",
    "StridedRaggedShard",
    "normalize_placement",
    "normalize_placements",
    "plan_axes",
    "transition_candidates",
]


class Placement:
    """Base class for placements (pure metadata, hashable, immutable)."""

    def is_shard(self, dim: Optional[int] = None) -> bool:
        is_shard = isinstance(self, Shard)
        if dim is not None and is_shard:
            return self.dim == dim  # type: ignore[attr-defined]
        return is_shard

    def is_interleaved_shard(self, dim: Optional[int] = None) -> bool:
        is_ils = isinstance(self, InterleavedShard)
        if dim is not None and is_ils:
            return self.dim == dim  # type: ignore[attr-defined]
        return is_ils

    def is_ragged_shard(self) -> bool:
        return isinstance(self, RaggedShard)

    def is_replicate(self) -> bool:
        return isinstance(self, Replicate)

    def is_partial(self) -> bool:
        return isinstance(self, Partial)


@dataclasses.dataclass(frozen=True)
class Shard(Placement):
    """Shard the tensor dim ``dim`` contiguously across a mesh dim.

    Uneven sizes follow the reference semantics (and GSPMD's): chunk sizes are
    ``ceil(size / n)`` with trailing ranks possibly holding smaller or empty
    shards; XLA pads internally.
    """

    dim: int

    def local_shard_size_and_offset(self, global_size: int, num_chunks: int, rank: int) -> Tuple[int, int]:
        """(local_size, global_offset) of ``rank``'s chunk of a dim of
        ``global_size`` split into ``num_chunks`` (ceil-division chunking,
        mirrors reference Shard._local_shard_size_on_dim)."""
        chunk = -(-global_size // num_chunks)  # ceil
        off = min(chunk * rank, global_size)
        return min(chunk, global_size - off), off

    def padded_size(self, global_size: int, num_chunks: int) -> int:
        return -(-global_size // num_chunks) * num_chunks

    def __repr__(self) -> str:
        return f"Shard(dim={self.dim})"

    def __str__(self) -> str:
        return f"S({self.dim})"


@dataclasses.dataclass(frozen=True)
class InterleavedShard(Placement):
    """Non-contiguous interleaved shard (reference placement_types.py:284).

    The tensor dim is logically split into ``interleaved_size`` contiguous
    sections; *each* section is sharded across the mesh dim.  Rank ``r`` holds
    the concatenation of the r-th chunk of every section.  Canonical use:
    merged QKV / gate-up projections where each logical sub-matrix must be
    TP-sharded independently.

    TPU lowering: reshape ``dim -> (interleaved_size, size/interleaved_size)``
    then ordinary ``Shard(dim+1)`` on the reshaped view (see spec.py); XLA
    sees a plain even shard, so no custom collectives are needed.
    """

    dim: int
    interleaved_size: int

    def __post_init__(self):
        if self.interleaved_size <= 0:
            raise ValueError("interleaved_size must be positive")

    def __repr__(self) -> str:
        return f"InterleavedShard(dim={self.dim}, interleaved_size={self.interleaved_size})"

    def __str__(self) -> str:
        return f"IS({self.dim},{self.interleaved_size})"


@dataclasses.dataclass(frozen=True)
class Replicate(Placement):
    """Replicate across the mesh dim."""

    def __repr__(self) -> str:
        return "Replicate()"

    def __str__(self) -> str:
        return "R"


@dataclasses.dataclass(frozen=True)
class Partial(Placement):
    """Pending reduction across the mesh dim (reference placement_types.py:249).

    Each participant holds a same-shaped local tensor; the logical global
    value is the elementwise reduction.  ``reduce_op`` in {"sum", "avg",
    "max", "min"}.

    TPU representation: a Partial DArray stores the unreduced operands
    *stacked* along a leading axis that is Shard-placed on the mesh dim, so
    the global jax.Array remains well-defined; ``redistribute`` lowers the
    reduction to ``psum`` / reduce-scatter (see darray.py).
    """

    reduce_op: str = "sum"

    _VALID = ("sum", "avg", "max", "min")

    def __post_init__(self):
        if self.reduce_op not in self._VALID:
            raise ValueError(f"unsupported reduce_op {self.reduce_op!r}; expected one of {self._VALID}")

    def __repr__(self) -> str:
        return f"Partial({self.reduce_op})"

    def __str__(self) -> str:
        return f"P({self.reduce_op})"


@dataclasses.dataclass(frozen=True)
class RaggedShard(Placement):
    """Asymmetric contiguous shard of a *flattened* group of dims
    (reference vescale/dtensor/placement_types.py:46, raggedshard.md).

    ``dims`` — the leading-contiguous tensor dims that are flattened before
    splitting.  ``local_units`` — one weight per mesh-dim rank; rank ``r``
    owns ``local_units[r] / sum(local_units)`` of the flattened extent.  Unit
    boundaries must divide the flattened size exactly.

    TPU lowering: the data is stored flattened over ``dims`` and padded to
    ``max(unit) * n`` so XLA sees an even ``Shard(0)``; the ragged unit map is
    carried in metadata and used for all-gather-v / all-to-all-v style
    redistributes and communication-free checkpoint chunk math.
    """

    dims: Tuple[int, ...]
    local_units: Tuple[int, ...]

    def __post_init__(self):
        object.__setattr__(self, "dims", tuple(self.dims))
        object.__setattr__(self, "local_units", tuple(int(u) for u in self.local_units))
        if len(self.dims) == 0:
            raise ValueError("RaggedShard needs at least one dim")
        if tuple(self.dims) != tuple(range(self.dims[0], self.dims[0] + len(self.dims))):
            raise ValueError(f"RaggedShard dims must be contiguous, got {self.dims}")
        if any(u < 0 for u in self.local_units) or sum(self.local_units) == 0:
            raise ValueError(f"invalid local_units {self.local_units}")

    @property
    def total_units(self) -> int:
        return sum(self.local_units)

    def unit_size(self, flat_size: int) -> int:
        if flat_size % self.total_units != 0:
            raise ValueError(f"flattened size {flat_size} not divisible by total units {self.total_units}")
        return flat_size // self.total_units

    def local_sizes_and_offsets(self, flat_size: int) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-rank (sizes, offsets) in elements of the flattened extent."""
        us = self.unit_size(flat_size)
        sizes = tuple(u * us for u in self.local_units)
        offs = tuple(int(x) for x in _exclusive_cumsum(sizes))
        return sizes, offs

    def __repr__(self) -> str:
        return f"RaggedShard(dims={self.dims}, local_units={self.local_units})"

    def __str__(self) -> str:
        return f"RS({list(self.dims)},{list(self.local_units)})"


@dataclasses.dataclass(frozen=True)
class StridedRaggedShard(RaggedShard):
    """RaggedShard composed *inside* an outer ``Shard`` of the same flat
    extent (reference vescale/dtensor/placement_types.py:229).

    ``split_factor`` = product of the outer mesh-dim sizes that shard the same
    flattened extent before this placement applies.  Rank ``r`` of this mesh
    dim owns its ragged chunk *within each* of the ``split_factor`` outer
    chunks, enabling 2-D (e.g. fsdp x ep) ragged layouts.
    """

    split_factor: int = 1

    def __post_init__(self):
        super().__post_init__()
        if self.split_factor < 1:
            raise ValueError("split_factor must be >= 1")

    def __repr__(self) -> str:
        return (
            f"StridedRaggedShard(dims={self.dims}, local_units={self.local_units}, "
            f"split_factor={self.split_factor})"
        )

    def __str__(self) -> str:
        return f"SRS({list(self.dims)},{list(self.local_units)},sf={self.split_factor})"


def _exclusive_cumsum(xs: Sequence[int]):
    out, acc = [], 0
    for x in xs:
        out.append(acc)
        acc += x
    return out


def normalize_placement(p, ndim: Optional[int] = None) -> Placement:
    """Accept shorthand: int -> Shard(int), "replicate"/"r" -> Replicate(),
    "partial" -> Partial(); negative Shard dims normalized given ndim."""
    if isinstance(p, Placement):
        if ndim is not None and isinstance(p, (Shard, InterleavedShard)) and p.dim < 0:
            return dataclasses.replace(p, dim=p.dim + ndim)
        return p
    if isinstance(p, int):
        return Shard(p if ndim is None or p >= 0 else p + ndim)
    if isinstance(p, str):
        s = p.strip().lower()
        if s in ("r", "replicate"):
            return Replicate()
        if s in ("p", "partial"):
            return Partial()
        if s.startswith("s(") and s.endswith(")"):
            return Shard(int(s[2:-1]))
    raise ValueError(f"cannot interpret placement {p!r}")


def normalize_placements(placements, mesh_ndim: int, tensor_ndim: Optional[int] = None) -> Tuple[Placement, ...]:
    """Normalize a user-facing placements argument to a full tuple of length
    ``mesh_ndim`` (missing trailing entries replicate, mirroring reference
    api semantics)."""
    if placements is None:
        return tuple(Replicate() for _ in range(mesh_ndim))
    if isinstance(placements, (Placement, int, str)):
        placements = [placements]
    out = [normalize_placement(p, tensor_ndim) for p in placements]
    if len(out) > mesh_ndim:
        raise ValueError(f"{len(out)} placements for mesh of {mesh_ndim} dims")
    out.extend(Replicate() for _ in range(mesh_ndim - len(out)))
    return tuple(out)


def transition_candidates(src_p: Placement, dst_p: Placement) -> Tuple[Placement, ...]:
    """Candidate intermediate placements for ONE mesh dim when planning a
    multi-hop redistribution (redistribute_plan.py).

    The lattice spanned per mesh dim: the two endpoint placements, the
    plain-``Shard`` relaxation of any ``InterleavedShard`` endpoint (the
    bridge for merged-QKV interleave changes that differ on several mesh
    dims at once), and ``Replicate`` — the universal bridge every primitive
    kernel can reach (gather) and leave (slice/seed).  Kept deliberately
    small: the planner's node set is the cartesian product across mesh dims,
    and 3-4 candidates per dim keep a 4-D mesh's lattice under ~300 specs.
    """
    out: list = []
    for p in (src_p, dst_p):
        if p not in out:
            out.append(p)
        if isinstance(p, InterleavedShard):
            s = Shard(p.dim)
            if s not in out:
                out.append(s)
    r = Replicate()
    if r not in out:
        out.append(r)
    return tuple(out)


def plan_axes(mesh, **dims) -> list:
    """Placements list for ``mesh`` with ``dims[name]`` at the mesh dim
    *named* ``name`` and Replicate elsewhere.

    Makes sharding plans mesh-shape-agnostic: the reference's plans are
    positional lists tied to a fixed ("dp","tp") mesh
    (legacy/examples/open_llama_4D_benchmark/sharding_plan.py); here the same
    plan composes unchanged onto ("pp","dp","tp") or 5-D meshes — names
    absent from the mesh are simply dropped (that axis stays unsharded).
    """
    out = [Replicate() for _ in range(len(mesh.mesh_dim_names))]
    for name, p in dims.items():
        if name in mesh.mesh_dim_names:
            out[mesh.mesh_dim_names.index(name)] = normalize_placement(p)
        elif len(mesh.mesh_dim_names) > 1:
            import warnings

            warnings.warn(
                f"plan_axes: mesh {mesh.mesh_dim_names} has no dim named {name!r}; "
                "that axis stays unsharded (replicated)",
                stacklevel=2,
            )
    return out
