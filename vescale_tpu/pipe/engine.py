"""PipeEngine — executes pipeline schedules.

Capability parity with the reference PipeEngine + ScheduleEngine
(legacy/vescale/engine/pipe.py:33, pipe/pipe_emmiter.py:43,132): minibatch ->
microbatch split, instruction execution, loss aggregation across the last
stage, shared-param grad sync, zero-bubble W/B split.

TPU-native semantics: this is the *eager* (schedule-exact) engine — each
instruction runs as a JAX op batch, activations/cotangents flow through a
table (the SEND/RECV of the reference's p2p layer are device-to-device
transfers XLA performs on placement; a shape handshake is unnecessary since
shapes are static at trace time).  The compiled whole-pipeline path lives in
spmd.py.

Backward decomposition: FORWARD records a ``jax.vjp`` pullback per (group,
microbatch) for fused-backward schedules.  For zero-bubble schedules FORWARD
records a ``jax.linearize`` instead, and the backward is split for real
(reference zero_bubble_v.py:132 ScheduledNode B/W):
BACKWARD_DGRAD transposes the linearized map w.r.t. the *input only*
(``jax.linear_transpose`` with the params tangent pinned to zero) — the
weight-grad matmuls do NOT run; BACKWARD_WGRAD later transposes w.r.t. the
*params only*, actually computing the deferred weight grads in the bubble
slots.  Both transposes share the single linearization's residuals, so the
forward runs once."""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..plan import PipelineParallelPlan
from ..telemetry import memtrack as _memtrack
from .pipe_stage import PipeModule
from .schedules import Instruction, InstructionKind, build_schedule

__all__ = ["PipeEngine", "PendingWgrad"]


def _zero_tangent(x):
    """Zero tangent for a primal (float0 for integer leaves, e.g. tokens)."""
    import numpy as np

    dt = jnp.result_type(x)
    if jnp.issubdtype(dt, jnp.inexact):
        return jnp.zeros(jnp.shape(x), dt)
    return np.zeros(jnp.shape(x), jax.dtypes.float0)


@dataclasses.dataclass
class PendingWgrad:
    """A deferred weight-grad: everything needed to compute dparams later.

    Holding (f_lin, dy) rather than a computed dparams is the observable
    difference from a fake split — the wgrad matmuls run when
    BACKWARD_WGRAD executes, not at dgrad time."""

    f_lin: Callable        # linearized (dp, dx) -> dy_out map (shares residuals)
    dy: Any                # output cotangent for this (group, microbatch)
    params_example: Any    # primal params (structure + zeros for the transpose)
    x_example: Any         # primal input

    def compute(self):
        zero_x = jax.tree_util.tree_map(_zero_tangent, self.x_example)
        wgrad_t = jax.linear_transpose(
            lambda pp: self.f_lin(pp, zero_x), self.params_example
        )
        (dparams,) = wgrad_t(self.dy)
        return dparams


class PipeEngine:
    """Schedule-exact EAGER pipeline executor — the semantics/profiling
    engine, NOT the hardware perf path.

    Single-controller by construction: activations and cotangents flow
    through Python tables on the driving process, so it cannot scale
    multi-host and pays per-instruction dispatch.  On hardware, run real
    training through the COMPILED pipeline (``pipe/spmd.py``
    ``pipeline_blocks`` / ``pipeline_blocks_zb`` — one XLA program, ppermute
    over ICI, multi-host capable).  Use this engine for schedule studies,
    instruction-level parity tests, and ``profile_costs`` feeding the
    cost-graph scheduler.  A multi-process run refuses to start (see
    ``forward_backward``) rather than silently not scaling."""

    def __init__(
        self,
        module: PipeModule,
        plan: PipelineParallelPlan,
        loss_fn: Callable,
        device_mesh=None,
    ):
        self.module = module
        self.plan = plan
        self.loss_fn = loss_fn  # loss_fn(last_stage_output, target_microbatch)
        self.mesh = device_mesh
        # optional (instruction, seconds) callback; when set, each
        # instruction's produced value is block_until_ready'd so the wall
        # time is the instruction's own (profiling mode — see profile_costs)
        self.on_instruction: Optional[Callable] = None

    # ----------------------------------------------------------- helpers
    def _split_microbatches(self, batch, num_microbatches: int):
        def split(x):
            if x.shape[0] % num_microbatches != 0:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by {num_microbatches} microbatches"
                )
            return jnp.split(x, num_microbatches, axis=0)

        leaves, treedef = jax.tree_util.tree_flatten(batch)
        split_leaves = [split(l) for l in leaves]
        return [
            jax.tree_util.tree_unflatten(treedef, [sl[m] for sl in split_leaves])
            for m in range(num_microbatches)
        ]

    def _check_stage_boundaries(self, micro) -> None:
        """One-time static audit of the plan's declared cross-stage
        activation layouts (PipelineParallelPlan.stage_out/in_placements)
        through analysis/shardcheck: a boundary whose resharding would hit
        the materializing fallback raises (strict) or warns (warn mode)
        BEFORE the first microbatch runs.  The p2p tensor shape comes from
        the plan (``p2p_tensor_shapes``) when declared, else the first
        microbatch leaf."""
        if getattr(self, "_boundaries_checked", False):
            return
        self._boundaries_checked = True
        plan = self.plan
        if self.mesh is None or getattr(plan, "stage_out_placements", None) is None:
            return
        from .. import analysis

        if not analysis.enabled():
            return
        shapes = plan.p2p_tensor_shapes
        if shapes:
            shape = shapes[0] if isinstance(shapes[0], (tuple, list)) else shapes
        else:
            leaves = jax.tree_util.tree_leaves(micro[0]) if micro else []
            if not leaves:
                return
            shape = leaves[0].shape
        analysis.dispatch_report(
            plan.boundary_report(self.mesh, tuple(shape)), stacklevel=4
        )

    # ------------------------------------------------------------- main
    def forward_backward(
        self,
        params_per_group: List[Dict[str, Any]],
        minibatch: Dict[str, Any],
        num_microbatches: Optional[int] = None,
        forward_only: bool = False,
    ):
        """Run the configured schedule over the minibatch.

        Returns (mean_loss, grads_per_group) — grads aligned with
        ``params_per_group`` and shared-group grads already synced
        (reference engine/pipe.py:138 forward_backward).  In
        ``forward_only`` mode returns (mean_loss_or_None, last_stage_outputs)
        and 'target' may be omitted from the minibatch."""
        if jax.process_count() > 1:
            raise RuntimeError(
                "PipeEngine is the single-controller EAGER semantics engine "
                "(activations flow through Python tables on this process); "
                "a multi-process run would silently not scale.  Use the "
                "compiled pipeline — pipe/spmd.py pipeline_blocks / "
                "pipeline_blocks_zb — for multi-host training."
            )
        M = num_microbatches or 1
        G = self.module.num_groups
        micro = self._split_microbatches(
            {k: v for k, v in minibatch.items() if k != "target"}, M
        )
        self._check_stage_boundaries(micro)
        has_target = "target" in minibatch
        if not has_target and not forward_only:
            raise ValueError("training forward_backward requires a 'target' in the minibatch")
        targets = (
            self._split_microbatches({"target": minibatch["target"]}, M) if has_target else None
        )
        schedule = build_schedule(self.plan, M)
        if forward_only:
            schedule = [
                [i for i in stage_ins if i.kind == InstructionKind.FORWARD]
                for stage_ins in schedule
            ]

        # split-backward (zero-bubble) schedules linearize at FORWARD time so
        # dgrad/wgrad can be transposed independently later
        uses_split = any(
            i.kind == InstructionKind.BACKWARD_DGRAD for stage_ins in schedule for i in stage_ins
        )

        acts: Dict[Tuple[int, int], Any] = {}       # (g, m) -> output
        pullbacks: Dict[Tuple[int, int], Any] = {}
        linears: Dict[Tuple[int, int], Any] = {}     # (g, m) -> (f_lin, params, x)
        cotangents: Dict[Tuple[int, int], Any] = {}  # (g, m) -> dy for group g
        wgrad_stash: Dict[Tuple[int, int], PendingWgrad] = {}
        losses: Dict[int, Any] = {}
        outputs: Dict[int, Any] = {}  # forward-only: last-group outputs per microbatch
        grads: List[Optional[Dict[str, Any]]] = [None] * G

        def ready(ins: Instruction) -> bool:
            g = self.module.group_index(ins.stage, ins.chunk)
            m = ins.microbatch
            if ins.kind == InstructionKind.FORWARD:
                return g == 0 or (g - 1, m) in acts
            if ins.kind in (InstructionKind.BACKWARD, InstructionKind.BACKWARD_DGRAD):
                if (g, m) not in pullbacks and (g, m) not in linears:
                    return False
                return g == G - 1 or (g, m) in cotangents
            if ins.kind == InstructionKind.BACKWARD_WGRAD:
                return (g, m) in wgrad_stash
            return False

        def run(ins: Instruction):
            """Execute one instruction; returns EVERYTHING it produced
            (for profiling-mode block_until_ready timing — blocking a
            subset would let sibling outputs bleed into the next timer)."""
            g = self.module.group_index(ins.stage, ins.chunk)
            m = ins.microbatch
            if ins.kind == InstructionKind.FORWARD:
                # the producing entry is consumed exactly once: evict so peak
                # memory under 1F1B stays O(stages), not O(stages*microbatches)
                x = micro[m]["input"] if g == 0 else acts.pop((g - 1, m))
                fwd = self.module.group_forward(g)
                if forward_only:
                    # no linearization / residuals in inference mode
                    if g == G - 1:
                        y = fwd(params_per_group[g], x)
                        outputs[m] = y
                        if targets is not None:
                            losses[m] = self.loss_fn(y, targets[m]["target"])
                        acts[(g, m)] = _memtrack.tag_tree(y, "activation_stash")
                        return (y, losses.get(m))
                    acts[(g, m)] = _memtrack.tag_tree(
                        fwd(params_per_group[g], x), "activation_stash"
                    )
                    return acts[(g, m)]
                if g == G - 1:
                    def f(p, xx):
                        return self.loss_fn(fwd(p, xx), targets[m]["target"])
                else:
                    f = fwd
                p = params_per_group[g]
                if uses_split:
                    y, f_lin = jax.linearize(f, p, x)
                    linears[(g, m)] = (f_lin, p, x)
                else:
                    y, pb = jax.vjp(f, p, x)
                    pullbacks[(g, m)] = pb
                # the stash IS the 1F1B memory cost — owner-tag it so an OOM
                # census shows how many microbatches were in flight
                acts[(g, m)] = _memtrack.tag_tree(y, "activation_stash")
                if g == G - 1:
                    losses[m] = y
                return y
            elif ins.kind == InstructionKind.BACKWARD:
                pb = pullbacks.pop((g, m))
                dy = (
                    jnp.asarray(1.0 / M, dtype=losses[m].dtype)
                    if g == G - 1
                    else cotangents.pop((g, m))
                )
                dparams, dx = pb(dy)
                if g > 0:
                    cotangents[(g - 1, m)] = dx
                _accumulate(grads, g, dparams)
                return (dparams, dx, grads[g])
            elif ins.kind == InstructionKind.BACKWARD_DGRAD:
                f_lin, p, x = linears.pop((g, m))
                dy = (
                    jnp.asarray(1.0 / M, dtype=losses[m].dtype)
                    if g == G - 1
                    else cotangents.pop((g, m))
                )
                dx = None
                if g > 0:
                    # input-grad only: transpose the linear map in its x slot
                    # (params tangent pinned to zero — no weight-grad matmuls)
                    zero_p = jax.tree_util.tree_map(_zero_tangent, p)
                    dgrad_t = jax.linear_transpose(lambda xx: f_lin(zero_p, xx), x)
                    (dx,) = dgrad_t(dy)
                    cotangents[(g - 1, m)] = dx
                # deferred-wgrad residual held into the bubble slots — part
                # of the activation stash for attribution purposes
                wgrad_stash[(g, m)] = PendingWgrad(
                    f_lin, _memtrack.tag_tree(dy, "activation_stash"), p, x
                )
                return (dx, dy)
            elif ins.kind == InstructionKind.BACKWARD_WGRAD:
                dp = wgrad_stash.pop((g, m)).compute()
                _accumulate(grads, g, dp)
                return (dp, grads[g])
            return None

        # round-robin clock over stages, dependency-driven (the reference's
        # per-rank executors run concurrently; single-controller execution
        # needs only the dependency order)
        from .. import telemetry as _tel
        from ..ndtimeline import predefined as _metrics
        from ..ndtimeline.api import is_active

        _nd_active = is_active()  # snapshot: dormant profiler costs nothing
        _tel_active = _tel.is_active()  # same gate for the metrics registry
        _t_sched0 = time.perf_counter() if _tel_active else 0.0
        _metric_of = {
            InstructionKind.FORWARD: _metrics.FORWARD_COMPUTE,
            InstructionKind.BACKWARD: _metrics.BACKWARD_COMPUTE,
            InstructionKind.BACKWARD_DGRAD: _metrics.BACKWARD_COMPUTE,
            InstructionKind.BACKWARD_WGRAD: _metrics.WGRAD_COMPUTE,
        }
        timer = self.on_instruction
        queues = [list(s) for s in schedule]
        pos = [0] * len(queues)
        try:
            self._run_schedule(queues, pos, ready, run, timer, _nd_active,
                               _tel_active, _metric_of)
        except BaseException as e:
            # OOM forensics: the stash tables above are exactly what an
            # OOM census needs to attribute — dump before unwinding them
            _memtrack.maybe_dump_oom(e)
            raise

        if _tel_active:
            # un-blocked instructions are async dispatches, so the honest
            # whole-schedule signal is the pass duration + instruction count
            _tel.count("pipe_forward_backward_total")
            _tel.count("pipe_instructions_total", sum(len(q) for q in queues))
            _tel.set_gauge("pipe_num_microbatches", M)
            _tel.observe(
                "pipe_forward_backward_seconds", time.perf_counter() - _t_sched0
            )
        mean_loss = sum(losses.values()) / M if losses else None
        if forward_only:
            outs = (
                jnp.concatenate([outputs[m] for m in range(M)], axis=0) if outputs else None
            )
            return mean_loss, outs
        grads = self.module.sync_shared_params_grads([g if g is not None else {} for g in grads])
        return mean_loss, _memtrack.tag_tree(grads, "grads")

    def _run_schedule(self, queues, pos, ready, run, timer, _nd_active,
                      _tel_active, _metric_of):
        """Dependency-driven round-robin clock over the stage queues."""
        import contextlib

        from .. import telemetry as _tel
        from ..ndtimeline.api import ndtimeit

        while any(p < len(q) for p, q in zip(pos, queues)):
            progressed = False
            for s, q in enumerate(queues):
                if pos[s] < len(q) and ready(q[pos[s]]):
                    ins = q[pos[s]]
                    # auto-instrumentation (reference predefined.py spans
                    # around the pipe runtime): every instruction emits an
                    # ndtimeline span tagged (stage, chunk, microbatch) when
                    # the profiler is initialized.  NOTE host-side region:
                    # it brackets dispatch (async) unless profiling mode
                    # blocks below.
                    span = (
                        ndtimeit(
                            _metric_of.get(ins.kind, str(ins.kind)),
                            tags={
                                "stage": ins.stage,
                                "chunk": ins.chunk,
                                "microbatch": ins.microbatch,
                                "dgrad": ins.kind == InstructionKind.BACKWARD_DGRAD,
                                # VERDICT item 9: un-blocked spans bracket
                                # async DISPATCH, not device execution — the
                                # tag rides into the chrome-trace args so a
                                # near-zero "compute" lane is self-explaining
                                "timing": "host-dispatch" if timer is None else "blocked",
                            },
                        )
                        if _nd_active
                        else contextlib.nullcontext()
                    )
                    if timer is None:
                        with span:
                            run(ins)
                    else:
                        # every profiled instruction is blocked, so the device
                        # queue is empty at start: wall time == own duration
                        t0 = time.perf_counter()
                        with span:
                            jax.block_until_ready(run(ins))
                        dt = time.perf_counter() - t0
                        if _tel_active:
                            # blocked instructions give true per-kind device
                            # latency — the profiling-mode histogram feed
                            _tel.observe(
                                f"pipe_instr_{ins.kind.name.lower()}_seconds", dt
                            )
                        timer(ins, dt)
                    pos[s] += 1
                    progressed = True
            if not progressed:
                stuck = [q[p] for p, q in zip(pos, queues) if p < len(q)]
                raise RuntimeError(f"pipeline schedule deadlock; waiting on {stuck[:8]}")

    def forward_only(self, params_per_group, minibatch, num_microbatches=None):
        return self.forward_backward(
            params_per_group, minibatch, num_microbatches, forward_only=True
        )

    def _timed_pass(self, params_per_group, minibatch, num_microbatches, warmup: int):
        """One wall-timed schedule pass (after ``warmup`` untimed passes);
        returns {(kind, stage): [durations]}."""
        times: Dict[Tuple[Any, int], List[float]] = {}

        def cb(ins, dt):
            times.setdefault((ins.kind, ins.stage), []).append(dt)

        old = self.on_instruction
        self.on_instruction = cb
        try:
            for _ in range(warmup):
                self.forward_backward(params_per_group, minibatch, num_microbatches)
            times.clear()  # keep only the post-warmup (compile-cached) pass
            self.forward_backward(params_per_group, minibatch, num_microbatches)
        finally:
            self.on_instruction = old
        return times

    def profile_costs(self, params_per_group, minibatch, num_microbatches=None,
                      warmup: int = 1, comm: float = 0.0,
                      calibrate_host_overhead: bool = False):
        """Measured per-stage instruction durations -> ``StageCosts`` (the
        reference CostGraph's *profiled* inputs, zero_bubble_v.py:198).

        Runs ``warmup + 1`` passes of the configured schedule with each
        instruction block_until_ready'd and wall-timed; the last pass's
        median duration per (kind, stage) becomes the cost.  Fused BACKWARD
        timings split evenly into bd/w.  V=1 only (cost schedules model one
        chunk per stage).

        ``calibrate_host_overhead``: each eager instruction pays a
        per-call host cost (jax.linearize / vjp re-trace, dict bookkeeping)
        that is roughly SIZE-INDEPENDENT, while the device work scales with
        the microbatch — so raw wall times flatten the stage ratios the
        scheduler cares about (ADVICE r2).  Calibration re-profiles on a
        sequence-decimated copy of the minibatch and subtracts the
        per-(kind, stage) medians: what remains is the size-scaling
        (device) component.  Costs are clamped at a tenth of the raw
        measurement so a noisy calibration can never zero a stage out."""
        from .schedules import StageCosts

        if self.module.num_groups != self.plan.num_stages:
            raise ValueError("profile_costs needs one group per stage (V=1)")
        S = self.plan.num_stages
        times = self._timed_pass(params_per_group, minibatch, num_microbatches, warmup)

        base: Dict[Tuple[Any, int], List[float]] = {}
        if calibrate_host_overhead:
            tiny = {
                k: (v[:, :8] if hasattr(v, "ndim") and v.ndim >= 2 and v.shape[1] > 8 else v)
                for k, v in minibatch.items()
            }
            base = self._timed_pass(params_per_group, tiny, num_microbatches, warmup)

        def med(table, kind, s, default=0.0):
            v = table.get((kind, s))
            return statistics.median(v) if v else default

        def cost(kind, s):
            raw = med(times, kind, s)
            if not calibrate_host_overhead:
                return raw
            return max(raw - med(base, kind, s), raw * 0.1)

        F, B = InstructionKind.FORWARD, InstructionKind.BACKWARD
        Bd, W = InstructionKind.BACKWARD_DGRAD, InstructionKind.BACKWARD_WGRAD
        f = tuple(cost(F, s) for s in range(S))
        if any((Bd, s) in times for s in range(S)):
            bd = tuple(cost(Bd, s) for s in range(S))
            w = tuple(cost(W, s) for s in range(S))
        else:  # fused-backward schedule: split the measurement evenly
            bd = tuple(cost(B, s) / 2.0 for s in range(S))
            w = bd
        return StageCosts(f=f, bd=bd, w=w, comm=comm)

    __call__ = forward_backward


def _accumulate(grads: List, g: int, dparams) -> None:
    if grads[g] is None:
        grads[g] = dparams
    else:
        grads[g] = jax.tree_util.tree_map(jnp.add, grads[g], dparams)
