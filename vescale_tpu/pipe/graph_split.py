"""Graph-level automatic pipeline splitting.

Capability parity with the reference's fx-based PipeParser
(legacy/vescale/pipe/pipe_parser.py:46, tracer.py:81,93): split an
*arbitrary* model — not just one already structured as a list of blocks —
into balanced pipeline stages.

TPU-native mechanism: where the reference traces ``nn.Module``s into a
torch.fx graph and partitions the node list, here the model function is
traced into a **jaxpr** (``jax.make_jaxpr``), its topologically-ordered
equation list is cut into contiguous ranges balanced by a FLOP cost model
(dot_general/conv dominate, matching the reference's param-count balancing
but measuring compute directly), and each range is replayed by a small
jaxpr interpreter.  Values produced before a cut and consumed after it
become the carried activation tuple — residual streams, tied embeddings and
multi-tensor carries all fall out of the dataflow instead of needing the
reference's send/recv shape handshake.

``GraphPipeModule`` exposes the same surface as ``PipeModule``
(``group_forward`` / ``group_index`` / ``sync_shared_params_grads``), so the
eager ``PipeEngine`` and every schedule (1F1B, interleaved, zero-bubble)
run unmodified on auto-split graphs.

The traced function must be deterministic (no rng argument): trace-time
splitting sees one static graph, same as the reference tracer.  Stages are
shape-specialized (XLA static shapes), so ``x_example`` must be shaped like
one *microbatch* when the module is driven by ``PipeEngine`` — where the
reference's fx modules stay shape-polymorphic, the TPU analog re-traces per
shape, and the engine always feeds microbatches of one shape.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
try:
    from jax.core import Literal
except ImportError:  # pragma: no cover - older/newer jax layouts
    from jax._src.core import Literal

from ..plan import PipelineParallelPlan
from .pipe_stage import _cuts_by_weight

__all__ = ["GraphPipeModule", "split_graph", "jaxpr_flops"]


# ------------------------------------------------------------- cost model
def _eqn_flops(eqn) -> float:
    """FLOP estimate for one equation.  dot_general gets exact MAC math;
    conv gets the dense im2col equivalent; everything else counts output
    elements (so long elementwise chains still carry a little weight)."""
    if eqn.primitive.name == "dot_general":
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        batch = 1
        for d in lb:
            batch *= lhs.shape[d]
        k = 1
        for d in lc:
            k *= lhs.shape[d]
        m = 1
        for i, s in enumerate(lhs.shape):
            if i not in lc and i not in lb:
                m *= s
        n = 1
        for i, s in enumerate(rhs.shape):
            if i not in rc and i not in rb:
                n *= s
        return 2.0 * batch * m * n * k
    if eqn.primitive.name.startswith("conv"):
        out = eqn.outvars[0].aval
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        k = 1
        for s in rhs.shape[1:]:
            k *= s
        return 2.0 * out.size * k
    total = 0.0
    for ov in eqn.outvars:
        total += getattr(ov.aval, "size", 0)
    return total


def jaxpr_flops(jaxpr) -> float:
    """Total FLOPs of a (closed) jaxpr under the same cost model, recursing
    into call/sub-jaxprs (pjit, remat, custom_vjp, scan, cond branches)."""
    j = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0.0
    for eqn in j.eqns:
        subs = []
        for v in eqn.params.values():
            if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
                subs.append(v)
            elif isinstance(v, (tuple, list)):
                subs.extend(x for x in v if hasattr(x, "eqns") or hasattr(x, "jaxpr"))
        if subs:
            mult = eqn.params.get("length", 1) if eqn.primitive.name == "scan" else 1
            total += mult * sum(jaxpr_flops(s) for s in subs)
        else:
            total += _eqn_flops(eqn)
    return total


def _eqn_invars(eqn):
    return [v for v in eqn.invars if not isinstance(v, Literal)]


def _run_eqns(eqns, env: Dict[Any, Any]) -> None:
    """Interpret a contiguous eqn range in-place over ``env`` (the standard
    eval_jaxpr loop, scoped to a sub-range)."""
    for eqn in eqns:
        invals = [v.val if isinstance(v, Literal) else env[v] for v in eqn.invars]
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        ans = eqn.primitive.bind(*subfuns, *invals, **bind_params)
        if eqn.primitive.multiple_results:
            for ov, a in zip(eqn.outvars, ans):
                env[ov] = a
        else:
            env[eqn.outvars[0]] = ans


class GraphPipeModule:
    """Pipeline groups cut from a traced jaxpr (see module docstring).

    ``params_per_group = module.partition_params(params)`` gives each group
    the param leaves its equations consume (tied params are placed in every
    consuming group and registered as a shared group, mirroring
    ``PipeModule.shared_groups``); ``group_forward(g)`` returns the pure
    ``(group_params, carry) -> carry`` replay function.
    """

    def __init__(self, fn: Callable, params_example, x_example, plan: PipelineParallelPlan):
        self.plan = plan
        self.num_stages = plan.num_stages
        self.virtual_chunks = max(1, plan.virtual_chunks)
        n = self.num_stages * self.virtual_chunks

        closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(params_example, x_example)
        jaxpr = closed.jaxpr
        self._consts = dict(zip(jaxpr.constvars, closed.consts))
        self._out_tree = jax.tree_util.tree_structure(out_shape)
        self._outvars = list(jaxpr.outvars)

        # invars = flattened (params, x); recover the param-leaf names
        p_paths = [
            ".".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp)
            for kp, _ in jax.tree_util.tree_flatten_with_path(params_example)[0]
        ]
        self._params_treedef = jax.tree_util.tree_structure(params_example)
        n_p = len(p_paths)
        self._param_vars = list(jaxpr.invars[:n_p])
        self._param_names = p_paths
        self._x_vars = list(jaxpr.invars[n_p:])
        self._x_treedef = jax.tree_util.tree_structure(x_example)

        eqns = list(jaxpr.eqns)
        if n > max(1, len(eqns)):
            raise ValueError(f"{n} pipeline groups for a graph of {len(eqns)} equations")
        cuts = _cuts_by_weight([_eqn_flops(e) for e in eqns], n)
        self._bounds = [0] + list(cuts) + [len(eqns)]
        self._eqns = eqns

        # dataflow at each boundary: defs before the cut, uses at/after it
        var_of_param = dict(zip(self._param_vars, self._param_names))
        var_of_name = dict(zip(self._param_names, self._param_vars))
        self._carry_vars: List[List[Any]] = []  # carry INTO group g (g>=1)
        self._group_params: List[List[Tuple[str, Any]]] = []
        use_after: List[set] = [set() for _ in range(n + 1)]
        live = set(v for v in self._outvars if not isinstance(v, Literal))
        for g in range(n, 0, -1):
            lo, hi = self._bounds[g - 1], self._bounds[g]
            use_after[g - 1] = set(live)
            for eqn in eqns[lo:hi]:
                live |= set(_eqn_invars(eqn))
            live -= set(v for e in eqns[lo:hi] for v in e.outvars)
        for g in range(n):
            lo, hi = self._bounds[g], self._bounds[g + 1]
            used = set(v for e in eqns[lo:hi] for v in _eqn_invars(e))
            pnames = sorted({var_of_param[v] for v in used if v in var_of_param})
            self._group_params.append([(nm, var_of_name[nm]) for nm in pnames])
            if g > 0:
                # carry = non-param, non-const values defined earlier and
                # still needed by this group or any later one (incl. outputs)
                need = use_after[g] | used
                carry = [
                    v
                    for v in self._iter_defs_before(lo)
                    if v in need and v not in var_of_param and v not in self._consts
                ]
                self._carry_vars.append(carry)

        # param leaves no equation consumes (config-disabled branches, extra
        # checkpoint heads): park them in group 0 so partition/merge stays a
        # lossless round-trip; vjp gives them zero grads there
        assigned = {nm for plist in self._group_params for nm, _ in plist}
        for nm, var in zip(self._param_names, self._param_vars):
            if nm not in assigned:
                self._group_params[0].append((nm, var))

        # shared (tied) params: used by >1 group
        counts: Dict[str, List[int]] = {}
        for g, plist in enumerate(self._group_params):
            for nm, _ in plist:
                counts.setdefault(nm, []).append(g)
        self.shared_groups: Dict[str, List[Tuple[int, str]]] = {
            nm: [(g, nm) for g in gs] for nm, gs in counts.items() if len(gs) > 1
        }

    # ------------------------------------------------------------ helpers
    def _iter_defs_before(self, lo: int):
        for v in self._x_vars:
            yield v
        for eqn in self._eqns[:lo]:
            for v in eqn.outvars:
                yield v

    @property
    def num_groups(self) -> int:
        return len(self._group_params)

    def group_index(self, stage: int, chunk: int = 0) -> int:
        return chunk * self.num_stages + stage

    def stage_of_group(self, g: int) -> Tuple[int, int]:
        return g % self.num_stages, g // self.num_stages

    def group_param_names(self, g: int) -> List[str]:
        return [nm for nm, _ in self._group_params[g]]

    # ------------------------------------------------------------- params
    def partition_params(self, params) -> List[Dict[str, Any]]:
        """Split a full params tree into per-group {name: leaf} dicts (tied
        leaves are copied into every consuming group)."""
        leaves = jax.tree_util.tree_leaves(params)
        by_name = dict(zip(self._param_names, leaves))
        return [{nm: by_name[nm] for nm, _ in plist} for plist in self._group_params]

    def merge_params(self, params_per_group) -> Any:
        """Inverse of partition_params (shared leaves: first group wins)."""
        by_name: Dict[str, Any] = {}
        for d in reversed(params_per_group):
            by_name.update(d)
        return jax.tree_util.tree_unflatten(
            self._params_treedef, [by_name[nm] for nm in self._param_names]
        )

    # ------------------------------------------------------------ forward
    def group_forward(self, g: int) -> Callable:
        lo, hi = self._bounds[g], self._bounds[g + 1]
        eqns = self._eqns[lo:hi]
        plist = self._group_params[g]
        last = g == self.num_groups - 1
        carry_in = self._carry_vars[g - 1] if g > 0 else None
        carry_out = self._carry_vars[g] if not last else None

        def bind(env, var, val):
            if tuple(getattr(val, "shape", ())) != tuple(var.aval.shape):
                raise ValueError(
                    f"graph pipeline stages are shape-specialized (XLA static "
                    f"shapes): got {getattr(val, 'shape', None)} for traced "
                    f"{var.aval.shape}.  Trace split_graph with a "
                    f"microbatch-sized x_example."
                )
            env[var] = val

        def fwd(group_params, x):
            env = dict(self._consts)
            for nm, var in plist:
                env[var] = group_params[nm]
            if g == 0:
                for var, leaf in zip(self._x_vars, jax.tree_util.tree_leaves(x)):
                    bind(env, var, leaf)
            else:
                for var, val in zip(carry_in, x):
                    bind(env, var, val)
            _run_eqns(eqns, env)
            if last:
                outs = [v.val if isinstance(v, Literal) else env[v] for v in self._outvars]
                return jax.tree_util.tree_unflatten(self._out_tree, outs)
            return tuple(env[v] for v in carry_out)

        return fwd

    def stage_forward(self, stage: int, chunk: int = 0) -> Callable:
        return self.group_forward(self.group_index(stage, chunk))

    def full_forward(self, params, x):
        """Chain every group (debug / parity checking)."""
        pg = self.partition_params(params)
        y = x
        for g in range(self.num_groups):
            y = self.group_forward(g)(pg[g], y)
        return y

    # ------------------------------------------------------------- shared
    def sync_shared_params_grads(self, grads_per_group):
        """Sum tied-param grads across their groups (PipeModule parity)."""
        for nm, members in self.shared_groups.items():
            total = None
            for g, _ in members:
                gr = grads_per_group[g].get(nm)
                if gr is None:
                    continue
                total = gr if total is None else jax.tree_util.tree_map(jnp.add, total, gr)
            for g, _ in members:
                if nm in grads_per_group[g]:
                    grads_per_group[g][nm] = total
        return grads_per_group


def split_graph(
    fn: Callable,
    params_example,
    x_example,
    plan: PipelineParallelPlan,
) -> GraphPipeModule:
    """Trace ``fn(params, x)`` and cut it into ``num_stages * virtual_chunks``
    FLOP-balanced pipeline groups (reference pipe_parser.py:46 parse +
    construct_pipeline_stage flow, in one step)."""
    return GraphPipeModule(fn, params_example, x_example, plan)
