from .pipe_stage import PipeModule, construct_pipeline_stage
from .schedules import (
    Instruction,
    InstructionKind,
    gpipe_schedule,
    one_f_one_b_schedule,
    interleaved_1f1b_schedule,
    zero_bubble_schedule,
    build_schedule,
)
from .engine import PipeEngine
