from .pipe_stage import PipeModule, construct_pipeline_stage
from .schedules import (
    Instruction,
    InstructionKind,
    StageCosts,
    gpipe_schedule,
    one_f_one_b_schedule,
    interleaved_1f1b_schedule,
    zero_bubble_schedule,
    zero_bubble_cost_schedule,
    simulate_schedule,
    estimate_stage_costs,
    build_schedule,
)
from .engine import PipeEngine
from .graph_split import GraphPipeModule, split_graph


def build_pipe_module(plan, *, units=None, fn=None, params_example=None, x_example=None):
    """Construct a pipeline module per ``plan.tracer_type`` (the reference's
    PipeParser.parse dispatch, pipe_parser.py:60): MODULE_PATH splits an
    explicit ``units`` list; JAXPR (and the torch tracer aliases) auto-splits
    the traced ``fn(params, x)`` graph."""
    from ..plan import TracerType

    if plan.tracer_type == TracerType.MODULE_PATH:
        if units is None:
            raise ValueError("MODULE_PATH tracer needs `units`")
        return construct_pipeline_stage(units, plan, x_example)
    if fn is None or params_example is None or x_example is None:
        raise ValueError(f"{plan.tracer_type} tracer needs fn, params_example and x_example")
    return split_graph(fn, params_example, x_example, plan)
