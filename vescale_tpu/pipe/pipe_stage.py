"""Pipeline stage construction.

Capability parity with the reference PipeParser/PipeModule
(legacy/vescale/pipe/pipe_parser.py:46, pipe_stage.py:64,285,311):
  - split a model into stages (uniform / manual split points / by-params)
  - virtual chunks for interleaved schedules (looping_bfs.py)
  - shared-module groups (tied embeddings) synced across stages
  - per-stage param partitions

TPU-native: there is no fx graph to trace — a JAX model is already a
function.  Stage splitting is *module-path splitting* over a sequence of
stage units (SURVEY §7.6: "the GRAPH_EAGER fx-tracing mode translates to
simple module-path splitting since JAX has no fx").  A stage unit is any
flax module; the canonical decomposition for decoder LMs is
[embed, block_0..block_{L-1}, head].

With ``virtual_chunks`` V > 1 the units are split into S*V *groups*; group
``g`` runs as model-chunk ``g // S`` on physical stage ``g % S`` (Megatron
VPP assignment).  A microbatch traverses groups in order g = 0..S*V-1.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from ..plan import PipelineParallelPlan, PipelineSplitMethodType

__all__ = ["PipeModule", "construct_pipeline_stage", "StageUnit"]


@dataclasses.dataclass
class StageUnit:
    """One indivisible unit (reference smallest_unsplittable_units)."""

    name: str
    module: nn.Module
    shared_group: Optional[str] = None  # e.g. "embeddings" for tied wte


class PipeModule:
    """Holds per-group unit lists + param partitions + shared groups
    (reference pipe_stage.py:64)."""

    def __init__(self, groups: List[List[StageUnit]], plan: PipelineParallelPlan):
        self.groups = groups
        self.plan = plan
        self.num_stages = plan.num_stages
        self.virtual_chunks = max(1, len(groups) // plan.num_stages)
        if len(groups) != self.num_stages * self.virtual_chunks:
            raise ValueError(
                f"{len(groups)} groups != num_stages {self.num_stages} x virtual chunks"
            )
        # shared groups: name -> [(group_idx, unit_name), ...]
        self.shared_groups: Dict[str, List[Tuple[int, str]]] = {}
        for g, units in enumerate(groups):
            for u in units:
                if u.shared_group:
                    self.shared_groups.setdefault(u.shared_group, []).append((g, u.name))

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    def group_index(self, stage: int, chunk: int = 0) -> int:
        return chunk * self.num_stages + stage

    def stage_of_group(self, g: int) -> Tuple[int, int]:
        """(physical stage, chunk) of group g."""
        return g % self.num_stages, g // self.num_stages

    # ------------------------------------------------------------- init
    def init_all(self, rng, x_example):
        """Init every group in model order, propagating activation shapes and
        sharing tied params (reference deferred pipeline init +
        build_shared_module_group, pipe_stage.py:311).  Returns per-group
        params list."""
        shared: Dict[str, Any] = {}
        all_params = []
        x = x_example
        for g in range(self.num_groups):
            params = {}
            for u in self.groups[g]:
                if u.shared_group and u.shared_group in shared:
                    p = shared[u.shared_group]
                else:
                    rng, sub = jax.random.split(rng)
                    p = u.module.init(sub, x)["params"]
                    if u.shared_group:
                        shared[u.shared_group] = p
                params[u.name] = p
                out = jax.eval_shape(lambda pp, xx: u.module.apply({"params": pp}, xx), p, x)
                x = jnp.zeros(out.shape, out.dtype)
            all_params.append(params)
        return all_params

    # ---------------------------------------------------------- forward
    def group_forward(self, g: int) -> Callable:
        """Pure fn (group_params, x) -> y running group g's units."""
        units = self.groups[g]

        def fwd(params, x):
            for u in units:
                x = u.module.apply({"params": params[u.name]}, x)
            return x

        return fwd

    def stage_forward(self, stage: int, chunk: int = 0) -> Callable:
        return self.group_forward(self.group_index(stage, chunk))

    def sync_shared_params_grads(self, grads_per_group):
        """Sum grads of tied params across their groups (reference
        engine/pipe.py:211 sync_shared_params)."""
        for name, members in self.shared_groups.items():
            if len(members) < 2:
                continue
            total = None
            for g, uname in members:
                gr = grads_per_group[g].get(uname)
                if gr is None:
                    continue
                total = gr if total is None else jax.tree_util.tree_map(jnp.add, total, gr)
            for g, uname in members:
                if uname in grads_per_group[g]:
                    grads_per_group[g][uname] = total
        return grads_per_group


def _cuts_by_weight(weights: List[float], n: int) -> List[int]:
    """Contiguous partition of unit weights into n groups balancing totals
    (same greedy as the reference's params/uniform split)."""
    total = sum(weights)
    target = total / n
    cuts = []
    acc = 0.0
    for k, w in enumerate(weights):
        if len(cuts) < n - 1 and acc >= target * (len(cuts) + 1):
            cuts.append(k)
        acc += w
    while len(cuts) < n - 1:
        cuts.append(len(weights))
    # repair pass: cuts must be strictly increasing with >= 1 unit per group
    # (weight concentrated at the end can otherwise produce empty groups)
    for i in range(n - 1):
        lo = (cuts[i - 1] if i > 0 else 0) + 1
        hi = len(weights) - (n - 1 - i)
        cuts[i] = min(max(cuts[i], lo), hi)
    return cuts


def construct_pipeline_stage(
    units: Sequence[StageUnit],
    plan: PipelineParallelPlan,
    x_example=None,
) -> PipeModule:
    """Split an ordered list of stage units into ``num_stages * virtual_chunks``
    groups (reference construct_pipeline_stage, pipe_stage.py:285).

    - MANUAL: ``plan.split_points`` lists the unit *names that end* each group
      but the last (num_stages*virtual_chunks - 1 names).
    - UNIFORM: balance by unit count.
    - PARAMETERS: balance by param count (needs x_example).
    """
    units = list(units)
    n = plan.num_stages * max(1, plan.virtual_chunks)
    if n > len(units):
        raise ValueError(f"{n} groups for {len(units)} units")

    if plan.split_method == PipelineSplitMethodType.MANUAL:
        if not plan.split_points or len(plan.split_points) != n - 1:
            raise ValueError(f"MANUAL split needs {n - 1} split_points")
        names = [u.name for u in units]
        cuts = []
        for sp in plan.split_points:
            if sp not in names:
                raise ValueError(f"split point {sp!r} not among units {names}")
            cuts.append(names.index(sp) + 1)
        if cuts != sorted(cuts):
            raise ValueError("split_points must be in model order")
    elif plan.split_method == PipelineSplitMethodType.PARAMETERS:
        if x_example is None:
            raise ValueError("PARAMETERS split needs x_example")
        weights = []
        x = x_example
        rng = jax.random.key(0)
        for u in units:
            vars_ = jax.eval_shape(lambda r, xx: u.module.init(r, xx), rng, x)
            w = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(vars_))
            weights.append(float(w))
            out = jax.eval_shape(
                lambda v, xx: u.module.apply({"params": v["params"]}, xx), vars_, x
            )
            x = jnp.zeros(out.shape, out.dtype)
        cuts = _cuts_by_weight(weights, n)
    else:  # UNIFORM
        per = len(units) / n
        cuts = [int(round(per * (i + 1))) for i in range(n - 1)]

    bounds = [0] + list(cuts) + [len(units)]
    groups = [units[bounds[i]:bounds[i + 1]] for i in range(n)]
    if any(len(g) == 0 for g in groups):
        raise ValueError(f"empty pipeline group in split {bounds}")
    return PipeModule(groups, plan)
