"""Compiled SPMD pipeline — the TPU perf path.

Where the reference drives PP with per-rank executors + NCCL send/recv
(legacy/vescale/pipe/p2p_communication.py), the TPU-native path compiles the
WHOLE pipeline into one XLA program: stage params are stacked on a ``pp``
mesh axis, microbatches stream through a ``lax.scan`` whose steady state
rotates activations with ``lax.ppermute`` over ICI.  Reverse-mode AD
transposes the ppermute (reverse rotation), so ``jax.grad`` of this function
IS the backward pipeline — 1F1B emerges from XLA's scheduler rather than an
instruction VM.  (Pattern from public JAX pipelining recipes; see the
scaling-book's pipelining chapter.)

Requirements: homogeneous stages (same block params structure per stage) —
the canonical transformer middle.  Embedding/head run outside, replicated or
dp/tp-sharded.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..mesh import DeviceMesh
from ..collectives import shard_map

__all__ = ["pipeline_blocks", "stack_stage_params", "shard_stacked_params"]


def stack_stage_params(params_list):
    """Stack per-stage param trees (same structure) along a new leading axis
    -> leaves (S, ...)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *params_list)


def shard_stacked_params(
    stacked,
    mesh: DeviceMesh,
    param_plan,
    pp_dim: str = "pp",
    fqn_prefix: str = "",
):
    """Place pp-stacked per-stage block params by a DModule param plan.

    Each leaf is (S, *block_shape): the stage axis is Shard-placed on
    ``pp_dim`` and the block dims follow the plan's placements for
    ``fqn_prefix + leaf_path`` (the same FQN-regex plans
    ``parallelize_module`` consumes — reference dmodule/_dmodule.py:217
    _distribute_parameter, applied to the compiled-pipeline layout).
    Returns the tree with leaves ``jax.device_put`` onto the mesh.
    """
    from jax.sharding import NamedSharding

    from ..dmodule.api import DModule, keypath_fqn, pspec_of
    from ..placements import Replicate

    dm = DModule(None, mesh, {"parameter": param_plan})
    pp_index = mesh._dim_index(pp_dim)

    def one(keypath, leaf):
        path = keypath_fqn(keypath)
        placements = list(dm.param_placements(fqn_prefix + path, leaf.ndim - 1))
        placements[pp_index] = Replicate()  # pp is the stage axis, not a block dim
        block_spec = pspec_of(placements, leaf.ndim - 1, mesh)
        spec = P(pp_dim, *block_spec)
        return jax.device_put(leaf, NamedSharding(mesh.jax_mesh, spec))

    return jax.tree_util.tree_map_with_path(one, stacked)


def pipeline_blocks(
    block_fn: Callable,
    stacked_params,
    x,
    mesh: DeviceMesh,
    pp_dim: str = "pp",
    num_microbatches: Optional[int] = None,
    extra_specs: Optional[P] = None,
):
    """Apply ``num_stages`` sequential stages (one per pp-mesh rank) to ``x``,
    pipelined over microbatches.

    ``block_fn(stage_params, x_micro) -> y_micro`` must preserve the
    activation shape.  ``stacked_params`` leaves are (S, ...), sharded on
    ``pp``.  ``x``: (B, ...) with B divisible by num_microbatches.
    Returns (B, ...) outputs (as if stages were applied sequentially).
    """
    S = mesh.size(pp_dim)
    M = num_microbatches or S
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    xm = x.reshape(M, B // M, *x.shape[1:])

    act_spec = extra_specs if extra_specs is not None else P()

    def worker(params, xm_local):
        # params leaves: (1, ...) local slice -> squeeze stage axis
        params = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), params)
        idx = jax.lax.axis_index(pp_dim)
        perm = [(i, (i + 1) % S) for i in range(S)]
        micro = xm_local  # (M, b, ...)
        outs0 = jnp.zeros_like(micro)
        act0 = jnp.zeros_like(micro[0])

        def body(carry, t):
            act, outs = carry
            x_in = jnp.where(
                idx == 0,
                jax.lax.dynamic_index_in_dim(micro, jnp.minimum(t, M - 1), 0, keepdims=False),
                act,
            )
            y = block_fn(params, x_in)
            out_t = t - (S - 1)
            collect = (idx == S - 1) & (out_t >= 0)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(
                    collect,
                    y,
                    jax.lax.dynamic_index_in_dim(outs, jnp.maximum(out_t, 0), 0, keepdims=False),
                ),
                jnp.maximum(out_t, 0),
                0,
            )
            act_next = jax.lax.ppermute(y, pp_dim, perm)
            return (act_next, outs), None

        (_, outs), _ = jax.lax.scan(body, (act0, outs0), jnp.arange(M + S - 1))
        # only the last stage holds real outputs; psum broadcasts them
        # (zeros elsewhere) so downstream (head/loss) sees the full tensor
        outs = jnp.where(idx == S - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, pp_dim)

    out = shard_map(
        worker,
        mesh=mesh.jax_mesh,
        in_specs=(P(pp_dim), act_spec),
        out_specs=act_spec,
        check_vma=False,
        # only pp is manual — dp/tp/sp remain auto so GSPMD shards the
        # per-stage compute (4D composition: PP x DP x TP x SP)
        axis_names=frozenset({pp_dim}) if mesh.ndim > 1 else frozenset(mesh.mesh_dim_names),
    )(stacked_params, xm)
    return out.reshape(B, *x.shape[1:])
