"""Compiled SPMD pipeline — the TPU perf path.

Where the reference drives PP with per-rank executors + NCCL send/recv
(legacy/vescale/pipe/p2p_communication.py), the TPU-native path compiles the
WHOLE pipeline into one XLA program: stage params are stacked on a ``pp``
mesh axis, microbatches stream through a ``lax.scan`` whose steady state
rotates activations with ``lax.ppermute`` over ICI.  Reverse-mode AD
transposes the ppermute (reverse rotation), so ``jax.grad`` of this function
IS the backward pipeline — 1F1B emerges from XLA's scheduler rather than an
instruction VM.  (Pattern from public JAX pipelining recipes; see the
scaling-book's pipelining chapter.)

Schedules on the compiled path (reference _schedules/):
  - 1F1B-equivalent     ``pipeline_blocks``            <- pipedream_flush.py
  - Interleaved/VPP     ``pipeline_blocks(virtual_chunks=V)`` <- looping_bfs.py:699,873
    (each physical stage hosts V model chunks; microbatches re-enter stage 0
    after stage S-1, Megatron wave ordering, waves of S microbatches)
  - Zero-bubble         ``pipeline_blocks_zb``         <- zero_bubble_v.py:132,198,602
    (custom backward: phase 1 propagates ONLY input cotangents — the
    critical path; phase 2 computes every deferred weight grad afterwards,
    so wgrad work sits behind all dgrads in program order and XLA's
    scheduler is free to slot it into bubbles — the role of the reference's
    CostGraph, done by the compiler)

Requirements: homogeneous stages (same block params structure per stage) —
the canonical transformer middle.  Embedding/head run outside, replicated or
dp/tp-sharded.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..mesh import DeviceMesh
from ..collectives import shard_map

__all__ = [
    "pipeline_blocks",
    "pipeline_blocks_zb",
    "stack_stage_params",
    "stack_interleaved_params",
    "shard_stacked_params",
]


def stack_stage_params(params_list):
    """Stack per-stage param trees (same structure) along a new leading axis
    -> leaves (S, ...)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *params_list)


def stack_interleaved_params(groups_params, num_stages: int):
    """Stack ``S*V`` per-group param trees (PipeModule group order: group
    ``g`` = chunk ``g // S`` on stage ``g % S``) into leaves (S*V, ...)
    ordered *stage-major* (index = stage*V + chunk) so that ``Shard`` on the
    pp mesh dim gives each stage its V contiguous chunks."""
    n = len(groups_params)
    if n % num_stages:
        raise ValueError(f"{n} groups not divisible by {num_stages} stages")
    V = n // num_stages
    reordered = [groups_params[v * num_stages + s] for s in range(num_stages) for v in range(V)]
    return stack_stage_params(reordered)


def shard_stacked_params(
    stacked,
    mesh: DeviceMesh,
    param_plan,
    pp_dim: str = "pp",
    fqn_prefix: str = "",
):
    """Place pp-stacked per-stage block params by a DModule param plan.

    Each leaf is (S, *block_shape) — or the flat stage-major (S*V,
    *block_shape) from ``stack_interleaved_params`` — the leading axis is
    Shard-placed on
    ``pp_dim`` and the block dims follow the plan's placements for
    ``fqn_prefix + leaf_path`` (the same FQN-regex plans
    ``parallelize_module`` consumes — reference dmodule/_dmodule.py:217
    _distribute_parameter, applied to the compiled-pipeline layout).
    Returns the tree with leaves ``jax.device_put`` onto the mesh.
    """
    from jax.sharding import NamedSharding

    from ..dmodule.api import DModule, keypath_fqn, pspec_of
    from ..placements import Replicate

    dm = DModule(None, mesh, {"parameter": param_plan})
    pp_index = mesh._dim_index(pp_dim)

    def one(keypath, leaf):
        path = keypath_fqn(keypath)
        placements = list(dm.param_placements(fqn_prefix + path, leaf.ndim - 1))
        placements[pp_index] = Replicate()  # pp is the stage axis, not a block dim
        block_spec = pspec_of(placements, leaf.ndim - 1, mesh)
        spec = P(pp_dim, *block_spec)
        return jax.device_put(leaf, NamedSharding(mesh.jax_mesh, spec))

    return jax.tree_util.tree_map_with_path(one, stacked)


# ------------------------------------------------------------ schedule math
def _vpp_slot(t, idx, S: int, V: int, M: int):
    """Decode the (microbatch, chunk) occupying stage ``idx`` at step ``t``.

    Megatron wave ordering (looping_bfs.py): microbatch ``m`` enters stage 0
    chunk 0 at ``t = (m // S) * S*V + m % S``; each step the activation
    rotates one stage forward, re-entering stage 0 for the next chunk after
    stage S-1.  Position ``p = v*S + idx`` gives the unique decomposition
    below.  Returns (m, v, active, inject, collect) — all traced scalars.
    """
    u = t - idx
    w = u // (S * V)
    q = u - w * (S * V)
    v = q // S
    j = q - v * S
    m = w * S + j
    active = (u >= 0) & (m < M)
    inject = active & (v == 0) & (idx == 0)
    collect = active & (v == V - 1) & (idx == S - 1)
    return m, v, active, inject, collect


def _vpp_total_steps(S: int, V: int, M: int) -> int:
    return ((M - 1) // S) * S * V + ((M - 1) % S) + S * V


def _index_chunk(params, v, V: int):
    """Select chunk ``v``'s param slice from local (V, ...) leaves."""
    if V == 1:
        return jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), params)
    vc = jnp.clip(v, 0, V - 1)
    return jax.tree_util.tree_map(
        lambda p: jax.lax.dynamic_index_in_dim(p, vc, 0, keepdims=False), params
    )


def _prepare(x, mesh, pp_dim, num_microbatches, virtual_chunks, extra_specs, stacked_params):
    S = mesh.size(pp_dim)
    M = num_microbatches or S
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if leaves and leaves[0].shape[0] != S * virtual_chunks:
        raise ValueError(
            f"stacked_params leading axis {leaves[0].shape[0]} != num_stages {S} "
            f"* virtual_chunks {virtual_chunks} (use stack_stage_params / "
            "stack_interleaved_params)"
        )
    xm = x.reshape(M, B // M, *x.shape[1:])
    act_spec = extra_specs if extra_specs is not None else P()
    manual = frozenset({pp_dim}) if mesh.ndim > 1 else frozenset(mesh.mesh_dim_names)
    return S, M, B, xm, act_spec, manual


def _constrain_auto(z, auto_act_spec: Optional[P], lead: int = 0):
    """Pin an activation buffer to ``auto_act_spec`` on the AUTO axes
    (legal inside the pp-manual shard_map: dp/tp/... stay GSPMD-managed).
    A bare PartitionSpec resolves against the CONTEXT mesh, whose axis
    types are (Manual, Auto, ...) here — a NamedSharding built from the
    concrete mesh would carry all-Auto types and trip the context-mesh
    check when sharding propagates (zeros_like etc.).

    jax < 0.5 compat: without the abstract-mesh context machinery
    (``jax.sharding.get_abstract_mesh``) a bare-PartitionSpec constraint
    inside shard_map has no mesh to resolve against and raises — there the
    pin degrades to a no-op (it is a memory-LAYOUT knob, never a semantics
    change: the parity test asserts identical values either way; GSPMD
    still places the buffers, just without the explicit hint)."""
    if auto_act_spec is None:
        return z
    if getattr(jax.sharding, "get_abstract_mesh", None) is None:
        return z
    spec = P(*((None,) * lead + tuple(auto_act_spec)))
    return jax.lax.with_sharding_constraint(z, spec)


# ------------------------------------------------------------- 1F1B / VPP
def pipeline_blocks(
    block_fn: Callable,
    stacked_params,
    x,
    mesh: DeviceMesh,
    pp_dim: str = "pp",
    num_microbatches: Optional[int] = None,
    extra_specs: Optional[P] = None,
    virtual_chunks: int = 1,
    auto_act_spec: Optional[P] = None,
):
    """Apply ``S * virtual_chunks`` sequential model chunks (V per pp-mesh
    rank, Megatron interleaved assignment) to ``x``, pipelined over
    microbatches.

    ``block_fn(chunk_params, x_micro) -> y_micro`` must preserve the
    activation shape.  ``stacked_params`` leaves are (S, ...) for V=1
    (``stack_stage_params``) or (S*V, ...) stage-major
    (``stack_interleaved_params``), sharded on ``pp``.  ``x``: (B, ...) with
    B divisible by num_microbatches.  Returns (B, ...) outputs (as if the
    chunks were applied sequentially).

    ``auto_act_spec``: PartitionSpec over the AUTO (non-pp) mesh axes for
    one microbatch activation ``(b, *features)`` — e.g. ``P("dp", "tp")``
    for the Megatron-SP layout (batch over dp, sequence over tp).  Without
    it GSPMD chooses; with it the microbatch stash, the rotating carry, the
    outs buffer, and every scan-saved boundary (the backward stash) are
    pinned to that sharding — at 405B scale the difference between a 68 GB
    and a 1 GB per-device activation footprint.
    """
    S, M, B, xm, act_spec, manual = _prepare(
        x, mesh, pp_dim, num_microbatches, virtual_chunks, extra_specs, stacked_params
    )
    V = virtual_chunks
    T = _vpp_total_steps(S, V, M)

    def constrain(z, lead: int = 0):
        return _constrain_auto(z, auto_act_spec, lead)

    def worker(params, xm_local):
        # leaves (V, ...): the local stage's chunks
        idx = jax.lax.axis_index(pp_dim)
        perm = [(i, (i + 1) % S) for i in range(S)]
        micro = constrain(xm_local, lead=1)  # (M, b, ...)
        outs0 = jnp.zeros_like(micro)
        act0 = constrain(jnp.zeros_like(micro[0]))

        def body(carry, t):
            act, outs = carry
            m, v, active, inject, collect = _vpp_slot(t, idx, S, V, M)
            mc = jnp.clip(m, 0, M - 1)
            x_in = jnp.where(
                inject, jax.lax.dynamic_index_in_dim(micro, mc, 0, keepdims=False), act
            )
            y = constrain(block_fn(_index_chunk(params, v, V), x_in))
            outs = jax.lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(
                    collect,
                    y,
                    jax.lax.dynamic_index_in_dim(outs, mc, 0, keepdims=False),
                ),
                mc,
                0,
            )
            act_next = jax.lax.ppermute(y, pp_dim, perm)
            return (act_next, outs), None

        (_, outs), _ = jax.lax.scan(body, (act0, outs0), jnp.arange(T))
        # only the LAST stage's buffer holds real outputs; return it as a
        # pp-sharded stage axis — downstream slicing moves one copy instead
        # of the old zeros+psum all-reduce of the full activation
        return outs[None]

    out = shard_map(
        worker,
        mesh=mesh.jax_mesh,
        in_specs=(P(pp_dim), act_spec),
        out_specs=P(pp_dim, *tuple(act_spec)),
        check_vma=False,
        # only pp is manual — dp/tp/sp remain auto so GSPMD shards the
        # per-stage compute (4D composition: PP x DP x TP x SP)
        axis_names=manual,
    )(stacked_params, xm)
    return out[S - 1].reshape(B, *x.shape[1:])


# ------------------------------------------------------------- zero bubble
def pipeline_blocks_zb(
    block_fn: Callable,
    stacked_params,
    x,
    mesh: DeviceMesh,
    pp_dim: str = "pp",
    num_microbatches: Optional[int] = None,
    extra_specs: Optional[P] = None,
    virtual_chunks: int = 1,
    auto_act_spec: Optional[P] = None,
):
    """``pipeline_blocks`` with a REAL zero-bubble backward
    (reference zero_bubble_v.py: B/W split).

    ``auto_act_spec`` pins the microbatch stash AND the per-step
    input/cotangent stashes (``xins``/``dys`` — ZB's dominant activation
    memory, T steps x microbatch each) to the given auto-axis layout, the
    same 405B-scale memory knob as ``pipeline_blocks``.

    Forward is the same rotating scan (inputs stashed per step).  The custom
    backward runs two phases:

      1. **dgrad scan** (reverse): re-linearizes each step's block
         (rematerialization) and transposes w.r.t. the *input only* —
         cotangents rotate backwards over ICI with no weight-grad matmuls on
         the critical path.  The per-step output cotangents are stashed.
      2. **wgrad scan**: computes every deferred weight grad from the
         stashed (input, cotangent) pairs and accumulates into the param
         grads.  In program order all W work follows all B work, giving
         XLA's latency-hiding scheduler the whole bubble budget to fill —
         the compiled analog of the reference's CostGraph scheduling.

    Cost: one extra block forward per phase (remat), the standard TPU
    trade of HBM for FLOPs.
    """
    S, M, B, xm, act_spec, manual = _prepare(
        x, mesh, pp_dim, num_microbatches, virtual_chunks, extra_specs, stacked_params
    )
    V = virtual_chunks
    T = _vpp_total_steps(S, V, M)

    def worker(params, xm_local):
        perm = [(i, (i + 1) % S) for i in range(S)]
        perm_rev = [(i, (i - 1) % S) for i in range(S)]
        micro = _constrain_auto(xm_local, auto_act_spec, lead=1)

        @jax.custom_vjp
        def pipe(params, micro):
            outs, _ = _fwd(params, micro)
            return outs

        def _fwd(params, micro):
            # axis_index is taken inside each phase: a value captured from
            # the enclosing worker trace would leak into the custom_vjp
            idx = jax.lax.axis_index(pp_dim)
            outs0 = jnp.zeros_like(micro)
            act0 = _constrain_auto(jnp.zeros_like(micro[0]), auto_act_spec)
            xin0 = _constrain_auto(
                jnp.zeros((T, *micro.shape[1:]), micro.dtype), auto_act_spec, lead=1
            )

            def body(carry, t):
                act, outs, xins = carry
                m, v, active, inject, collect = _vpp_slot(t, idx, S, V, M)
                mc = jnp.clip(m, 0, M - 1)
                x_in = jnp.where(
                    inject, jax.lax.dynamic_index_in_dim(micro, mc, 0, keepdims=False), act
                )
                xins = jax.lax.dynamic_update_index_in_dim(xins, x_in, t, 0)
                y = _constrain_auto(
                    block_fn(_index_chunk(params, v, V), x_in), auto_act_spec
                )
                outs = jax.lax.dynamic_update_index_in_dim(
                    outs,
                    jnp.where(
                        collect,
                        y,
                        jax.lax.dynamic_index_in_dim(outs, mc, 0, keepdims=False),
                    ),
                    mc,
                    0,
                )
                act_next = jax.lax.ppermute(y, pp_dim, perm)
                return (act_next, outs, xins), None

            (_, outs, xins), _ = jax.lax.scan(
                body, (act0, outs0, xin0), jnp.arange(T)
            )
            return outs, xins

        def pipe_fwd(params, micro):
            outs, xins = _fwd(params, micro)
            return outs, (params, micro, xins)

        def pipe_bwd(res, d_outs):
            params, micro, xins = res
            idx = jax.lax.axis_index(pp_dim)

            # ---- phase 1: dgrad-only reverse scan (the critical path) ----
            def bwd_body(carry, t):
                dact, dmicro, dys = carry
                m, v, active, inject, collect = _vpp_slot(t, idx, S, V, M)
                mc = jnp.clip(m, 0, M - 1)
                x_in = jax.lax.dynamic_index_in_dim(xins, t, 0, keepdims=False)
                p_v = _index_chunk(params, v, V)
                # cotangent of this step's output: what flowed back from the
                # next stage, plus the direct output cotangent if collected
                dy = dact + jnp.where(
                    collect,
                    jax.lax.dynamic_index_in_dim(d_outs, mc, 0, keepdims=False),
                    jnp.zeros_like(dact),
                )
                dy = jnp.where(active, dy, jnp.zeros_like(dy))
                dys = jax.lax.dynamic_update_index_in_dim(dys, dy, t, 0)
                _, f_lin = jax.linearize(lambda xx: block_fn(p_v, xx), x_in)
                (dx,) = jax.linear_transpose(f_lin, x_in)(dy)
                # injected steps terminate at the microbatch input
                dmicro = jax.lax.dynamic_update_index_in_dim(
                    dmicro,
                    jnp.where(
                        inject,
                        dx,
                        jax.lax.dynamic_index_in_dim(dmicro, mc, 0, keepdims=False),
                    ),
                    mc,
                    0,
                )
                dx = jnp.where(inject, jnp.zeros_like(dx), dx)
                dact_next = jax.lax.ppermute(dx, pp_dim, perm_rev)
                return (dact_next, dmicro, dys), None

            dact0 = _constrain_auto(jnp.zeros_like(micro[0]), auto_act_spec)
            dmicro0 = jnp.zeros_like(micro)
            dys0 = _constrain_auto(
                jnp.zeros((T, *micro.shape[1:]), micro.dtype), auto_act_spec, lead=1
            )
            (_, dmicro, dys), _ = jax.lax.scan(
                bwd_body, (dact0, dmicro0, dys0), jnp.arange(T - 1, -1, -1)
            )

            # ---- phase 2: deferred wgrads (fill the bubbles) ----
            def w_body(dparams, t):
                m, v, active, _, _ = _vpp_slot(t, idx, S, V, M)
                x_in = jax.lax.dynamic_index_in_dim(xins, t, 0, keepdims=False)
                dy = jax.lax.dynamic_index_in_dim(dys, t, 0, keepdims=False)
                p_v = _index_chunk(params, v, V)
                _, f_lin = jax.linearize(lambda pp: block_fn(pp, x_in), p_v)
                (dp,) = jax.linear_transpose(f_lin, p_v)(dy)
                vc = jnp.clip(v, 0, V - 1)

                def add_chunk(acc, d):
                    if V == 1:
                        return acc + d[None]
                    cur = jax.lax.dynamic_index_in_dim(acc, vc, 0, keepdims=False)
                    return jax.lax.dynamic_update_index_in_dim(acc, cur + d, vc, 0)

                return jax.tree_util.tree_map(add_chunk, dparams, dp), None

            dparams0 = jax.tree_util.tree_map(jnp.zeros_like, params)
            dparams, _ = jax.lax.scan(w_body, dparams0, jnp.arange(T))
            return dparams, dmicro

        pipe.defvjp(pipe_fwd, pipe_bwd)
        outs = pipe(params, micro)
        return outs[None]

    out = shard_map(
        worker,
        mesh=mesh.jax_mesh,
        in_specs=(P(pp_dim), act_spec),
        out_specs=P(pp_dim, *tuple(act_spec)),
        check_vma=False,
        axis_names=manual,
    )(stacked_params, xm)
    return out[S - 1].reshape(B, *x.shape[1:])
