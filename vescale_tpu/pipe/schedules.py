"""Pipeline schedules — instruction IR + generators.

Capability parity with the reference instruction VM
(legacy/vescale/pipe/_schedules/):
  - instruction base/registry      <- instruction_base.py:58
  - 1F1B (pipedream flush)         <- pipedream_flush.py:653,762
  - GPipe                          <- pipedream_flush.py (forward_backward_no_pipelining variants)
  - Interleaved 1F1B (VPP)         <- looping_bfs.py:699,873
  - Zero-bubble (split B into dgrad/wgrad)  <- zero_bubble_v.py:132,198,602

The IR is deliberately tiny: SEND/RECV pairs are implicit in the
single-controller engine (activations flow through a table; on hardware the
transfer is an XLA transfer/ppermute — see spmd.py for the compiled path),
so instructions carry only compute semantics + ordering.  Zero-bubble's
W/B split is first-class: B (dgrad) propagates the activation gradient,
W (wgrad) accumulates the weight gradient later, filling bubbles.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
from typing import List, Optional, Sequence, Tuple, Union

from ..plan import PipelineParallelPlan, PipelineScheduleType

__all__ = [
    "InstructionKind",
    "Instruction",
    "StageCosts",
    "gpipe_schedule",
    "one_f_one_b_schedule",
    "interleaved_1f1b_schedule",
    "zero_bubble_schedule",
    "zero_bubble_cost_schedule",
    "simulate_schedule",
    "estimate_stage_costs",
    "build_schedule",
]


class InstructionKind(enum.Enum):
    FORWARD = "F"
    BACKWARD = "B"        # full backward (dgrad + wgrad fused)
    BACKWARD_DGRAD = "Bd"  # zero-bubble: input grad only
    BACKWARD_WGRAD = "W"   # zero-bubble: weight grad accumulation


@dataclasses.dataclass(frozen=True)
class Instruction:
    kind: InstructionKind
    stage: int          # physical pipeline stage
    microbatch: int
    chunk: int = 0      # virtual/model chunk (interleaved schedules)

    def __repr__(self):
        c = f"c{self.chunk}" if self.chunk else ""
        return f"{self.kind.value}{c}(s{self.stage},m{self.microbatch})"


def gpipe_schedule(num_stages: int, num_microbatches: int) -> List[List[Instruction]]:
    """All forwards, then all backwards (reference GPIPE mode)."""
    out = []
    for s in range(num_stages):
        ins = [Instruction(InstructionKind.FORWARD, s, m) for m in range(num_microbatches)]
        ins += [
            Instruction(InstructionKind.BACKWARD, s, m)
            for m in reversed(range(num_microbatches))
        ]
        out.append(ins)
    return out


def one_f_one_b_schedule(num_stages: int, num_microbatches: int) -> List[List[Instruction]]:
    """PipeDream-flush 1F1B (reference pipedream_flush.py:762): per stage,
    ``num_stages - s - 1`` warmup forwards, then 1F1B steady state, then
    cooldown backwards."""
    F, B = InstructionKind.FORWARD, InstructionKind.BACKWARD
    out = []
    for s in range(num_stages):
        warmup = min(num_stages - s - 1, num_microbatches)
        remaining = num_microbatches - warmup
        ins = [Instruction(F, s, m) for m in range(warmup)]
        fwd_m, bwd_m = warmup, 0
        for _ in range(remaining):
            ins.append(Instruction(F, s, fwd_m))
            fwd_m += 1
            ins.append(Instruction(B, s, bwd_m))
            bwd_m += 1
        while bwd_m < num_microbatches:
            ins.append(Instruction(B, s, bwd_m))
            bwd_m += 1
        out.append(ins)
    return out


def interleaved_1f1b_schedule(
    num_stages: int, num_microbatches: int, virtual_chunks: int
) -> List[List[Instruction]]:
    """Interleaved 1F1B / VPP (reference looping_bfs.py:873).  Each physical
    stage hosts ``virtual_chunks`` model chunks; microbatches cycle chunks in
    groups of ``num_stages`` (Megatron ordering).

    The generated order is dependency-consistent for the eager engine; exact
    bubble timing is the compiled path's concern.

    Requires ``num_microbatches % num_stages == 0`` (Megatron's own
    constraint): a partial tail wave makes the wave-cycled order
    dependency-INFEASIBLE — stage 0 would issue the tail microbatch's next
    chunk before its previous chunk cleared the pipeline, deadlocking the
    engine.  (The compiled ``pipe.spmd.pipeline_blocks`` path decodes slots
    per step and has no such restriction.)"""
    F, B = InstructionKind.FORWARD, InstructionKind.BACKWARD
    M, S, V = num_microbatches, num_stages, virtual_chunks
    if virtual_chunks > 1 and M % S != 0:
        raise ValueError(
            f"interleaved 1F1B needs num_microbatches ({M}) divisible by "
            f"num_stages ({S}) — a partial tail wave deadlocks the schedule "
            "(Megatron imposes the same constraint)"
        )
    out = []
    total = M * V
    for s in range(S):
        # forward order: chunks in waves of min(S, M) microbatches
        fwd_order = []
        group = min(S, M)
        m0 = 0
        while len(fwd_order) < total:
            for v in range(V):
                for m in range(m0, min(m0 + group, M)):
                    fwd_order.append((m, v))
            m0 += group
        bwd_order = []
        m0 = 0
        while len(bwd_order) < total:
            for v in reversed(range(V)):
                for m in range(m0, min(m0 + group, M)):
                    bwd_order.append((m, v))
            m0 += group
        warmup = min((S - s - 1) * 2 + (V - 1) * S, total)
        ins = [Instruction(F, s, m, v) for m, v in fwd_order[:warmup]]
        fi, bi = warmup, 0
        while fi < total or bi < total:
            if fi < total:
                m, v = fwd_order[fi]
                ins.append(Instruction(F, s, m, v))
                fi += 1
            if bi < total:
                m, v = bwd_order[bi]
                ins.append(Instruction(B, s, m, v))
                bi += 1
        out.append(ins)
    return out


def zero_bubble_schedule(num_stages: int, num_microbatches: int) -> List[List[Instruction]]:
    """Zero-bubble (ZB-H1-style, reference zero_bubble_v.py): 1F1B skeleton
    with backward split into Bd (dgrad, on the critical path) and W (wgrad,
    deferred to fill bubbles / drained at the end)."""
    F, Bd, W = InstructionKind.FORWARD, InstructionKind.BACKWARD_DGRAD, InstructionKind.BACKWARD_WGRAD
    out = []
    for s in range(num_stages):
        warmup = min(num_stages - s - 1, num_microbatches)
        remaining = num_microbatches - warmup
        ins = [Instruction(F, s, m) for m in range(warmup)]
        fwd_m, bwd_m, w_m = warmup, 0, 0
        for _ in range(remaining):
            ins.append(Instruction(F, s, fwd_m))
            fwd_m += 1
            ins.append(Instruction(Bd, s, bwd_m))
            bwd_m += 1
            # defer W by num_stages-s-1 microbatches to fill the bubble
            if bwd_m - w_m > max(0, num_stages - s - 1):
                ins.append(Instruction(W, s, w_m))
                w_m += 1
        while bwd_m < num_microbatches:
            ins.append(Instruction(Bd, s, bwd_m))
            bwd_m += 1
            if w_m < bwd_m - 1:
                ins.append(Instruction(W, s, w_m))
                w_m += 1
        while w_m < num_microbatches:
            ins.append(Instruction(W, s, w_m))
            w_m += 1
        out.append(ins)
    return out


# --------------------------------------------------- cost-graph scheduling
@dataclasses.dataclass(frozen=True)
class StageCosts:
    """Per-stage instruction costs driving cost-aware schedule generation —
    the role of the reference's profiled CostGraph (zero_bubble_v.py:198:
    per-ScheduledNode F/B/W durations + comm edges).

    ``f``/``bd``/``w``: cost of one FORWARD / BACKWARD_DGRAD /
    BACKWARD_WGRAD per stage (len ``num_stages``); a fused BACKWARD costs
    ``bd + w``.  ``comm``: activation/cotangent hop cost between adjacent
    stages (the reference's p2p edge weight; on TPU an ICI transfer)."""

    f: Tuple[float, ...]
    bd: Tuple[float, ...]
    w: Tuple[float, ...]
    comm: float = 0.0

    def __post_init__(self):
        # frozen dataclass doubles as the schedule-cache key: coerce
        # sequence fields so list-built instances stay hashable, and comm
        # so np scalars hash/compare like the equal python float
        for name in ("f", "bd", "w"):
            object.__setattr__(self, name, tuple(float(x) for x in getattr(self, name)))
        object.__setattr__(self, "comm", float(self.comm))

    @staticmethod
    def uniform(num_stages: int, f: float = 1.0, bd: float = 1.0,
                w: float = 1.0, comm: float = 0.0) -> "StageCosts":
        return StageCosts((f,) * num_stages, (bd,) * num_stages, (w,) * num_stages, comm)

    @staticmethod
    def from_weights(weights: Sequence[float], comm: float = 0.0) -> "StageCosts":
        """Costs proportional to per-stage work (e.g. param or FLOP counts):
        dgrad and wgrad each cost about one forward (2 matmuls vs 1 per
        linear map — the standard 1:1:1 F:Bd:W ratio the ZB paper assumes)."""
        t = tuple(float(x) for x in weights)
        return StageCosts(t, t, t, comm)

    def of(self, ins: Instruction) -> float:
        k = ins.kind
        if k == InstructionKind.FORWARD:
            return self.f[ins.stage]
        if k == InstructionKind.BACKWARD:
            return self.bd[ins.stage] + self.w[ins.stage]
        if k == InstructionKind.BACKWARD_DGRAD:
            return self.bd[ins.stage]
        return self.w[ins.stage]


def _dep_key(ins: Instruction):
    return (ins.kind, ins.stage, ins.microbatch, ins.chunk)


def _deps(ins: Instruction, num_stages: int, num_chunks: int = 1) -> List[Tuple]:
    """Predecessor completion events of ``ins`` — the edges of the reference
    CostGraph (zero_bubble_v.py:198), generalized to V virtual chunks: chunk
    ``v`` of stage ``s`` is virtual stage ``v*S + s`` (Megatron VPP order,
    matching PipeModule.group_index).  Forward flows up the virtual-stage
    chain (wrapping S-1 -> 0 into the next chunk); the cotangent flows back
    down it."""
    F, B = InstructionKind.FORWARD, InstructionKind.BACKWARD
    Bd, W = InstructionKind.BACKWARD_DGRAD, InstructionKind.BACKWARD_WGRAD
    s, m, v = ins.stage, ins.microbatch, ins.chunk
    if ins.kind == F:
        if s > 0:
            return [(F, s - 1, m, v)]
        if v > 0:
            return [(F, num_stages - 1, m, v - 1)]  # chunk wrap-around hop
        return []
    if ins.kind in (B, Bd):
        deps: List[Tuple] = [(F, s, m, v)]
        if s < num_stages - 1:
            # the downstream virtual stage produces our cotangent with its
            # dgrad (or fused backward — whichever its schedule uses)
            deps.append(("cot", s + 1, m, v))
        elif v < num_chunks - 1:
            deps.append(("cot", 0, m, v + 1))  # wrap: next chunk, stage 0
        return deps
    if ins.kind == W:
        return [(Bd, s, m, v)]
    return []


def _ready_time(
    ins: Instruction, done: dict, num_stages: int, costs: StageCosts, num_chunks: int = 1
) -> Optional[float]:
    """Earliest start of ``ins`` given completion times ``done`` — the ONE
    encoding of the dependency/hop rules, shared by the simulator and the
    greedy generator so their cost models can never drift apart.  None if a
    predecessor hasn't completed."""
    t = 0.0
    for dep in _deps(ins, num_stages, num_chunks):
        if dep[0] == "cot":
            _, ds, dm, dv = dep
            key = (InstructionKind.BACKWARD_DGRAD, ds, dm, dv)
            if key not in done:
                key = (InstructionKind.BACKWARD, ds, dm, dv)
            if key not in done:
                return None
            t = max(t, done[key] + costs.comm)
        else:
            if dep not in done:
                return None
            hop = costs.comm if dep[0] == InstructionKind.FORWARD and dep[1] != ins.stage else 0.0
            t = max(t, done[dep] + hop)
    return t


def _num_chunks_of(schedule: List[List[Instruction]]) -> int:
    return 1 + max((i.chunk for stage_ins in schedule for i in stage_ins), default=0)


def simulate_schedule(
    schedule: List[List[Instruction]],
    costs: StageCosts,
) -> float:
    """Event-driven makespan of a per-stage instruction schedule under the
    cost model: stages execute their lists in order (each stage is a serial
    resource), cross-stage edges add ``costs.comm``; virtual chunks follow
    the VPP virtual-stage chain (chunk costs = hosting stage's costs).
    Returns the time the last instruction completes."""
    S = len(schedule)
    if len(costs.f) != S or len(costs.bd) != S or len(costs.w) != S:
        raise ValueError(
            f"StageCosts for {len(costs.f)} stages used with a {S}-stage schedule"
        )
    done: dict = {}
    V = _num_chunks_of(schedule)

    def ready_at(ins: Instruction) -> Optional[float]:
        return _ready_time(ins, done, S, costs, V)

    stage_time = [0.0] * S
    pos = [0] * S
    makespan = 0.0
    while any(p < len(q) for p, q in zip(pos, schedule)):
        progressed = False
        for s in range(S):
            while pos[s] < len(schedule[s]):
                ins = schedule[s][pos[s]]
                t = ready_at(ins)
                if t is None:
                    break
                start = max(stage_time[s], t)
                end = start + costs.of(ins)
                done[_dep_key(ins)] = end
                stage_time[s] = end
                makespan = max(makespan, end)
                pos[s] += 1
                progressed = True
        if not progressed:
            stuck = [q[p] for p, q in zip(pos, schedule) if p < len(q)]
            raise RuntimeError(f"schedule deadlock in simulation; waiting on {stuck[:8]}")
    return makespan


def _zb_greedy_schedule(
    num_stages: int,
    num_microbatches: int,
    costs: StageCosts,
    virtual_chunks: int = 1,
    max_inflight: Optional[int] = None,
) -> List[List[Instruction]]:
    """Global-clock greedy over the ZB dependency graph: repeatedly start the
    schedulable instruction with the earliest feasible start time, preferring
    dgrad > forward > wgrad on ties — W work naturally slots into gaps whose
    length the cost model exposes (the reference generator's rollout,
    zero_bubble_v.py:602).  With ``virtual_chunks`` > 1 each stage's F/Bd/W
    streams exist per chunk and dependencies follow the VPP virtual-stage
    chain (the reference CostGraph's virtual chunks, zero_bubble_v.py:198).

    Memory bound: the engine pins each forward's linearization residuals
    until BACKWARD_WGRAD pops them (engine.py wgrad_stash), so stage ``s``
    may hold at most ``(V-1)*S + 2*(S-s) - 1`` forwards whose WGRAD hasn't
    run — the effective residual depth of the fixed-defer ZB-H1 heuristic
    (its in-flight F-Bd depth ``S-s`` plus its W deferral ``S-s-1``),
    extended by the VPP warmup term.  This MATCHES the heuristic candidate's
    own peak (both candidates honor the same contract); note it is ~2x the
    1F1B in-flight depth ``S-s`` — pass ``max_inflight`` to pin a tighter
    per-stage cap when HBM is the binding constraint (V=1 only; a cap below
    the VPP warmup depth deadlocks V>1).  A looser cap trades O(M) memory
    for makespan the way the reference's memory-limited CostGraph
    deliberately does not."""
    S, M, V = num_stages, num_microbatches, virtual_chunks
    F, Bd, W = InstructionKind.FORWARD, InstructionKind.BACKWARD_DGRAD, InstructionKind.BACKWARD_WGRAD
    prio = {Bd: 0, F: 1, W: 2}
    done: dict = {}
    stage_time = [0.0] * S
    schedule: List[List[Instruction]] = [[] for _ in range(S)]
    bptr = [[0] * V for _ in range(S)]
    wptr = [[0] * V for _ in range(S)]
    cap = [max(1, (V - 1) * S + 2 * (S - s) - 1) for s in range(S)]
    if max_inflight is not None:
        if V > 1:
            raise ValueError("max_inflight caps are V=1 only (VPP warmup needs the default)")
        cap = [max(1, min(c, max_inflight)) for c in cap]

    # Forwards issue in the canonical Megatron wave order (chunks cycle in
    # groups of min(S, M) microbatches — the same order the interleaved
    # generator uses).  A free F order lets the rollout burn a stage's whole
    # residual cap on chunk-0 forwards while every backward transitively
    # waits on the LAST chunk's forward (no W can free the cap) — deadlock.
    # Pinning the F order keeps the rollout deadlock-free; the cost model
    # still owns the placement of every Bd and W.
    group = min(S, M)
    fwd_order: List[Tuple[int, int]] = []
    m0 = 0
    while len(fwd_order) < M * V:
        for v in range(V):
            for m in range(m0, min(m0 + group, M)):
                fwd_order.append((m, v))
        m0 += group
    fnext = [0] * S
    fcount = [0] * S

    def candidates(s):
        out = []
        nxt = []
        live = fcount[s] - sum(wptr[s])
        if fnext[s] < M * V and live < cap[s]:
            m, v = fwd_order[fnext[s]]
            nxt.append(Instruction(F, s, m, v))
        for v in range(V):
            if bptr[s][v] < M:
                nxt.append(Instruction(Bd, s, bptr[s][v], v))
            if wptr[s][v] < bptr[s][v]:  # wgrad ready once its dgrad has run
                nxt.append(Instruction(W, s, wptr[s][v], v))
        for ins in nxt:
            rdy = _ready_time(ins, done, S, costs, V)
            if rdy is not None:
                out.append((ins, rdy))
        return out

    total = 3 * M * S * V
    scheduled = 0
    while scheduled < total:
        best = None
        for s in range(S):
            for ins, rdy in candidates(s):
                start = max(stage_time[s], rdy)
                # chunk in the tie-break keeps the rollout deterministic
                key = (start, prio[ins.kind], s, ins.chunk)
                if best is None or key < best[0]:
                    best = (key, ins, start)
        if best is None:
            raise RuntimeError("zb greedy scheduler stalled (dependency bug)")
        _, ins, start = best
        s = ins.stage
        end = start + costs.of(ins)
        done[_dep_key(ins)] = end
        stage_time[s] = end
        schedule[s].append(ins)
        if ins.kind == F:
            fnext[s] += 1
            fcount[s] += 1
        elif ins.kind == Bd:
            bptr[s][ins.chunk] += 1
        else:
            wptr[s][ins.chunk] += 1
        scheduled += 1
    return schedule


@functools.lru_cache(maxsize=256)
def _zb_cost_schedule_cached(
    num_stages: int,
    num_microbatches: int,
    costs: StageCosts,
    virtual_chunks: int = 1,
    max_inflight: Optional[int] = None,
):
    cands = [
        _zb_greedy_schedule(num_stages, num_microbatches, costs, virtual_chunks, max_inflight)
    ]
    if max_inflight is None:
        # the fixed heuristics don't honor a tightened residual cap — only
        # the capped greedy is a candidate when one is requested
        if virtual_chunks > 1:
            if num_microbatches % num_stages == 0:
                # interleaved 1F1B (fused B) is the V>1 heuristic baseline;
                # with a partial tail wave its order is infeasible (see
                # interleaved_1f1b_schedule) — the greedy alone covers that
                cands.append(
                    interleaved_1f1b_schedule(num_stages, num_microbatches, virtual_chunks)
                )
        else:
            cands.append(zero_bubble_schedule(num_stages, num_microbatches))
    # prediction recording lives in the public wrapper (this fn is
    # lru-cached: recording here would skip cache hits)
    return min(cands, key=lambda sch: simulate_schedule(sch, costs))  # vescale-lint: disable=VSC208


def zero_bubble_cost_schedule(
    num_stages: int,
    num_microbatches: int,
    costs: Union[StageCosts, Sequence[float], None] = None,
    virtual_chunks: int = 1,
    max_inflight: Optional[int] = None,
) -> List[List[Instruction]]:
    """Cost-aware zero-bubble schedule (reference CostGraph generator,
    zero_bubble_v.py:198,602): generate candidate schedules — a fixed
    heuristic (ZB-H1 defer for V=1, interleaved 1F1B for V>1) and a
    cost-model greedy rollout — simulate each under the cost model, and
    return the one with the smallest makespan.

    ``costs``: a ``StageCosts``, a per-stage weight sequence (param/FLOP
    counts — 1:1:1 F:Bd:W assumed), or None (uniform).  ``max_inflight``
    (V=1 only) pins a per-stage residual cap below the default ZB-H1 depth
    for HBM-bound configs (greedy-only: the fixed heuristics don't honor
    it).  Results are memoized per (S, M, costs, V, cap): a training loop
    re-building its schedule every step pays the Python rollout once."""
    if costs is None:
        costs = StageCosts.uniform(num_stages)
    elif not isinstance(costs, StageCosts):
        costs = StageCosts.from_weights(costs)
    if len(costs.f) != num_stages or len(costs.bd) != num_stages or len(costs.w) != num_stages:
        raise ValueError(
            f"schedule_costs has {len(costs.f)} stages, plan has {num_stages}"
        )
    cached = _zb_cost_schedule_cached(
        num_stages, num_microbatches, costs, virtual_chunks, max_inflight
    )
    from ..telemetry import costaudit as _ca

    if _ca.is_active():
        # ledger the chosen schedule's simulated makespan — units follow
        # the StageCosts the caller priced in (µs when they came from a
        # calibrated estimate_stage_costs, abstract cost units otherwise,
        # which the auditor counts but never computes divergence over)
        from ..telemetry import calibrate as _cal

        digest = _cal.active_digest()
        _ca.record_prediction(
            "pipe_schedule",
            predicted_us=simulate_schedule(cached, costs) if digest is not None else None,
            digest=digest,
            unit="us" if digest is not None else "cost",
            detail={"stages": num_stages, "microbatches": num_microbatches,
                    "virtual_chunks": virtual_chunks},
        )
    return [list(stage) for stage in cached]  # callers may mutate their copy


def estimate_stage_costs(
    pipe_module, params_per_group, x_example, comm: Optional[float] = 0.0
) -> StageCosts:
    """Per-stage costs from the graph FLOP model — the profiling role of the
    reference's CostGraph (zero_bubble_v.py:198): trace each group's forward
    (``jax.make_jaxpr`` on avals, no execution), total its FLOPs, and assume
    the standard 1:1:1 F:Bd:W ratio.  ``x_example`` is the stage-0 input
    (array or ShapeDtypeStruct); activations chain through ``eval_shape``.
    Requires one group per stage (V=1, the cost-schedule's domain).

    ``comm=None`` asks for MEASURED units: with a calibration table armed
    (``VESCALE_COST_CALIBRATION``, telemetry/calibrate.py) carrying a
    ``matmul_gflops`` throughput sample, stage FLOPs convert to measured
    microseconds and ``comm`` becomes the table's p2p (ppermute) wall time
    for the boundary activation's byte size — so ``simulate_schedule``
    ranks candidate schedules by wall-clock, not abstract FLOPs.  Without a
    (usable) table, ``comm=None`` degrades to the legacy ``comm=0.0``
    FLOP-denominated behavior, bit-identically."""
    import jax
    import numpy as np

    from .graph_split import jaxpr_flops

    weights, x = [], x_example
    act_bytes = None
    for g in range(pipe_module.num_groups):
        fwd = pipe_module.group_forward(g)
        weights.append(jaxpr_flops(jax.make_jaxpr(fwd)(params_per_group[g], x)))
        x = jax.eval_shape(fwd, params_per_group[g], x)
        if g == 0:  # the stage-boundary activation every p2p hop moves
            act_bytes = sum(
                int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(x)
            )
    if comm is None:
        from ..telemetry import calibrate as _cal

        # table_for, not active_table: the platform staleness check must
        # gate the matmul_gflops conversion too — a gloo-CPU throughput
        # sample silently inflating TPU stage costs would skew the
        # compute:comm ratio simulate_schedule ranks by
        table = _cal.table_for(None)
        gflops = (table.meta.get("matmul_gflops") if table is not None else None)
        if table is not None and gflops:
            us_per_flop = 1.0 / (float(gflops) * 1e3)  # GFLOP/s -> us/FLOP
            n = max(2, pipe_module.num_groups)
            comm_us = _cal.table_cost_us(table, "ppermute", n, act_bytes or 0)
            if comm_us is None:
                from .. import collectives as C

                comm_us = C.analytic_cost_us("ppermute", (act_bytes or 0) / 1e9, n)
            sc = StageCosts.from_weights(
                [w * us_per_flop for w in weights], comm=comm_us
            )
            from ..telemetry import costaudit as _ca

            # µs-denominated stage costs are a priced plan: ledger the
            # total so the auditor can join a measured pipeline step
            _ca.record_prediction(
                "pipe_stage_costs",
                predicted_us=sum(sc.f) + sum(sc.bd) + sum(sc.w),
                digest=table.digest(), unit="us",
                detail={"stages": len(sc.f), "comm_us": comm_us},
            )
            return sc
        comm = 0.0  # no usable table: legacy FLOP units
    return StageCosts.from_weights(weights, comm=comm)


def build_schedule(
    plan: PipelineParallelPlan,
    num_microbatches: int,
    costs: Optional[StageCosts] = None,
) -> List[List[Instruction]]:
    """Reference ScheduleEngine/PipelineEmitter dispatch (pipe_emmiter.py:132).
    ``costs`` (or ``plan.schedule_costs``) routes ZERO_BUBBLE through the
    cost-graph generator."""
    st = plan.schedule_type
    if st == PipelineScheduleType.GPIPE:
        return gpipe_schedule(plan.num_stages, num_microbatches)
    if st == PipelineScheduleType.SIMPLE_1F1B:
        return one_f_one_b_schedule(plan.num_stages, num_microbatches)
    if st == PipelineScheduleType.INTERLEAVED_1F1B:
        return interleaved_1f1b_schedule(plan.num_stages, num_microbatches, plan.virtual_chunks)
    if st == PipelineScheduleType.ZERO_BUBBLE:
        costs = costs if costs is not None else plan.schedule_costs
        V = max(1, plan.virtual_chunks or 1)
        if costs is not None or V > 1:
            # V>1 always routes through the cost generator (uniform costs if
            # none given) — the fixed-defer heuristic is V=1-only
            return zero_bubble_cost_schedule(plan.num_stages, num_microbatches, costs, V)
        return zero_bubble_schedule(plan.num_stages, num_microbatches)
    raise NotImplementedError(f"schedule {st}")
