"""Pipeline schedules — instruction IR + generators.

Capability parity with the reference instruction VM
(legacy/vescale/pipe/_schedules/):
  - instruction base/registry      <- instruction_base.py:58
  - 1F1B (pipedream flush)         <- pipedream_flush.py:653,762
  - GPipe                          <- pipedream_flush.py (forward_backward_no_pipelining variants)
  - Interleaved 1F1B (VPP)         <- looping_bfs.py:699,873
  - Zero-bubble (split B into dgrad/wgrad)  <- zero_bubble_v.py:132,198,602

The IR is deliberately tiny: SEND/RECV pairs are implicit in the
single-controller engine (activations flow through a table; on hardware the
transfer is an XLA transfer/ppermute — see spmd.py for the compiled path),
so instructions carry only compute semantics + ordering.  Zero-bubble's
W/B split is first-class: B (dgrad) propagates the activation gradient,
W (wgrad) accumulates the weight gradient later, filling bubbles.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List

from ..plan import PipelineParallelPlan, PipelineScheduleType

__all__ = [
    "InstructionKind",
    "Instruction",
    "gpipe_schedule",
    "one_f_one_b_schedule",
    "interleaved_1f1b_schedule",
    "zero_bubble_schedule",
    "build_schedule",
]


class InstructionKind(enum.Enum):
    FORWARD = "F"
    BACKWARD = "B"        # full backward (dgrad + wgrad fused)
    BACKWARD_DGRAD = "Bd"  # zero-bubble: input grad only
    BACKWARD_WGRAD = "W"   # zero-bubble: weight grad accumulation


@dataclasses.dataclass(frozen=True)
class Instruction:
    kind: InstructionKind
    stage: int          # physical pipeline stage
    microbatch: int
    chunk: int = 0      # virtual/model chunk (interleaved schedules)

    def __repr__(self):
        c = f"c{self.chunk}" if self.chunk else ""
        return f"{self.kind.value}{c}(s{self.stage},m{self.microbatch})"


def gpipe_schedule(num_stages: int, num_microbatches: int) -> List[List[Instruction]]:
    """All forwards, then all backwards (reference GPIPE mode)."""
    out = []
    for s in range(num_stages):
        ins = [Instruction(InstructionKind.FORWARD, s, m) for m in range(num_microbatches)]
        ins += [
            Instruction(InstructionKind.BACKWARD, s, m)
            for m in reversed(range(num_microbatches))
        ]
        out.append(ins)
    return out


def one_f_one_b_schedule(num_stages: int, num_microbatches: int) -> List[List[Instruction]]:
    """PipeDream-flush 1F1B (reference pipedream_flush.py:762): per stage,
    ``num_stages - s - 1`` warmup forwards, then 1F1B steady state, then
    cooldown backwards."""
    F, B = InstructionKind.FORWARD, InstructionKind.BACKWARD
    out = []
    for s in range(num_stages):
        warmup = min(num_stages - s - 1, num_microbatches)
        remaining = num_microbatches - warmup
        ins = [Instruction(F, s, m) for m in range(warmup)]
        fwd_m, bwd_m = warmup, 0
        for _ in range(remaining):
            ins.append(Instruction(F, s, fwd_m))
            fwd_m += 1
            ins.append(Instruction(B, s, bwd_m))
            bwd_m += 1
        while bwd_m < num_microbatches:
            ins.append(Instruction(B, s, bwd_m))
            bwd_m += 1
        out.append(ins)
    return out


def interleaved_1f1b_schedule(
    num_stages: int, num_microbatches: int, virtual_chunks: int
) -> List[List[Instruction]]:
    """Interleaved 1F1B / VPP (reference looping_bfs.py:873).  Each physical
    stage hosts ``virtual_chunks`` model chunks; microbatches cycle chunks in
    groups of ``num_stages`` (Megatron ordering).

    The generated order is dependency-consistent for the eager engine; exact
    bubble timing is the compiled path's concern."""
    F, B = InstructionKind.FORWARD, InstructionKind.BACKWARD
    M, S, V = num_microbatches, num_stages, virtual_chunks
    out = []
    total = M * V
    for s in range(S):
        # forward order: chunks in waves of min(S, M) microbatches
        fwd_order = []
        group = min(S, M)
        m0 = 0
        while len(fwd_order) < total:
            for v in range(V):
                for m in range(m0, min(m0 + group, M)):
                    fwd_order.append((m, v))
            m0 += group
        bwd_order = []
        m0 = 0
        while len(bwd_order) < total:
            for v in reversed(range(V)):
                for m in range(m0, min(m0 + group, M)):
                    bwd_order.append((m, v))
            m0 += group
        warmup = min((S - s - 1) * 2 + (V - 1) * S, total)
        ins = [Instruction(F, s, m, v) for m, v in fwd_order[:warmup]]
        fi, bi = warmup, 0
        while fi < total or bi < total:
            if fi < total:
                m, v = fwd_order[fi]
                ins.append(Instruction(F, s, m, v))
                fi += 1
            if bi < total:
                m, v = bwd_order[bi]
                ins.append(Instruction(B, s, m, v))
                bi += 1
        out.append(ins)
    return out


def zero_bubble_schedule(num_stages: int, num_microbatches: int) -> List[List[Instruction]]:
    """Zero-bubble (ZB-H1-style, reference zero_bubble_v.py): 1F1B skeleton
    with backward split into Bd (dgrad, on the critical path) and W (wgrad,
    deferred to fill bubbles / drained at the end)."""
    F, Bd, W = InstructionKind.FORWARD, InstructionKind.BACKWARD_DGRAD, InstructionKind.BACKWARD_WGRAD
    out = []
    for s in range(num_stages):
        warmup = min(num_stages - s - 1, num_microbatches)
        remaining = num_microbatches - warmup
        ins = [Instruction(F, s, m) for m in range(warmup)]
        fwd_m, bwd_m, w_m = warmup, 0, 0
        for _ in range(remaining):
            ins.append(Instruction(F, s, fwd_m))
            fwd_m += 1
            ins.append(Instruction(Bd, s, bwd_m))
            bwd_m += 1
            # defer W by num_stages-s-1 microbatches to fill the bubble
            if bwd_m - w_m > max(0, num_stages - s - 1):
                ins.append(Instruction(W, s, w_m))
                w_m += 1
        while bwd_m < num_microbatches:
            ins.append(Instruction(Bd, s, bwd_m))
            bwd_m += 1
            if w_m < bwd_m - 1:
                ins.append(Instruction(W, s, w_m))
                w_m += 1
        while w_m < num_microbatches:
            ins.append(Instruction(W, s, w_m))
            w_m += 1
        out.append(ins)
    return out


def build_schedule(plan: PipelineParallelPlan, num_microbatches: int) -> List[List[Instruction]]:
    """Reference ScheduleEngine/PipelineEmitter dispatch (pipe_emmiter.py:132)."""
    st = plan.schedule_type
    if st == PipelineScheduleType.GPIPE:
        return gpipe_schedule(plan.num_stages, num_microbatches)
    if st == PipelineScheduleType.SIMPLE_1F1B:
        return one_f_one_b_schedule(plan.num_stages, num_microbatches)
    if st == PipelineScheduleType.INTERLEAVED_1F1B:
        return interleaved_1f1b_schedule(plan.num_stages, num_microbatches, plan.virtual_chunks)
    if st == PipelineScheduleType.ZERO_BUBBLE:
        return zero_bubble_schedule(plan.num_stages, num_microbatches)
    raise NotImplementedError(f"schedule {st}")
