"""TokenDataLoader — ctypes binding of the native prefetching loader.

The TPU-native equivalent of the reference examples' data pipelines
(legacy/examples/nanogpt_4D_finetune/finetune_4D.py get_batch): a C++
mmap + prefetch-thread loader (data/native/dataloader.cpp) keeps the host
input path off the TPU step's critical path.  DP sharding: each dp rank
draws a disjoint deterministic stream, so batches differ across dp while
runs reproduce exactly (seed-stable SplitMix64).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Optional, Tuple

import numpy as np

__all__ = ["TokenDataLoader", "build_native"]

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SRC = os.path.join(_NATIVE_DIR, "dataloader.cpp")
_SO = os.path.join(_NATIVE_DIR, "libvdl.so")
_BUILD_LOCK = threading.Lock()
_LIB = None


def build_native(force: bool = False) -> str:
    """Compile the native loader (g++ -O3 -shared) if needed; returns the
    .so path."""
    with _BUILD_LOCK:
        if force or not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread", _SRC, "-o", _SO]
            subprocess.run(cmd, check=True, capture_output=True)
    return _SO


def _lib():
    global _LIB
    if _LIB is None:
        so = build_native()
        lib = ctypes.CDLL(so)
        lib.vdl_open.restype = ctypes.c_void_p
        lib.vdl_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_uint64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.vdl_next.restype = ctypes.c_int
        lib.vdl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.vdl_num_tokens.restype = ctypes.c_int64
        lib.vdl_num_tokens.argtypes = [ctypes.c_void_p]
        lib.vdl_close.restype = None
        lib.vdl_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
    return _LIB


class TokenDataLoader:
    """Batches of (input, target) next-token pairs from a binary token file
    (uint16 or int32/uint32 tokens, nanoGPT .bin convention).

        loader = TokenDataLoader("train.bin", batch=8, seq_len=1024, seed=1)
        batch = loader.next()   # {"input": (B,T) int32, "target": (B,T)}
    """

    def __init__(
        self,
        path: str,
        batch: int,
        seq_len: int,
        *,
        seed: int = 0,
        dp_rank: int = 0,
        dp_world: int = 1,
        token_dtype=np.uint16,
        num_prefetch_threads: int = 2,
    ):
        token_bytes = np.dtype(token_dtype).itemsize
        if token_bytes not in (2, 4):
            raise ValueError("token dtype must be 2 or 4 bytes")
        self.batch, self.seq_len = batch, seq_len
        self._h = _lib().vdl_open(
            path.encode(), token_bytes, seq_len, batch, seed, dp_rank, dp_world, num_prefetch_threads
        )
        if not self._h:
            raise OSError(f"cannot open token file {path!r} (too small or unreadable)")

    @property
    def num_tokens(self) -> int:
        return int(_lib().vdl_num_tokens(self._h))

    def next(self) -> dict:
        # DATA_LOAD span (VERDICT item 7): the one host-side region of the
        # input path — a batch that waits here is a batch the step waited
        # for.  Dormant profiler/telemetry: nullcontext + one branch.
        from ..ndtimeline.api import ndtimeit
        from ..ndtimeline.predefined import DATA_LOAD
        from .. import telemetry as _tel

        # unconditional stamp (~ns): telemetry flipping on mid-fetch must
        # not observe perf_counter() - 0.0 into the histogram
        t0 = time.perf_counter()
        with ndtimeit(DATA_LOAD):
            x = np.empty((self.batch, self.seq_len), np.int32)
            y = np.empty((self.batch, self.seq_len), np.int32)
            rc = _lib().vdl_next(
                self._h,
                x.ctypes.data_as(ctypes.c_void_p),
                y.ctypes.data_as(ctypes.c_void_p),
            )
            if rc != 0:
                raise RuntimeError("native loader failed")
        if _tel.is_active():
            _tel.observe("data_load_seconds", time.perf_counter() - t0)
        return {"input": x, "target": y}

    def __iter__(self):
        while True:
            yield self.next()

    def close(self) -> None:
        if getattr(self, "_h", None):
            _lib().vdl_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
