"""TokenDataLoader — ctypes binding of the native prefetching loader.

The TPU-native equivalent of the reference examples' data pipelines
(legacy/examples/nanogpt_4D_finetune/finetune_4D.py get_batch): a C++
mmap + prefetch-thread loader (data/native/dataloader.cpp) keeps the host
input path off the TPU step's critical path.  DP sharding: each dp rank
draws a disjoint deterministic stream, so batches differ across dp while
runs reproduce exactly (seed-stable SplitMix64).

Resilience surfaces (resilience/):

  * ``next()`` routes through the retry/backoff policy
    (``VESCALE_LOADER_RETRIES`` / ``VESCALE_IO_BACKOFF_*``) and the
    faultsim ``loader_next`` hook, so transient native failures are
    absorbed and injectable.
  * ``state()`` / ``load_state()`` — the sample-exact resume contract:
    batches are a pure function of (seed, dp coords, batch index), so the
    position is one counter.  Restore fast-forwards via the native
    ``vdl_seek`` (O(1) — skipped batches are never filled); rewinding
    reopens the file first (prefetch state cannot run backwards).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Dict, Optional

import numpy as np

__all__ = ["TokenDataLoader", "build_native"]

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SRC = os.path.join(_NATIVE_DIR, "dataloader.cpp")
_SO = os.path.join(_NATIVE_DIR, "libvdl.so")
_BUILD_LOCK = threading.Lock()
_LIB = None


def build_native(force: bool = False) -> str:
    """Compile the native loader (g++ -O3 -shared) if needed; returns the
    .so path."""
    with _BUILD_LOCK:
        if force or not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread", _SRC, "-o", _SO]
            subprocess.run(cmd, check=True, capture_output=True)
    return _SO


def _lib():
    global _LIB
    if _LIB is None:
        so = build_native()
        lib = ctypes.CDLL(so)
        lib.vdl_open.restype = ctypes.c_void_p
        lib.vdl_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_uint64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.vdl_next.restype = ctypes.c_int
        lib.vdl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.vdl_num_tokens.restype = ctypes.c_int64
        lib.vdl_num_tokens.argtypes = [ctypes.c_void_p]
        lib.vdl_close.restype = None
        lib.vdl_close.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "vdl_seek"):  # absent only with a stale prebuilt .so
            lib.vdl_seek.restype = ctypes.c_int
            lib.vdl_seek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _LIB = lib
    return _LIB


class TokenDataLoader:
    """Batches of (input, target) next-token pairs from a binary token file
    (uint16 or int32/uint32 tokens, nanoGPT .bin convention).

        loader = TokenDataLoader("train.bin", batch=8, seq_len=1024, seed=1)
        batch = loader.next()   # {"input": (B,T) int32, "target": (B,T)}
    """

    def __init__(
        self,
        path: str,
        batch: int,
        seq_len: int,
        *,
        seed: int = 0,
        dp_rank: int = 0,
        dp_world: int = 1,
        token_dtype=np.uint16,
        num_prefetch_threads: int = 2,
    ):
        token_bytes = np.dtype(token_dtype).itemsize
        if token_bytes not in (2, 4):
            raise ValueError("token dtype must be 2 or 4 bytes")
        self.batch, self.seq_len = batch, seq_len
        self.path = path
        self.seed, self.dp_rank, self.dp_world = int(seed), int(dp_rank), int(dp_world)
        self._token_bytes = token_bytes
        self._nprefetch = num_prefetch_threads
        # the lib handle is cached ON the instance: __del__ during
        # interpreter shutdown must not re-enter build_native()/CDLL (module
        # globals may already be torn down)
        self._lib = _lib()
        self._batches_served = 0  # serve cursor, = next batch index
        self._close_lock = threading.Lock()  # close() idempotent under races
        self._h = self._open_native()

    def _open_native(self):
        h = self._lib.vdl_open(
            self.path.encode(),
            self._token_bytes,
            self.seq_len,
            self.batch,
            self.seed,
            self.dp_rank,
            self.dp_world,
            self._nprefetch,
        )
        if not h:
            raise OSError(f"cannot open token file {self.path!r} (too small or unreadable)")
        return h

    @property
    def num_tokens(self) -> int:
        return int(self._lib.vdl_num_tokens(self._h))

    @property
    def batches_served(self) -> int:
        return self._batches_served

    def _fetch(self) -> dict:
        """One native batch fetch — the unit the retry policy wraps.  The
        faultsim hook sits INSIDE so an injected fault consumes one attempt
        and a clean retry can succeed (transient-failure semantics)."""
        from ..resilience import faultsim as _fs

        _fs.check("loader_next", ctx=f"batch#{self._batches_served} {self.path}")
        if self._h is None:
            raise RuntimeError(f"TokenDataLoader({self.path!r}) is closed")
        x = np.empty((self.batch, self.seq_len), np.int32)
        y = np.empty((self.batch, self.seq_len), np.int32)
        rc = self._lib.vdl_next(
            self._h,
            x.ctypes.data_as(ctypes.c_void_p),
            y.ctypes.data_as(ctypes.c_void_p),
        )
        if rc != 0:
            raise RuntimeError(
                f"native loader failed: vdl_next rc={rc} "
                f"(path={self.path!r}, batch_index={self._batches_served})"
            )
        return {"input": x, "target": y}

    def next(self) -> dict:
        # DATA_LOAD span (VERDICT item 7): the one host-side region of the
        # input path — a batch that waits here is a batch the step waited
        # for.  Dormant profiler/telemetry: nullcontext + one branch.
        from ..ndtimeline.api import ndtimeit
        from ..ndtimeline.predefined import DATA_LOAD
        from .. import telemetry as _tel
        from ..resilience.retry import loader_policy

        # closed-loader check BEFORE the retry wrapper: a programming error
        # must fail fast, not burn retries/backoff as if it were transient
        if self._h is None:
            raise RuntimeError(f"TokenDataLoader({self.path!r}) is closed")
        # unconditional stamp (~ns): telemetry flipping on mid-fetch must
        # not observe perf_counter() - 0.0 into the histogram
        t0 = time.perf_counter()
        with ndtimeit(DATA_LOAD):
            out = loader_policy().call(
                self._fetch, description=f"batch#{self._batches_served} of {self.path}"
            )
        self._batches_served += 1
        if _tel.is_active():
            _tel.observe("data_load_seconds", time.perf_counter() - t0)
        return out

    # --------------------------------------------------------- resume state
    def state(self) -> Dict[str, int]:
        """Checkpointable position: batches are a pure function of
        (seed, dp_rank, dp_world, batch index), so the stream is one
        counter plus its identity coords (dp coords are part of the state
        because restoring rank r's counter into rank q's stream would
        silently change the data)."""
        return {
            "batches_served": int(self._batches_served),
            "seed": self.seed,
            "dp_rank": self.dp_rank,
            "dp_world": self.dp_world,
            "batch": int(self.batch),
            "seq_len": int(self.seq_len),
        }

    def load_state(self, state: Dict[str, int]) -> None:
        """Position the stream so the next ``next()`` returns batch
        ``state['batches_served']`` — sample-exact resume.  Forward moves
        use the native seek (O(1)); backward moves (rollback) reopen the
        file and seek from zero.  Identity coords must match: a loader
        built for different dp coords / shape is a DIFFERENT stream."""
        for key in ("seed", "dp_rank", "dp_world", "batch", "seq_len"):
            if key in state and int(state[key]) != int(getattr(self, key)):
                raise ValueError(
                    f"loader state mismatch on {key!r}: checkpoint has "
                    f"{state[key]}, this loader has {getattr(self, key)} — "
                    "resuming would silently change the data stream"
                )
        target = int(state["batches_served"])
        if self._h is None:
            raise RuntimeError(f"TokenDataLoader({self.path!r}) is closed")
        if target < self._batches_served:
            # prefetch cannot run backwards: reopen, then seek forward
            with self._close_lock:
                h, self._h = self._h, None
            if h:
                self._lib.vdl_close(h)
            self._h = self._open_native()
            self._batches_served = 0
        if target > self._batches_served:
            self._seek(target)
        self._batches_served = target

    def _seek(self, target: int) -> None:
        if hasattr(self._lib, "vdl_seek"):
            rc = self._lib.vdl_seek(self._h, target)
            if rc != 0:
                raise RuntimeError(
                    f"native loader seek to {target} failed: rc={rc} (path={self.path!r})"
                )
            return
        # stale .so without vdl_seek: drain-and-discard fallback
        x = np.empty((self.batch, self.seq_len), np.int32)
        y = np.empty((self.batch, self.seq_len), np.int32)
        for _ in range(target - self._batches_served):
            rc = self._lib.vdl_next(
                self._h,
                x.ctypes.data_as(ctypes.c_void_p),
                y.ctypes.data_as(ctypes.c_void_p),
            )
            if rc != 0:
                raise RuntimeError(
                    f"native loader failed during fast-forward: vdl_next rc={rc} "
                    f"(path={self.path!r})"
                )

    def __iter__(self):
        while True:
            yield self.next()

    def close(self) -> None:
        # pop the handle under the lock so concurrent close()/close() (or
        # close racing __del__ at shutdown) frees it exactly once; getattr
        # guards a __del__ after a failed __init__
        lock = getattr(self, "_close_lock", None)
        if lock is None:
            return
        with lock:
            h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.vdl_close(h)

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
