"""TokenDataLoader — ctypes binding of the native prefetching loader.

The TPU-native equivalent of the reference examples' data pipelines
(legacy/examples/nanogpt_4D_finetune/finetune_4D.py get_batch): a C++
mmap + prefetch-thread loader (data/native/dataloader.cpp) keeps the host
input path off the TPU step's critical path.  DP sharding: each dp rank
draws a disjoint deterministic stream, so batches differ across dp while
runs reproduce exactly (seed-stable SplitMix64).

Resilience surfaces (resilience/):

  * ``next()`` routes through the retry/backoff policy
    (``VESCALE_LOADER_RETRIES`` / ``VESCALE_IO_BACKOFF_*``) and the
    faultsim ``loader_next`` hook, so transient native failures are
    absorbed and injectable.
  * ``state()`` / ``load_state()`` — the sample-exact resume contract:
    batches are a pure function of (seed, dp coords, batch index), so the
    position is one counter.  Restore fast-forwards via the native
    ``vdl_seek`` (O(1) — skipped batches are never filled); rewinding
    reopens the file first (prefetch state cannot run backwards).
  * ``elastic=True`` (env ``VESCALE_ELASTIC_LOADER``) keys every sample on
    its GLOBAL row index instead of the per-rank partition, making the
    global stream invariant to the (dp_world, per-rank batch) split; the
    state then carries a rank-invariant global cursor so a resume onto a
    different world size re-splits the position sample-exactly
    (docs/resilience.md §Elastic world size).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Dict, Optional

import numpy as np

__all__ = ["TokenDataLoader", "build_native"]

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SRC = os.path.join(_NATIVE_DIR, "dataloader.cpp")
_ABI_VERSION = 2  # must match dataloader.cpp vdl_abi_version()
# ABI-versioned output name: a stale .so from an older C-API can otherwise
# shadow a rebuild forever (dlopen dedups by pathname, so reloading the
# same path after a rebuild returns the cached stale handle) and silently
# ignore trailing vdl_open arguments
_SO = os.path.join(_NATIVE_DIR, f"libvdl.abi{_ABI_VERSION}.so")
_BUILD_LOCK = threading.Lock()
_LIB = None


def build_native(force: bool = False) -> str:
    """Compile the native loader (g++ -O3 -shared) if needed; returns the
    .so path."""
    with _BUILD_LOCK:
        if force or not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread", _SRC, "-o", _SO]
            subprocess.run(cmd, check=True, capture_output=True)
    return _SO


def _lib():
    global _LIB
    if _LIB is None:
        so = build_native()
        lib = ctypes.CDLL(so)
        if not hasattr(lib, "vdl_abi_version") or lib.vdl_abi_version() != _ABI_VERSION:
            # can only mean the versioned .so on disk was built from
            # mismatched source; a re-CDLL of the same path would return
            # the cached stale dlopen handle, so there is no in-process
            # recovery — fail loudly
            raise RuntimeError(
                f"native loader {so} does not export ABI v{_ABI_VERSION}; "
                "remove it and restart (stale build artifact)"
            )
        lib.vdl_open.restype = ctypes.c_void_p
        lib.vdl_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_uint64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.vdl_next.restype = ctypes.c_int
        lib.vdl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.vdl_num_tokens.restype = ctypes.c_int64
        lib.vdl_num_tokens.argtypes = [ctypes.c_void_p]
        lib.vdl_close.restype = None
        lib.vdl_close.argtypes = [ctypes.c_void_p]
        lib.vdl_seek.restype = ctypes.c_int
        lib.vdl_seek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _LIB = lib
    return _LIB


class TokenDataLoader:
    """Batches of (input, target) next-token pairs from a binary token file
    (uint16 or int32/uint32 tokens, nanoGPT .bin convention).

        loader = TokenDataLoader("train.bin", batch=8, seq_len=1024, seed=1)
        batch = loader.next()   # {"input": (B,T) int32, "target": (B,T)}
    """

    def __init__(
        self,
        path: str,
        batch: int,
        seq_len: int,
        *,
        seed: int = 0,
        dp_rank: int = 0,
        dp_world: int = 1,
        token_dtype=np.uint16,
        num_prefetch_threads: int = 2,
        elastic: Optional[bool] = None,
    ):
        token_bytes = np.dtype(token_dtype).itemsize
        if token_bytes not in (2, 4):
            raise ValueError("token dtype must be 2 or 4 bytes")
        if elastic is None:
            from ..analysis import envreg

            elastic = envreg.get_bool("VESCALE_ELASTIC_LOADER")
        self.batch, self.seq_len = batch, seq_len
        self.path = path
        self.seed, self.dp_rank, self.dp_world = int(seed), int(dp_rank), int(dp_world)
        # elastic: samples are keyed on their GLOBAL row index over the full
        # token span, so the global stream is invariant to the
        # (dp_world, per-rank batch) factorization of a fixed global batch —
        # the property that lets a resume re-split the position across a
        # world-size change (docs/resilience.md §Elastic restore)
        self.elastic = bool(elastic)
        self._token_bytes = token_bytes
        self._nprefetch = num_prefetch_threads
        # the lib handle is cached ON the instance: __del__ during
        # interpreter shutdown must not re-enter build_native()/CDLL (module
        # globals may already be torn down)
        self._lib = _lib()
        self._batches_served = 0  # serve cursor, = next batch index
        self._close_lock = threading.Lock()  # close() idempotent under races
        self._h = self._open_native()

    def _open_native(self):
        h = self._lib.vdl_open(
            self.path.encode(),
            self._token_bytes,
            self.seq_len,
            self.batch,
            self.seed,
            self.dp_rank,
            self.dp_world,
            self._nprefetch,
            1 if self.elastic else 0,
        )
        if not h:
            raise OSError(f"cannot open token file {self.path!r} (too small or unreadable)")
        return h

    @property
    def num_tokens(self) -> int:
        return int(self._lib.vdl_num_tokens(self._h))

    @property
    def batches_served(self) -> int:
        return self._batches_served

    def _fetch(self) -> dict:
        """One native batch fetch — the unit the retry policy wraps.  The
        faultsim hook sits INSIDE so an injected fault consumes one attempt
        and a clean retry can succeed (transient-failure semantics)."""
        from ..resilience import faultsim as _fs

        _fs.check("loader_next", ctx=f"batch#{self._batches_served} {self.path}")
        if self._h is None:
            raise RuntimeError(f"TokenDataLoader({self.path!r}) is closed")
        x = np.empty((self.batch, self.seq_len), np.int32)
        y = np.empty((self.batch, self.seq_len), np.int32)
        rc = self._lib.vdl_next(
            self._h,
            x.ctypes.data_as(ctypes.c_void_p),
            y.ctypes.data_as(ctypes.c_void_p),
        )
        if rc != 0:
            raise RuntimeError(
                f"native loader failed: vdl_next rc={rc} "
                f"(path={self.path!r}, batch_index={self._batches_served})"
            )
        return {"input": x, "target": y}

    def next(self) -> dict:
        # DATA_LOAD span (VERDICT item 7): the one host-side region of the
        # input path — a batch that waits here is a batch the step waited
        # for.  Dormant profiler/telemetry: nullcontext + one branch.
        from ..ndtimeline.api import ndtimeit
        from ..ndtimeline.predefined import DATA_LOAD
        from .. import telemetry as _tel
        from ..resilience.retry import loader_policy

        # closed-loader check BEFORE the retry wrapper: a programming error
        # must fail fast, not burn retries/backoff as if it were transient
        if self._h is None:
            raise RuntimeError(f"TokenDataLoader({self.path!r}) is closed")
        # unconditional stamp (~ns): telemetry flipping on mid-fetch must
        # not observe perf_counter() - 0.0 into the histogram
        t0 = time.perf_counter()
        with ndtimeit(DATA_LOAD):
            out = loader_policy().call(
                self._fetch, description=f"batch#{self._batches_served} of {self.path}"
            )
        self._batches_served += 1
        if _tel.is_active():
            _tel.observe("data_load_seconds", time.perf_counter() - t0)
        return out

    # --------------------------------------------------------- resume state
    def state(self) -> Dict[str, int]:
        """Checkpointable position: batches are a pure function of
        (seed, dp_rank, dp_world, batch index), so the stream is one
        counter plus its identity coords (dp coords are part of the state
        because restoring rank r's counter into rank q's stream would
        silently change the data).

        Elastic mode adds the rank-INVARIANT global cursor
        (``samples_served`` = global rows consumed, ``global_batch`` =
        rows per global step): a resume onto a different
        (dp_world, per-rank batch) split of the SAME global batch re-derives
        its per-rank position from it — no sample skipped or replayed."""
        st = {
            "batches_served": int(self._batches_served),
            "seed": self.seed,
            "dp_rank": self.dp_rank,
            "dp_world": self.dp_world,
            "batch": int(self.batch),
            "seq_len": int(self.seq_len),
            "elastic": int(self.elastic),
        }
        if self.elastic:
            gb = int(self.batch) * int(self.dp_world)
            st["global_batch"] = gb
            st["samples_served"] = int(self._batches_served) * gb
        return st

    def load_state(self, state: Dict[str, int]) -> None:
        """Position the stream so the next ``next()`` returns batch
        ``state['batches_served']`` — sample-exact resume.  Forward moves
        use the native seek (O(1)); backward moves (rollback) reopen the
        file and seek from zero.

        Identity coords must match — a loader built for different dp
        coords / shape is a DIFFERENT stream — EXCEPT when both sides are
        elastic: then the split (``dp_rank``/``dp_world``/``batch``) may
        change freely and the position is re-derived from the global cursor
        (``samples_served // global_batch``), provided seed, seq_len and
        the global batch are preserved (a changed global batch cannot be
        re-split sample-exactly: VSC133)."""
        resplit = (
            self.elastic
            and bool(state.get("elastic"))
            and "samples_served" in state
            and any(
                int(state.get(k, getattr(self, k))) != int(getattr(self, k))
                for k in ("dp_rank", "dp_world", "batch")
            )
        )
        if resplit:
            for key in ("seed", "seq_len"):
                if key in state and int(state[key]) != int(getattr(self, key)):
                    raise ValueError(
                        f"loader state mismatch on {key!r}: checkpoint has "
                        f"{state[key]}, this loader has {getattr(self, key)} — "
                        "resuming would silently change the data stream"
                    )
            gb = int(self.batch) * int(self.dp_world)
            saved_gb = int(state.get("global_batch", -1))
            if saved_gb != gb:
                raise ValueError(
                    f"[VSC133] loader position cannot be re-split: checkpoint "
                    f"global batch is {saved_gb} rows, this run's is {gb} — an "
                    "elastic resume must preserve batch*dp_world (change the "
                    "per-rank batch, not the global one)"
                )
            target = int(state["samples_served"]) // gb
        else:
            # "elastic" is an identity coord too: the two modes key samples
            # differently, so a state crossing the mode boundary would
            # silently switch the stream even at identical dp coords
            for key in ("seed", "dp_rank", "dp_world", "batch", "seq_len", "elastic"):
                if key in state and int(state[key]) != int(getattr(self, key)):
                    raise ValueError(
                        f"loader state mismatch on {key!r}: checkpoint has "
                        f"{state[key]}, this loader has {int(getattr(self, key))} — "
                        "resuming would silently change the data stream"
                        + (
                            " (enable elastic=True on BOTH runs to re-split "
                            "across a world-size change)"
                            if key in ("dp_rank", "dp_world", "batch")
                            else ""
                        )
                    )
            target = int(state["batches_served"])
        if self._h is None:
            raise RuntimeError(f"TokenDataLoader({self.path!r}) is closed")
        if target < self._batches_served:
            # prefetch cannot run backwards: reopen, then seek forward
            with self._close_lock:
                h, self._h = self._h, None
            if h:
                self._lib.vdl_close(h)
            self._h = self._open_native()
            self._batches_served = 0
        if target > self._batches_served:
            self._seek(target)
        self._batches_served = target

    def _seek(self, target: int) -> None:
        # vdl_seek always exists: _lib() enforces the ABI version, and every
        # ABI >= 1 exports it (the pre-seek drain-and-discard fallback died
        # with the ABI-versioned .so name)
        rc = self._lib.vdl_seek(self._h, target)
        if rc != 0:
            raise RuntimeError(
                f"native loader seek to {target} failed: rc={rc} (path={self.path!r})"
            )

    def __iter__(self):
        while True:
            yield self.next()

    def close(self) -> None:
        # pop the handle under the lock so concurrent close()/close() (or
        # close racing __del__ at shutdown) frees it exactly once; getattr
        # guards a __del__ after a failed __init__
        lock = getattr(self, "_close_lock", None)
        if lock is None:
            return
        with lock:
            h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.vdl_close(h)

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
