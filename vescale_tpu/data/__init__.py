from .loader import TokenDataLoader, build_native
