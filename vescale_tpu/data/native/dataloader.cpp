// vescale_tpu native data loader.
//
// Role parity: the reference's training input pipeline (nanoGPT-style
// get_batch over binary token files, legacy/examples/*/data loading) — here
// implemented natively so tokenization-adjacent host work never blocks the
// TPU step: an mmap'd token file is sampled into a ring of pinned batch
// buffers by background prefetch threads; Python (ctypes) just hands out
// filled buffers.
//
// C API (see data/loader.py):
//   vdl_open(path, token_bytes, seq_len, batch, seed, rank, world, nprefetch,
//            elastic)
//   vdl_next(handle, x_out, y_out)   -> blocks until a batch is ready
//   vdl_seek(handle, index)          -> forward-seek the serve cursor
//   vdl_num_tokens(handle)
//   vdl_abi_version()                -> bumped on any signature change so a
//                                       stale prebuilt .so forces a rebuild
//   vdl_close(handle)
//
// Sampling, elastic == 0 (historical default): deterministic per
// (seed, rank, batch_index) via SplitMix64 — rank r of `world` draws from a
// disjoint start-offset partition of the file, so DP ranks see different
// data while runs are reproducible.  x = tokens[i : i+seq_len],
// y = tokens[i+1 : i+seq_len+1] (next-token targets).
//
// Sampling, elastic == 1: every sample is keyed on its GLOBAL row index
//   g = batch_index * (batch * world) + rank * batch + row
// over the FULL span — the global token stream is a pure function of
// (seed, g), invariant to how (world, per-rank batch) split a fixed global
// batch.  This is what makes a checkpoint resumable on a different world
// size with no sample skipped or replayed (elastic world-size resume);
// rank r still serves the contiguous global-batch slice [r*batch,(r+1)*batch).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t state;
  explicit SplitMix64(uint64_t seed) : state(seed) {}
  uint64_t next() {
    uint64_t z = (state += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }
};

struct Batch {
  std::vector<int32_t> x;
  std::vector<int32_t> y;
};

struct Loader {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t file_bytes = 0;
  int token_bytes = 2;  // uint16 or 4 for uint32/int32
  size_t num_tokens = 0;
  int64_t seq_len = 0;
  int64_t batch = 0;
  uint64_t seed = 0;
  int64_t rank = 0, world = 1;
  int elastic = 0;  // world-invariant global-row sampling (header comment)
  std::atomic<uint64_t> batch_counter{0};

  // prefetch ring, served strictly in batch-index order so multi-threaded
  // prefetch stays deterministic
  std::map<uint64_t, Batch> ready;
  uint64_t next_serve = 0;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  size_t max_ready = 4;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};

  int32_t token_at(size_t i) const {
    if (token_bytes == 2) {
      uint16_t v;
      std::memcpy(&v, map + i * 2, 2);
      return static_cast<int32_t>(v);
    }
    int32_t v;
    std::memcpy(&v, map + i * 4, 4);
    return v;
  }

  void fill(Batch& b, uint64_t index) {
    b.x.resize(batch * seq_len);
    b.y.resize(batch * seq_len);
    size_t full_span = num_tokens - (size_t)seq_len - 1;
    if (elastic) {
      // world-invariant: sample g = global row index over the FULL span —
      // any (world, per-rank batch) factorization of the same global batch
      // reproduces the identical global token stream (elastic resume)
      for (int64_t row = 0; row < batch; ++row) {
        uint64_t g = index * (uint64_t)(batch * world) +
                     (uint64_t)rank * (uint64_t)batch + (uint64_t)row;
        SplitMix64 rng(seed * 0x9E3779B97F4A7C15ull + g * 0xD1B54A32D192ED03ull);
        size_t start = (size_t)(rng.next() % full_span);
        for (int64_t t = 0; t < seq_len; ++t) {
          b.x[row * seq_len + t] = token_at(start + t);
          b.y[row * seq_len + t] = token_at(start + t + 1);
        }
      }
      return;
    }
    // deterministic per (seed, rank, batch index); ranks draw from DISJOINT
    // start-offset partitions of the file so dp shards never overlap
    size_t rank_span = full_span / (size_t)world;
    size_t rank_base = (size_t)rank * rank_span;
    if (rank_span == 0) {  // degenerate tiny file: fall back to shared span
      rank_span = full_span;
      rank_base = 0;
    }
    for (int64_t row = 0; row < batch; ++row) {
      SplitMix64 rng(seed * 0x9E3779B97F4A7C15ull + (uint64_t)rank * 0x85EBCA77C2B2AE63ull +
                     index * 1000003ull + (uint64_t)row);
      size_t start = rank_base + (size_t)(rng.next() % rank_span);
      for (int64_t t = 0; t < seq_len; ++t) {
        b.x[row * seq_len + t] = token_at(start + t);
        b.y[row * seq_len + t] = token_at(start + t + 1);
      }
    }
  }

  void worker_loop() {
    while (!stop.load()) {
      // wait for space BEFORE claiming an index: a worker that claimed the
      // next-to-serve index must never block behind later batches (deadlock)
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_space.wait(lk, [&] { return ready.size() < max_ready || stop.load(); });
      }
      if (stop.load()) return;
      uint64_t index = batch_counter.fetch_add(1);
      Batch b;
      fill(b, index);
      std::unique_lock<std::mutex> lk(mu);
      if (stop.load()) return;
      // insert unless a seek already moved the cursor past this index (a
      // stale batch would pile up in `ready` forever); ready may briefly
      // exceed max_ready by up to the worker count, which is bounded and
      // preserves in-order serving
      if (index >= next_serve) {
        ready.emplace(index, std::move(b));
        cv_ready.notify_all();
      }
    }
  }
};

}  // namespace

extern "C" {

// bumped on any C-API signature change: the Python side refuses (and
// rebuilds) a stale prebuilt .so instead of calling through a mismatched
// ABI, where an extra trailing argument would be SILENTLY ignored
int vdl_abi_version() { return 2; }

void* vdl_open(const char* path, int token_bytes, int64_t seq_len, int64_t batch,
               uint64_t seed, int64_t rank, int64_t world, int n_prefetch,
               int elastic) {
  auto* L = new Loader();
  L->token_bytes = token_bytes;
  L->seq_len = seq_len;
  L->batch = batch;
  L->seed = seed;
  L->rank = rank;
  L->world = world <= 0 ? 1 : world;
  L->elastic = elastic != 0 ? 1 : 0;
  L->fd = ::open(path, O_RDONLY);
  if (L->fd < 0) {
    delete L;
    return nullptr;
  }
  struct stat st;
  if (fstat(L->fd, &st) != 0) {
    ::close(L->fd);
    delete L;
    return nullptr;
  }
  L->file_bytes = (size_t)st.st_size;
  L->num_tokens = L->file_bytes / (size_t)token_bytes;
  if ((int64_t)L->num_tokens <= seq_len + 1) {
    ::close(L->fd);
    delete L;
    return nullptr;
  }
  L->map = (const uint8_t*)::mmap(nullptr, L->file_bytes, PROT_READ, MAP_PRIVATE, L->fd, 0);
  if (L->map == MAP_FAILED) {
    ::close(L->fd);
    delete L;
    return nullptr;
  }
  ::madvise((void*)L->map, L->file_bytes, MADV_RANDOM);
  int n = n_prefetch <= 0 ? 2 : n_prefetch;
  L->max_ready = (size_t)n * 2;
  for (int i = 0; i < n; ++i) L->workers.emplace_back([L] { L->worker_loop(); });
  return L;
}

int64_t vdl_num_tokens(void* handle) {
  return handle ? (int64_t)((Loader*)handle)->num_tokens : -1;
}

int vdl_next(void* handle, int32_t* x_out, int32_t* y_out) {
  if (!handle) return -1;
  auto* L = (Loader*)handle;
  Batch b;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_ready.wait(lk, [&] { return L->ready.count(L->next_serve) > 0; });
    auto it = L->ready.find(L->next_serve);
    b = std::move(it->second);
    L->ready.erase(it);
    ++L->next_serve;
    L->cv_space.notify_all();
  }
  std::memcpy(x_out, b.x.data(), b.x.size() * sizeof(int32_t));
  std::memcpy(y_out, b.y.data(), b.y.size() * sizeof(int32_t));
  return 0;
}

int vdl_seek(void* handle, uint64_t target) {
  // Forward-seek the serve cursor to batch `target` (resume fast-forward:
  // batches are generated independently per index, so skipping is O(1) —
  // no fill work is owed for the skipped range).  Backward seeks are
  // rejected; the Python side reopens the loader instead (prefetch state
  // cannot be rewound).
  if (!handle) return -1;
  auto* L = (Loader*)handle;
  std::unique_lock<std::mutex> lk(L->mu);
  if (target < L->next_serve) return -2;
  // drop prefetched batches the seek skips over
  for (auto it = L->ready.begin(); it != L->ready.end();) {
    if (it->first < target)
      it = L->ready.erase(it);
    else
      ++it;
  }
  L->next_serve = target;
  // advance the claim counter so workers start filling from `target`; a
  // worker mid-fill on a stale index is handled by the insert guard above
  uint64_t cur = L->batch_counter.load();
  while (cur < target && !L->batch_counter.compare_exchange_weak(cur, target)) {
  }
  L->cv_space.notify_all();
  return 0;
}

void vdl_close(void* handle) {
  if (!handle) return;
  auto* L = (Loader*)handle;
  {
    // hold the mutex while flipping stop: a worker between predicate check
    // and blocking would otherwise miss the wakeup and hang join() forever
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
  }
  L->cv_space.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers)
    if (t.joinable()) t.join();
  if (L->map && L->map != MAP_FAILED) ::munmap((void*)L->map, L->file_bytes);
  if (L->fd >= 0) ::close(L->fd);
  delete L;
}

}  // extern "C"
