"""Deferred init — materialize only the local shard, no fake-tensor C++.

Capability parity with the reference deferred_init
(legacy/vescale/initialize/deferred_init.py:38,98,182), which needs the
patched torchdistX ``materializeWithShape`` (C++) to record factory ops on
fake tensors and replay them at local-shard shape.

TPU-native: ``jax.eval_shape`` IS deferred init (tracing produces shape-only
avals with zero FLOPs/bytes), and ``jax.jit`` with ``out_shardings``
materializes each param directly as its shard on its devices — the replay
with a different shape is XLA partitioning the initializer (SURVEY §2.2).
"""

from __future__ import annotations

from typing import Any, Callable

import jax

from ..darray import DArray, _apply_sharding
from ..mesh import DeviceMesh
from ..placements import normalize_placements
from ..spec import DArraySpec, TensorMeta

__all__ = [
    "deferred_init",
    "is_deferred",
    "materialize_dtensor",
    "materialize_dparameter",
    "materialize_module",
]


def deferred_init(fn: Callable, *args, **kwargs):
    """Trace ``fn`` (e.g. ``module.init`` or a factory) into a
    ShapeDtypeStruct pytree — nothing is allocated (reference
    deferred_init:38)."""
    return jax.eval_shape(fn, *args, **kwargs)


def is_deferred(tree) -> bool:
    leaves = jax.tree_util.tree_leaves(tree)
    return bool(leaves) and all(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)


def materialize_dtensor(fn: Callable, mesh: DeviceMesh, placements, *args, **kwargs) -> DArray:
    """Run the deferred factory sharded: only the local shard of the result
    is computed/stored per device (reference materialize_dtensor:98)."""
    aval = jax.eval_shape(fn, *args, **kwargs)
    spec = DArraySpec(
        mesh,
        normalize_placements(placements, mesh.ndim, len(aval.shape)),
        TensorMeta(tuple(aval.shape), aval.dtype),
    )
    out_sharding = spec.named_sharding()
    # pack inside jit so the physical layout is produced under the sharding
    packed = jax.jit(lambda *a, **k: spec.pack(fn(*a, **k)), out_shardings=out_sharding)(
        *args, **kwargs
    )
    return DArray(packed, spec)


def materialize_dparameter(fn: Callable, mesh: DeviceMesh, placements, *args, **kwargs) -> DArray:
    """(reference materialize_dparameter:182) — param flavor of the above."""
    return materialize_dtensor(fn, mesh, placements, *args, **kwargs)


def materialize_module(init_fn: Callable, shardings, *args, **kwargs):
    """Materialize a whole deferred module init with a shardings pytree
    (what DModule.init uses; exposed for parity with the module-level
    deferred-init flow)."""
    return jax.jit(init_fn, out_shardings=shardings)(*args, **kwargs)
