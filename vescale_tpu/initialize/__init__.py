from .deferred_init import deferred_init, is_deferred, materialize_dtensor, materialize_dparameter, materialize_module
