"""Per-shard redistribute kernels — scale-safe placement transitions.

The reference's redistribute (legacy/vescale/dtensor/redistribute.py:223)
walks a per-pair transition table issuing NCCL collectives on *local*
tensors.  This is the TPU-native equivalent: a cached, jit-compiled
``shard_map`` program in which every rank touches only its own shard and the
collectives are XLA ops over mesh axis names:

  Partial -> Replicate        psum / pmax / pmin / pmean
  Partial(sum) -> Shard(d)    psum_scatter (reduce-scatter)
  Shard(d) -> Replicate       all_gather (tiled) + unpad
  Shard(d) -> Shard(d')       all_to_all  (pad / unpad at the edges)
  Replicate -> Shard(d)       local dynamic-slice of the own chunk
  Replicate -> Partial        seed (slot-0 keeps the value for "sum")

No logical-size allocation happens on any device unless the *destination*
itself is logical-size (→ Replicate), fixing round-1's
``unpack -> pack`` global materialization (VERDICT weak #5).

Coverage: same-mesh transitions where each tensor axis is sharded by at most
one mesh dim on each side and each tensor axis participates in at most one
transition.  Everything else (ragged, interleaved, cross-mesh, nested
shards, axis collisions) falls back to the pack/unpack path compiled under
jit — correct, but may materialize the logical value.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .collectives import shard_map
from .placements import Partial, Replicate, Shard
from .spec import DArraySpec

__all__ = ["transition_fn", "fallback_fn"]


def _single_shard_map(spec: DArraySpec) -> Optional[Dict[int, int]]:
    """{tensor_dim: mesh_dim} when every Shard-ed tensor axis has exactly one
    mesh dim; None for nested sharding."""
    m: Dict[int, int] = {}
    for i, p in enumerate(spec.placements):
        if type(p) is Shard:
            if p.dim in m:
                return None
            m[p.dim] = i
    return m


def _plan_ops(src: DArraySpec, dst: DArraySpec) -> Optional[List[Tuple]]:
    """Static transition plan, or None if this pair needs the fallback."""
    if src.mesh != dst.mesh:
        return None
    for s in (src, dst):
        if s.has_ragged() or s.layout().interleaves:
            return None
    smap, dmap = _single_shard_map(src), _single_shard_map(dst)
    if smap is None or dmap is None:
        return None

    reduces: List[Tuple] = []
    gathers: List[Tuple] = []
    moves: List[Tuple] = []
    finals: List[Tuple] = []   # reduce_scatter / slice
    seeds: List[Tuple] = []
    changed_axes: set = set()

    for i in range(src.mesh.ndim):
        sp, dp = src.placements[i], dst.placements[i]
        if sp == dp:
            continue
        if isinstance(sp, Partial):
            if isinstance(dp, Replicate):
                reduces.append(("reduce", i, sp.reduce_op))
            elif type(dp) is Shard:
                finals.append(("reduce_scatter", i, sp.reduce_op, dp.dim))
                changed_axes.add(dp.dim)
            else:
                return None  # Partial -> Partial with different op
        elif type(sp) is Shard:
            if isinstance(dp, Replicate):
                gathers.append(("gather", i, sp.dim))
                changed_axes.add(sp.dim)
            elif type(dp) is Shard:
                moves.append(("move", i, sp.dim, dp.dim))
                changed_axes.update((sp.dim, dp.dim))
            else:
                return None  # Shard -> Partial has no meaning
        elif isinstance(sp, Replicate):
            if type(dp) is Shard:
                finals.append(("slice", i, dp.dim))
                changed_axes.add(dp.dim)
            elif isinstance(dp, Partial):
                seeds.append(("seed", i, dp.reduce_op))
            else:
                return None
        else:
            return None

    # an axis that keeps the same mesh dim on both sides must not change
    # extent mid-flight via another op
    for d, i in smap.items():
        if dmap.get(d) == i and d in changed_axes:
            return None

    # order: reduces -> gathers (restore full extents) -> moves (topo-sorted:
    # a move needs its split axis full, which another move's concat restores)
    # -> scatters/slices -> seeds
    ordered_moves: List[Tuple] = []
    pending = list(moves)
    while pending:
        progress = False
        for mv in list(pending):
            _, _i, d, d2 = mv
            # d2 must be full: no remaining move still has d2 as its src axis
            if not any(o is not mv and o[2] == d2 for o in pending):
                ordered_moves.append(mv)
                pending.remove(mv)
                progress = True
        if not progress:
            return None  # axis-swap cycle: needs the fallback
    return reduces + gathers + ordered_moves + finals + seeds


def _chunk_of(spec: DArraySpec, tensor_dim: int) -> int:
    # body axis == tensor dim on the fast path (no interleaves)
    return spec.layout().body_axes[tensor_dim].chunk


def _pad_to(x, d: int, size: int):
    if x.shape[d] == size:
        return x
    pads = [(0, 0)] * x.ndim
    pads[d] = (0, size - x.shape[d])
    return jnp.pad(x, pads)


def _trim_to(x, d: int, size: int):
    if x.shape[d] == size:
        return x
    return jax.lax.slice_in_dim(x, 0, size, axis=d)


@functools.lru_cache(maxsize=256)
def transition_fn(src: DArraySpec, dst: DArraySpec):
    """A compiled ``physical(src) -> physical(dst)`` transition running
    per-shard collectives, or None when the pair needs the pack/unpack
    fallback."""
    ops = _plan_ops(src, dst)
    if ops is None:
        return None

    mesh = src.mesh
    name = mesh.dim_name
    src_lead = src.layout().partial_mesh_dims   # ascending
    dst_lead = dst.layout().partial_mesh_dims
    ext = dict(enumerate(src.shape))            # logical extents by tensor dim

    def worker(x):
        # local view: lead partial axes are size-1 — drop them
        if src_lead:
            x = jnp.squeeze(x, axis=tuple(range(len(src_lead))))
        for op in ops:
            kind = op[0]
            if kind == "reduce":
                _, i, rop = op
                red = {"sum": jax.lax.psum, "avg": jax.lax.pmean,
                       "max": jax.lax.pmax, "min": jax.lax.pmin}[rop]
                x = red(x, name(i))
            elif kind == "reduce_scatter":
                _, i, rop, d = op
                n = mesh.shape[i]
                chunk = _chunk_of(dst, d)
                x = _pad_to(x, d, chunk * n)
                if rop in ("sum", "avg"):
                    x = jax.lax.psum_scatter(x, name(i), scatter_dimension=d, tiled=True)
                    if rop == "avg":
                        x = x / n
                else:  # max/min have no scatter primitive: reduce then slice
                    red = jax.lax.pmax if rop == "max" else jax.lax.pmin
                    x = red(x, name(i))
                    idx = jax.lax.axis_index(name(i))
                    x = jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=d)
            elif kind == "gather":
                _, i, d = op
                x = jax.lax.all_gather(x, name(i), axis=d, tiled=True)
                x = _trim_to(x, d, ext[d])
            elif kind == "move":
                _, i, d, d2 = op
                n = mesh.shape[i]
                x = _pad_to(x, d2, _chunk_of(dst, d2) * n)
                x = jax.lax.all_to_all(x, name(i), split_axis=d2, concat_axis=d, tiled=True)
                x = _trim_to(x, d, ext[d])
            elif kind == "slice":
                _, i, d = op
                n = mesh.shape[i]
                chunk = _chunk_of(dst, d)
                x = _pad_to(x, d, chunk * n)
                idx = jax.lax.axis_index(name(i))
                x = jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=d)
            elif kind == "seed":
                _, i, rop = op
                if rop == "sum":
                    idx = jax.lax.axis_index(name(i))
                    x = jnp.where(idx == 0, x, jnp.zeros_like(x))
                # avg/max/min: every slot holds the value — reduction
                # reproduces it (reference pack semantics)
        if dst_lead:
            x = jnp.expand_dims(x, axis=tuple(range(len(dst_lead))))
        return x

    fn = shard_map(
        worker,
        mesh=mesh.jax_mesh,
        in_specs=(src.layout().pspec,),
        out_specs=dst.layout().pspec,
        check_vma=False,
        axis_names=frozenset(mesh.mesh_dim_names),
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def fallback_fn(src: DArraySpec, dst: DArraySpec):
    """pack(unpack(.)) compiled under jit with the destination sharding —
    correct for every pair (ragged, interleaved, nested); the logical
    intermediate may materialize (use only off the fast path)."""

    def go(phys):
        return dst.pack(src.unpack(phys))

    if src.mesh == dst.mesh:
        return jax.jit(go, out_shardings=dst.named_sharding())
    return go  # cross-mesh: device sets differ; stay eager
