"""Per-shard redistribute kernels — scale-safe placement transitions.

The reference's redistribute (legacy/vescale/dtensor/redistribute.py:223)
walks a per-pair transition table issuing NCCL collectives on *local*
tensors.  This is the TPU-native equivalent: a cached, jit-compiled
``shard_map`` program in which every rank touches only its own shard and the
collectives are XLA ops over mesh axis names:

  Partial -> Replicate        psum / pmax / pmin / pmean
  Partial(sum) -> Shard(d)    psum_scatter (reduce-scatter)
  Shard(d) -> Replicate       all_gather (tiled) + unpad
  Shard(d) -> Shard(d')       all_to_all  (pad / unpad at the edges)
  Replicate -> Shard(d)       local dynamic-slice of the own chunk
  Replicate -> Partial        seed (slot-0 keeps the value for "sum")

No logical-size allocation happens on any device unless the *destination*
itself is logical-size (→ Replicate), fixing round-1's
``unpack -> pack`` global materialization (VERDICT weak #5).

Ragged transitions (round 4, VERDICT r3 next #4) get their own per-shard
kernels — the reference's variable-size collectives
(vescale/dtensor/placement_types.py:128 all-gather-v, :152 all-to-all-v):

  Ragged -> Replicate         all-gather-v (gather padded cells + static
                              reassembly — dst is logical-size by definition;
                              plain AND strided)
  Replicate -> Ragged         local slice-v (no comm; O(cell) output;
                              plain AND strided)
  Ragged -> Ragged'           all-to-all-v (static exchange plan over the
                              ragged mesh dim; peak per-device bytes
                              O(max shard), never the logical size)
  StridedRagged -> StridedRagged'  all-to-all-v over the combined
                              (inner, rj) flat rank (fsdp x ep reallocation
                              under a composing tp Shard)
  plain <-> strided ragged    same plan; the plain side replicates its
                              cell over the inner dim (per-expert
                              TP-degree changes)

Coverage: same-mesh transitions where each tensor axis is sharded by at most
one mesh dim on each side and each tensor axis participates in at most one
transition, plus the ragged pairs above.  Everything else (interleaved,
cross-mesh, nested shards, axis collisions, differing inner dims) falls
back to the pack/unpack path compiled under jit — correct, but may
materialize the logical value.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .collectives import shard_map
from .placements import Partial, Replicate, Shard
from .spec import DArraySpec

__all__ = [
    "transition_fn",
    "fallback_fn",
    "ragged_transition_fn",
    "interleaved_transition_fn",
    "quant_plan_info",
    "quant_transition_fn",
]


def _single_shard_map(spec: DArraySpec) -> Optional[Dict[int, int]]:
    """{tensor_dim: mesh_dim} when every Shard-ed tensor axis has exactly one
    mesh dim; None for nested sharding."""
    m: Dict[int, int] = {}
    for i, p in enumerate(spec.placements):
        if type(p) is Shard:
            if p.dim in m:
                return None
            m[p.dim] = i
    return m


def _plan_ops(src: DArraySpec, dst: DArraySpec) -> Optional[List[Tuple]]:
    """Static transition plan, or None if this pair needs the fallback."""
    if src.mesh != dst.mesh:
        return None
    for s in (src, dst):
        if s.has_ragged() or s.layout().interleaves:
            return None
    smap, dmap = _single_shard_map(src), _single_shard_map(dst)
    if smap is None or dmap is None:
        return None

    reduces: List[Tuple] = []
    gathers: List[Tuple] = []
    moves: List[Tuple] = []
    finals: List[Tuple] = []   # reduce_scatter / slice
    seeds: List[Tuple] = []
    changed_axes: set = set()

    for i in range(src.mesh.ndim):
        sp, dp = src.placements[i], dst.placements[i]
        if sp == dp:
            continue
        if isinstance(sp, Partial):
            if isinstance(dp, Replicate):
                reduces.append(("reduce", i, sp.reduce_op))
            elif type(dp) is Shard:
                finals.append(("reduce_scatter", i, sp.reduce_op, dp.dim))
                changed_axes.add(dp.dim)
            else:
                return None  # Partial -> Partial with different op
        elif type(sp) is Shard:
            if isinstance(dp, Replicate):
                gathers.append(("gather", i, sp.dim))
                changed_axes.add(sp.dim)
            elif type(dp) is Shard:
                moves.append(("move", i, sp.dim, dp.dim))
                changed_axes.update((sp.dim, dp.dim))
            else:
                return None  # Shard -> Partial has no meaning
        elif isinstance(sp, Replicate):
            if type(dp) is Shard:
                finals.append(("slice", i, dp.dim))
                changed_axes.add(dp.dim)
            elif isinstance(dp, Partial):
                seeds.append(("seed", i, dp.reduce_op))
            else:
                return None
        else:
            return None

    # an axis that keeps the same mesh dim on both sides must not change
    # extent mid-flight via another op
    for d, i in smap.items():
        if dmap.get(d) == i and d in changed_axes:
            return None

    # order: reduces -> gathers (restore full extents) -> moves (topo-sorted:
    # a move needs its split axis full, which another move's concat restores)
    # -> scatters/slices -> seeds
    ordered_moves: List[Tuple] = []
    pending = list(moves)
    while pending:
        progress = False
        for mv in list(pending):
            _, _i, d, d2 = mv
            # d2 must be full: no remaining move still has d2 as its src axis
            if not any(o is not mv and o[2] == d2 for o in pending):
                ordered_moves.append(mv)
                pending.remove(mv)
                progress = True
        if not progress:
            return None  # axis-swap cycle: needs the fallback
    return reduces + gathers + ordered_moves + finals + seeds


def _chunk_of(spec: DArraySpec, tensor_dim: int) -> int:
    # body axis == tensor dim on the fast path (no interleaves)
    return spec.layout().body_axes[tensor_dim].chunk


def _pad_to(x, d: int, size: int):
    if x.shape[d] == size:
        return x
    pads = [(0, 0)] * x.ndim
    pads[d] = (0, size - x.shape[d])
    return jnp.pad(x, pads)


def _trim_to(x, d: int, size: int):
    if x.shape[d] == size:
        return x
    return jax.lax.slice_in_dim(x, 0, size, axis=d)


@functools.lru_cache(maxsize=256)
def transition_fn(src: DArraySpec, dst: DArraySpec):
    """A compiled ``physical(src) -> physical(dst)`` transition running
    per-shard collectives, or None when the pair needs the pack/unpack
    fallback."""
    ops = _plan_ops(src, dst)
    if ops is None:
        return None

    mesh = src.mesh
    name = mesh.dim_name
    src_lead = src.layout().partial_mesh_dims   # ascending
    dst_lead = dst.layout().partial_mesh_dims
    ext = dict(enumerate(src.shape))            # logical extents by tensor dim

    def worker(x):
        # local view: lead partial axes are size-1 — drop them
        if src_lead:
            x = jnp.squeeze(x, axis=tuple(range(len(src_lead))))
        for op in ops:
            kind = op[0]
            if kind == "reduce":
                _, i, rop = op
                red = {"sum": jax.lax.psum, "avg": jax.lax.pmean,
                       "max": jax.lax.pmax, "min": jax.lax.pmin}[rop]
                x = red(x, name(i))
            elif kind == "reduce_scatter":
                _, i, rop, d = op
                n = mesh.shape[i]
                chunk = _chunk_of(dst, d)
                x = _pad_to(x, d, chunk * n)
                if rop in ("sum", "avg"):
                    x = jax.lax.psum_scatter(x, name(i), scatter_dimension=d, tiled=True)
                    if rop == "avg":
                        x = x / n
                else:  # max/min have no scatter primitive: reduce then slice
                    red = jax.lax.pmax if rop == "max" else jax.lax.pmin
                    x = red(x, name(i))
                    idx = jax.lax.axis_index(name(i))
                    x = jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=d)
            elif kind == "gather":
                _, i, d = op
                x = jax.lax.all_gather(x, name(i), axis=d, tiled=True)
                x = _trim_to(x, d, ext[d])
            elif kind == "move":
                _, i, d, d2 = op
                n = mesh.shape[i]
                x = _pad_to(x, d2, _chunk_of(dst, d2) * n)
                x = jax.lax.all_to_all(x, name(i), split_axis=d2, concat_axis=d, tiled=True)
                x = _trim_to(x, d, ext[d])
            elif kind == "slice":
                _, i, d = op
                n = mesh.shape[i]
                chunk = _chunk_of(dst, d)
                x = _pad_to(x, d, chunk * n)
                idx = jax.lax.axis_index(name(i))
                x = jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=d)
            elif kind == "seed":
                _, i, rop = op
                if rop == "sum":
                    idx = jax.lax.axis_index(name(i))
                    x = jnp.where(idx == 0, x, jnp.zeros_like(x))
                # avg/max/min: every slot holds the value — reduction
                # reproduces it (reference pack semantics)
        if dst_lead:
            x = jnp.expand_dims(x, axis=tuple(range(len(dst_lead))))
        return x

    fn = shard_map(
        worker,
        mesh=mesh.jax_mesh,
        in_specs=(src.layout().pspec,),
        out_specs=dst.layout().pspec,
        check_vma=False,
        axis_names=frozenset(mesh.mesh_dim_names),
    )
    return jax.jit(fn)


# ------------------------------------------------------- ragged kernels
def _any_ragged(spec: DArraySpec) -> Optional[Tuple[int, Optional[int]]]:
    """(ragged mesh dim, inner-shard mesh dim or None) for plain OR strided
    ragged specs whose remaining dims are Replicate; None otherwise."""
    lay = spec.layout()
    if lay.ragged is None:
        return None
    return lay.ragged[0], lay.ragged_inner_shard


def _ragged_sizes_offsets(spec: DArraySpec, rj: int):
    rp = spec.placements[rj]
    total = 1
    for s in spec.shape:
        total *= s
    sizes, offs = rp.local_sizes_and_offsets(total)
    return list(sizes), list(offs), total


@functools.lru_cache(maxsize=256)
def ragged_transition_fn(src: DArraySpec, dst: DArraySpec):
    """Per-shard kernel for ragged placement transitions, or None when the
    pair needs the generic fallback.  All cell sizes/offsets are static at
    trace time (they live in the placements), so the "variable-size"
    collectives compile to fixed-size XLA collectives + masks:

      ragged -> replicate : all_gather of padded cells, static reassembly
                            (reference all-gather-v, placement_types.py:128)
      replicate -> ragged : local dynamic-slice of the own cell (scatter-v
                            locality without communication)
      ragged -> ragged'   : all_to_all of a static (n, Emax) exchange plan
                            (reference all-to-all-v, placement_types.py:152);
                            Emax = the largest pairwise overlap, so no device
                            ever holds a logical-size buffer
    """
    import numpy as np

    if src.mesh != dst.mesh or src.shape != dst.shape:
        return None
    mesh = src.mesh

    # ---- ragged (plain OR strided) -> replicate (all-gather-v)
    src_any = _any_ragged(src)
    if src_any is not None and dst.is_replicated():
        rj, inner = src_any
        lay = src.layout()
        rj_name = mesh.dim_name(rj)
        # gather over (inner, rj) — outermost-first, matching the physical
        # block order a*nj + r of the strided-ragged layout
        ax = (mesh.dim_name(inner), rj_name) if inner is not None else rj_name

        def worker(x):
            # gathered g is exactly the full physical flat buffer; the
            # spec's own unpack owns the block-order reassembly math
            g = jax.lax.all_gather(x, ax, axis=0, tiled=True)  # (s*nj*cell_pad,)
            return src._unpack_ragged(g)

        fn = shard_map(
            worker,
            mesh=mesh.jax_mesh,
            in_specs=(lay.pspec,),
            out_specs=dst.layout().pspec,
            check_vma=False,
            axis_names=frozenset(mesh.mesh_dim_names),
        )
        return jax.jit(fn)

    # ---- replicate -> ragged (plain OR strided) (slice-v; no communication)
    dst_any = _any_ragged(dst)
    if src.is_replicated() and dst_any is not None:
        rj, inner = dst_any
        dlay = dst.layout()
        cell_pad = dlay.cell_pad
        sizes, offs, total = _ragged_sizes_offsets(dst, rj)
        s = mesh.shape[inner] if inner is not None else 1
        rj_name = mesh.dim_name(rj)
        sizes_arr = np.asarray(sizes, np.int32)
        offs_arr = np.asarray(offs, np.int32)

        def worker(x):
            flat = jnp.ravel(x)
            flatp = jnp.concatenate([flat, jnp.zeros((cell_pad,), flat.dtype)])
            r = jax.lax.axis_index(rj_name)
            a = jax.lax.axis_index(mesh.dim_name(inner)) if inner is not None else 0
            cell = jnp.asarray(sizes_arr)[r] // s
            piece = jax.lax.dynamic_slice(flatp, (jnp.asarray(offs_arr)[r] + a * cell,), (cell_pad,))
            return jnp.where(jnp.arange(cell_pad) < cell, piece, jnp.zeros_like(piece))

        fn = shard_map(
            worker,
            mesh=mesh.jax_mesh,
            in_specs=(src.layout().pspec,),
            out_specs=dlay.pspec,
            check_vma=False,
            axis_names=frozenset(mesh.mesh_dim_names),
        )
        return jax.jit(fn)

    # ---- ragged -> ragged' (all-to-all-v), plain OR strided with the
    # same inner dim: the fsdp/MoE reallocation transitions.  Device
    # (a, r) — a = inner coord (0 when plain) — owns the global flat
    # interval [offs[r] + a*cell_r, +cell_r); the exchange plan is computed
    # over the combined flat rank rho = a*nj + r and executed as one
    # ppermute round per active ring offset (delta), each sized to the
    # LARGEST exchange at that delta.  Similar splits exchange only with
    # ring neighbours (deltas {0, +-1}, lengths O(cell)); a rank holding
    # most of the buffer talks to everyone but already owns O(total)
    # itself — peak per-device bytes stay O(max shard), unlike an
    # (n, Emax) all_to_all plan which is O(n * max overlap).
    src_any2, dst_any2 = _any_ragged(src), _any_ragged(dst)
    if (
        src_any2 is not None
        and dst_any2 is not None
        and src_any2[0] == dst_any2[0]
        # inner dims must agree when BOTH sides are strided; a plain side
        # simply replicates over the other side's inner dim
        and (src_any2[1] is None or dst_any2[1] is None or src_any2[1] == dst_any2[1])
    ):
        rj = src_any2[0]
        inner = src_any2[1] if src_any2[1] is not None else dst_any2[1]
        nj = mesh.shape[rj]
        s = mesh.shape[inner] if inner is not None else 1
        n = s * nj
        slay, dlay = src.layout(), dst.layout()
        s_sizes, s_offs, total = _ragged_sizes_offsets(src, rj)
        d_sizes, d_offs, _ = _ragged_sizes_offsets(dst, rj)
        src_strided = src_any2[1] is not None
        dst_strided = dst_any2[1] is not None

        def interval(offs, sizes, rho, strided):
            """Data interval at combined rank rho = a*nj + r.  A strided
            side owns its a-th slice of cell r; a plain side holds (src) or
            needs (dst) the FULL cell at every inner coord a."""
            a, r = divmod(rho, nj)
            if strided:
                cell = sizes[r] // s
                return offs[r] + a * cell, cell
            return offs[r], sizes[r]

        E = np.zeros((n, n), np.int32)          # exchanged lengths
        send_start = np.zeros((n, n), np.int32)  # src-local offset
        recv_start = np.zeros((n, n), np.int32)  # dst-local offset
        for p in range(n):
            slo, scell = interval(s_offs, s_sizes, p, src_strided)
            for q in range(n):
                if not src_strided and (p // nj) != (q // nj):
                    # plain source: every inner row replicates the cell —
                    # only the SAME-row copy sends, or each piece would
                    # arrive s times
                    continue
                dlo, dcell = interval(d_offs, d_sizes, q, dst_strided)
                g0, g1 = max(slo, dlo), min(slo + scell, dlo + dcell)
                if g1 > g0:
                    E[p, q] = g1 - g0
                    send_start[p, q] = g0 - slo
                    recv_start[p, q] = g0 - dlo
        deltas = sorted({(q - p) % n for p in range(n) for q in range(n) if E[p, q] > 0})
        plans = []
        for d in deltas:
            ln = np.asarray([E[p, (p + d) % n] for p in range(n)], np.int32)
            sst = np.asarray([send_start[p, (p + d) % n] for p in range(n)], np.int32)
            rln = np.asarray([E[(p - d) % n, p] for p in range(n)], np.int32)
            rst = np.asarray([recv_start[(p - d) % n, p] for p in range(n)], np.int32)
            plans.append((d, int(ln.max()), ln, sst, rln, rst))
        dst_pad = dlay.cell_pad
        rj_name = mesh.dim_name(rj)
        names = (mesh.dim_name(inner), rj_name) if inner is not None else rj_name

        # ppermute's perm indices flatten multi-axis tuples in MESH order
        # (jax.lax.axis_index flattens in TUPLE order — they differ when the
        # ragged dim precedes the inner dim in the mesh; verified
        # empirically).  Map our inner-major logical rank rho = a*nj + r
        # into ppermute's index space before building the pairs.
        def g(rho: int) -> int:
            if inner is None:
                return rho
            a, r = divmod(rho, nj)
            return a * nj + r if inner < rj else r * s + a

        perms = {d: [(g(p), g((p + d) % n)) for p in range(n)] for d, *_ in plans}

        def worker(x):
            r = jax.lax.axis_index(rj_name)
            a = jax.lax.axis_index(names[0]) if inner is not None else 0
            rho = a * nj + r
            lmax_all = max((p[1] for p in plans), default=1)
            xp = jnp.concatenate([x, jnp.zeros((lmax_all,), x.dtype)])
            out = jnp.zeros((dst_pad,), x.dtype)
            for d, lmax, ln, sst, rln, rst in plans:
                piece = jax.lax.dynamic_slice(xp, (jnp.asarray(sst)[rho],), (lmax,))
                piece = jnp.where(jnp.arange(lmax) < jnp.asarray(ln)[rho], piece, 0)
                if d != 0:
                    piece = jax.lax.ppermute(piece, names, perm=perms[d])
                pos = jnp.where(
                    jnp.arange(lmax) < jnp.asarray(rln)[rho],
                    jnp.asarray(rst)[rho] + jnp.arange(lmax),
                    dst_pad,  # out of bounds -> dropped
                )
                out = out.at[pos].set(piece, mode="drop")
            return out

        fn = shard_map(
            worker,
            mesh=mesh.jax_mesh,
            in_specs=(slay.pspec,),
            out_specs=dlay.pspec,
            check_vma=False,
            axis_names=frozenset(mesh.mesh_dim_names),
        )
        return jax.jit(fn)

    return None


# -------------------------------------------------- interleaved kernels
def _axis_span(spec: DArraySpec, d: int) -> Tuple[int, int]:
    """(first physical body axis, axis count) of logical dim ``d`` — 2 when
    ``d`` is interleave-reshaped into (m, size/m), else 1."""
    inter = dict(spec.layout().interleaves)
    pos = sum(2 if dd in inter else 1 for dd in range(d))
    return pos, (2 if d in inter else 1)


def _d_pieces(placement, L: int, n: int, r: int):
    """Rank ``r``'s pieces of logical dim ``d`` as (global_start,
    local_start, length) in the rank's CANONICAL local order (interleaved:
    concat of its chunk of every section)."""
    from .placements import InterleavedShard

    if isinstance(placement, InterleavedShard):
        k = placement.interleaved_size
        c = L // (k * n)
        return [(s * (L // k) + r * c, s * c, c) for s in range(k)]
    if type(placement) is Shard:
        C = L // n
        return [(r * C, 0, C)]
    return [(0, 0, L)]  # Replicate


@functools.lru_cache(maxsize=256)
def interleaved_transition_fn(src: DArraySpec, dst: DArraySpec):
    """Per-shard kernel for InterleavedShard transitions (reference
    interleaved view rules, legacy/vescale/dtensor/ops/vescale_view_ops.py:
    11-14; redistribute.py:223), or None when the pair needs the fallback.

    Scope: same mesh/shape, no ragged, exactly ONE mesh dim differs, and on
    that dim both sides place the SAME tensor dim ``d`` via
    Shard(d) / InterleavedShard(d, k) / Replicate with at least one
    interleave and exact divisibility.  UNCHANGED Partial placements on
    other mesh dims ride along: piece-exchange is pure data movement along
    mesh dim ``i`` — linear over the partial contributions, which never mix
    across their own mesh dim — so the result stays a valid partial value
    (a CHANGED partial dim fails the one-differing-dim/type guards below).
    Covers the merged-QKV reshards —
    IS(d,k) <-> Shard(d), IS(d,k) -> IS(d,k'), IS -> Replicate and back —
    whose r4 fallback could materialize the logical tensor (a 70B
    interleaved-QKV reshard would OOM a 96 GB chip).

    Mechanics: every rank's slice of dim ``d`` decomposes into STATIC
    contiguous pieces (k per rank for IS); intersecting src pieces with dst
    pieces yields a static exchange plan executed as one ppermute round per
    active ring delta with index-table gather/scatter — peak per-device
    bytes stay O(shard) + O(round buffer), never the logical size (asserted
    from compiled-HLO memory analysis in tests/test_placements.py)."""
    import numpy as np

    from .placements import InterleavedShard

    if src.mesh != dst.mesh or src.shape != dst.shape:
        return None
    if src.has_ragged() or dst.has_ragged():
        return None
    if not (src.layout().interleaves or dst.layout().interleaves):
        return None
    mesh = src.mesh
    diff = [i for i in range(mesh.ndim) if src.placements[i] != dst.placements[i]]
    if len(diff) != 1:
        return None
    i = diff[0]
    sp, dp = src.placements[i], dst.placements[i]
    ok_types = (Shard, InterleavedShard, Replicate)
    if not (isinstance(sp, ok_types) and isinstance(dp, ok_types)):
        return None
    if not (isinstance(sp, InterleavedShard) or isinstance(dp, InterleavedShard)):
        return None  # plain pairs belong to transition_fn
    dims = {p.dim for p in (sp, dp) if not isinstance(p, Replicate)}
    if len(dims) != 1:
        return None
    d = dims.pop()
    # dim d must belong to mesh dim i alone, on both sides
    for spec in (src, dst):
        for j, p in enumerate(spec.placements):
            if j != i and isinstance(p, (Shard, InterleavedShard)) and p.dim == d:
                return None
    n = mesh.shape[i]
    L = src.shape[d]
    for p in (sp, dp):
        if isinstance(p, InterleavedShard) and L % (p.interleaved_size * n) != 0:
            return None
        if type(p) is Shard and L % n != 0:
            return None

    # ---- static exchange plan over ring deltas
    src_rep = isinstance(sp, Replicate)
    src_local = L if src_rep else L // n
    dst_local = L if isinstance(dp, Replicate) else L // n
    ex: Dict[int, Dict[int, List[Tuple[int, int, int]]]] = {}  # delta -> p -> pieces
    for p in range(n):
        for q in range(n):
            if src_rep and p != q:
                continue  # every rank holds everything: only the self-copy
            pieces = []
            for gs, ls, ln in _d_pieces(sp, L, n, p):
                for gd, ld, dn in _d_pieces(dp, L, n, q):
                    lo, hi = max(gs, gd), min(gs + ln, gd + dn)
                    if hi > lo:
                        pieces.append((ls + lo - gs, ld + lo - gd, hi - lo))
            if pieces:
                ex.setdefault((q - p) % n, {})[p] = pieces
    plans = []
    for delta in sorted(ex):
        rows = ex[delta]
        lmax = max(sum(ln for _s, _d2, ln in ps) for ps in rows.values())
        send_idx = np.zeros((n, lmax), np.int32)
        recv_pos = np.full((n, lmax), dst_local, np.int32)  # OOB -> dropped
        for p, ps in rows.items():
            o = 0
            for ls, ld, ln in ps:
                send_idx[p, o:o + ln] = np.arange(ls, ls + ln)
                recv_pos[(p + delta) % n, o:o + ln] = np.arange(ld, ld + ln)
                o += ln
        plans.append((delta, send_idx, recv_pos))
    if not plans:
        return None

    # _axis_span counts BODY axes; partial dims prepend lead axes (local
    # extent 1 under shard_map) that shift dim d's physical position
    lead = len(src.layout().partial_mesh_dims)
    pos_s, span_s = _axis_span(src, d)
    pos_d, span_d = _axis_span(dst, d)
    pos_s += lead
    pos_d += lead
    ax_name = mesh.dim_name(i)
    perms = {
        delta: [(p, (p + delta) % n) for p in range(n)]
        for delta, *_ in plans
        if delta != 0
    }

    def worker(x):
        # canonicalize dim d to ONE leading axis in local layout order
        if span_s == 2:
            sh = x.shape
            x = jnp.reshape(x, sh[:pos_s] + (sh[pos_s] * sh[pos_s + 1],) + sh[pos_s + 2:])
        x = jnp.moveaxis(x, pos_s, 0)
        assert x.shape[0] == src_local, (x.shape, src_local)
        r = jax.lax.axis_index(ax_name)
        out = jnp.zeros((dst_local + 1,) + x.shape[1:], x.dtype)  # +1 drop row
        for delta, send_idx, recv_pos in plans:
            piece = jnp.take(x, jnp.asarray(send_idx)[r], axis=0)
            if delta != 0:
                piece = jax.lax.ppermute(piece, ax_name, perm=perms[delta])
            out = out.at[jnp.asarray(recv_pos)[r]].set(piece, mode="drop")
        out = out[:dst_local]
        out = jnp.moveaxis(out, 0, pos_d)
        if span_d == 2:
            m = dp.interleaved_size  # type: ignore[union-attr]
            sh = out.shape
            out = jnp.reshape(out, sh[:pos_d] + (m, sh[pos_d] // m) + sh[pos_d + 1:])
        # local shapes must match the dst layout exactly (other axes carry
        # their (possibly padded) extents through untouched)
        return out

    fn = shard_map(
        worker,
        mesh=mesh.jax_mesh,
        in_specs=(src.layout().pspec,),
        out_specs=dst.layout().pspec,
        check_vma=False,
        axis_names=frozenset(mesh.mesh_dim_names),
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def fallback_fn(src: DArraySpec, dst: DArraySpec):
    """pack(unpack(.)) compiled under jit with the destination sharding —
    correct for every pair (ragged, interleaved, nested); the logical
    intermediate may materialize (use only off the fast path)."""

    def go(phys):
        return dst.pack(src.unpack(phys))

    if src.mesh == dst.mesh:
        return jax.jit(go, out_shardings=dst.named_sharding())
    return go  # cross-mesh: device sets differ; stay eager


# --------------------------------------------- quantized transition kernel
# The quantize->move->dequantize variant of ``transition_fn``: every WIRE op
# of the static plan routes through the block-scaled int8 collectives
# (collectives.q_psum / q_all_gather / q_psum_scatter / q_all_to_all), so
# the payload on the wire is one packed int8 buffer per collective while
# local ops (slice / seed) stay exact.  LOSSY by construction — only the
# redistribution planner's gated quant hop (VESCALE_REDISTRIBUTE_QUANT)
# and the grad-compression knobs on DDP / DistributedOptimizer build these.

_Q_DTYPES = ("float32", "bfloat16", "float16")
_Q_WIRE = {"reduce", "reduce_scatter", "gather", "move"}


def quant_plan_info(src: DArraySpec, dst: DArraySpec, block: int = 64):
    """Static feasibility + byte accounting for a quantized transition.

    Returns ``(ops, collectives, q_bytes, raw_bytes, compute_bytes,
    wire_detail)`` or ``None`` when the pair has no quantizable plan:
    ``collectives`` maps tagged logical ops (``all_reduce:int8`` ...) to
    counts, ``q_bytes`` is the per-device packed payload estimate the
    planner's cost model charges on the wire, ``raw_bytes`` the
    unquantized payload the same wire ops would move, ``compute_bytes``
    the tensor bytes the quantize/dequantize elementwise passes touch, and
    ``wire_detail`` a per-wire-op ``(tag, q_bytes_op, mesh_dim_size,
    packed_payload)`` list so the cost model can weight each op's OWN bytes
    (not an average) and, in calibrated mode, look up the measured wall
    time for the op's actual fan-in at its raw packed PAYLOAD size — the
    calibration table is keyed by operand payload, not ring-scaled wire
    bytes (telemetry/calibrate.py).  Quantized
    all-reduce is gather-based (quantize ONCE, no per-hop requantization),
    so both its wire bytes and its dequantize-accumulate compute scale
    with the mesh-dim size — the cost model sees that honestly and
    declines where a ring psum is cheaper (large mesh dims)."""
    from .quant.blockscale import packed_nbytes

    if str(jnp.dtype(src.dtype)) not in _Q_DTYPES:
        return None
    ops = _plan_ops(src, dst)
    if ops is None:
        return None
    wire = [op for op in ops if op[0] in _Q_WIRE]
    if not wire:
        return None
    itemsize = jnp.dtype(src.dtype).itemsize
    sb, db = src.per_shard_bytes(), dst.per_shard_bytes()
    colls: Dict[str, int] = {}
    q_bytes = 0.0
    raw_bytes = 0.0
    compute_bytes = 0.0
    wire_detail: List[Tuple[str, int, int, int]] = []
    for op in wire:
        kind, i = op[0], op[1]
        n = src.mesh.shape[i]
        f = (n - 1) / max(1, n)
        if kind == "reduce":
            if op[2] not in ("sum", "avg"):
                return None
            # gather-based quantized all-reduce: each device receives n-1
            # packed contributions of its full shard and dequantize-adds
            # all n of them
            elems = sb // itemsize
            payload = packed_nbytes(int(elems), block)
            q, r, c = f * n * payload, 2 * f * sb, "all_reduce:int8"
            comp = (1 + n) * sb
        elif kind == "reduce_scatter":
            if op[2] not in ("sum", "avg"):
                return None
            elems = sb // itemsize
            payload = packed_nbytes(int(elems), block)
            q, r, c = f * payload, f * sb, "reduce_scatter:int8"
            comp = 2 * sb  # quantize full operand + dequant n chunks of sb/n
        elif kind == "gather":
            elems = db // itemsize
            # per-rank contribution: each rank quantizes and sends its OWN
            # chunk (db/n) — the calibrated lookup is keyed by that payload;
            # the wire estimate q still totals all n chunks' packed bytes
            payload = packed_nbytes(int(elems) // max(1, n), block)
            q, r, c = f * packed_nbytes(int(elems), block), f * db, "all_gather:int8"
            comp = db // max(1, n) + db  # quantize own chunk, dequant all n
        else:  # move
            elems = max(sb, db) // itemsize
            payload = packed_nbytes(int(elems), block)
            q, r, c = f * payload, f * max(sb, db), "all_to_all:int8"
            comp = 2 * max(sb, db)
        colls[c] = colls.get(c, 0) + 1
        q_bytes += q
        raw_bytes += r
        compute_bytes += comp
        wire_detail.append((c, int(q), int(n), int(payload)))
    return ops, colls, int(q_bytes), int(raw_bytes), int(compute_bytes), wire_detail


@functools.lru_cache(maxsize=128)
def quant_transition_fn(
    src: DArraySpec,
    dst: DArraySpec,
    block: int = 64,
    rounding: str = "nearest",
):
    """A compiled ``physical(src) -> physical(dst)`` transition whose wire
    collectives carry block-scaled int8 payloads, or None when the pair
    has no quantizable plan (see ``quant_plan_info``).

    The nearest-rounding kernel is unary; the stochastic kernel takes
    ``(x, key)`` — the key is a RUNTIME argument, never baked into the
    cached compilation, so every call can draw fresh noise
    (``collectives.next_sr_key``) without retracing."""
    from .collectives import q_all_gather, q_all_to_all, q_psum, q_psum_scatter

    info = quant_plan_info(src, dst, block)
    if info is None:
        return None
    ops = info[0]
    mesh = src.mesh
    name = mesh.dim_name
    src_lead = src.layout().partial_mesh_dims
    dst_lead = dst.layout().partial_mesh_dims
    ext = dict(enumerate(src.shape))
    qkw = dict(block=block, rounding=rounding)

    def worker(x, base_key=None):
        if src_lead:
            x = jnp.squeeze(x, axis=tuple(range(len(src_lead))))
        for op_idx, op in enumerate(ops):
            kind = op[0]
            # each wire op folds its ordinal into the SR key: two ops of
            # one plan must not share a noise mask (ranks whose indices
            # coincide across mesh dims would correlate their errors)
            key = None if base_key is None else jax.random.fold_in(base_key, op_idx)
            if kind == "reduce":
                _, i, rop = op
                x = q_psum(x, name(i), mesh.shape[i], key=key, reduce_op=rop, **qkw)
            elif kind == "reduce_scatter":
                _, i, rop, d = op
                n = mesh.shape[i]
                x = _pad_to(x, d, _chunk_of(dst, d) * n)
                x = q_psum_scatter(
                    x, name(i), n, scatter_dim=d, key=key, reduce_op=rop, **qkw
                )
            elif kind == "gather":
                _, i, d = op
                x = q_all_gather(
                    x, name(i), mesh.shape[i], axis=d, extent=ext[d], key=key, **qkw
                )
            elif kind == "move":
                _, i, d, d2 = op
                n = mesh.shape[i]
                x = _pad_to(x, d2, _chunk_of(dst, d2) * n)
                x = q_all_to_all(
                    x, name(i), n, split_axis=d2, concat_axis=d, key=key, **qkw
                )
                x = _trim_to(x, d, ext[d])
            elif kind == "slice":
                _, i, d = op
                n = mesh.shape[i]
                chunk = _chunk_of(dst, d)
                x = _pad_to(x, d, chunk * n)
                idx = jax.lax.axis_index(name(i))
                x = jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=d)
            elif kind == "seed":
                _, i, rop = op
                if rop == "sum":
                    idx = jax.lax.axis_index(name(i))
                    x = jnp.where(idx == 0, x, jnp.zeros_like(x))
        if dst_lead:
            x = jnp.expand_dims(x, axis=tuple(range(len(dst_lead))))
        return x

    if rounding == "stochastic":
        from jax.sharding import PartitionSpec as _P

        fn = shard_map(
            worker,
            mesh=mesh.jax_mesh,
            in_specs=(src.layout().pspec, _P()),  # key replicated
            out_specs=dst.layout().pspec,
            check_vma=False,
            axis_names=frozenset(mesh.mesh_dim_names),
        )
        return jax.jit(fn)
    fn = shard_map(
        lambda x: worker(x),
        mesh=mesh.jax_mesh,
        in_specs=(src.layout().pspec,),
        out_specs=dst.layout().pspec,
        check_vma=False,
        axis_names=frozenset(mesh.mesh_dim_names),
    )
    return jax.jit(fn)
