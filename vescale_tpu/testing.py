"""Shared 2-process gloo rig plumbing — ports, spawn, transport retry.

Every multi-process proof in this repo (tests/test_multiprocess.py,
tests/test_multihost_resilience.py, scripts/elastic_smoke.py,
scripts/serve_smoke.py) spawns a 2-process x N-virtual-device CPU world
over `jax.distributed` + gloo.  Each used to pick its coordinator port
independently with a bind-then-close probe, which has a classic race: the
probe closes the socket before any child binds it, so a full tier-1 run —
many rigs starting within the same second — occasionally hands two worlds
the same port, or hands a port still in TIME_WAIT from a previous rig.
The result was the PR-9 flake: `elastic_smoke` failing ~once per full run
with a gloo transport-setup error while passing in isolation.

This module is the single place ports come from and worlds get spawned:

  * :func:`reserve_port` never returns a port it has handed out before in
    this process (a process-global registry, asserted duplicate-free by a
    tier-1 test) — one rig can no longer collide with another in the same
    test session.
  * :func:`run_gloo_world` collects a spawned world and, when a child dies
    with a recognizable TRANSPORT-SETUP signature (address in use,
    connect-refused, coordination-service timeout), retries the whole
    world ONCE on a fresh port (``transport_retries`` bounds it) — the
    cross-session race (another process on the machine grabbing the port)
    is unobservable from in here, so it is absorbed rather than detected.
    An ``on_retry`` hook lets callers reset on-disk state (checkpoint
    roots) between attempts; failures with any other signature surface
    unchanged — a real assertion error must never be retried into hiding.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "reserve_port",
    "reserved_ports",
    "is_transport_error",
    "make_child_env",
    "run_gloo_world",
    "TRANSPORT_ERROR_SIGNATURES",
]

_RESERVED: List[int] = []
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# stderr/stdout fragments that mean "the WORLD never came up" (socket/
# coordination-service setup), as opposed to a failure of the code under
# test.  Deliberately narrow: an assertion failure inside a worker must
# never match.
TRANSPORT_ERROR_SIGNATURES = (
    "Address already in use",
    "Connection refused",
    "connectFullMesh",
    "failed to connect to coordination service",
    "coordination service is not available",
    "Gloo connect",
    "gloo transport",
    "DEADLINE_EXCEEDED: Barrier timed out waiting for init",
)


def reserve_port() -> int:
    """A fresh localhost port, never previously returned by this process.

    The OS assigns (bind to port 0); the registry retry makes same-process
    reuse impossible — the cross-rig collision that produced the PR-9
    elastic-smoke flake."""
    for _ in range(128):
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        if port not in _RESERVED:
            _RESERVED.append(port)
            return port
    raise RuntimeError(
        f"could not reserve an unused port after 128 probes "
        f"({len(_RESERVED)} already handed out)"
    )


def reserved_ports() -> Tuple[int, ...]:
    """Every port handed out so far (the no-reuse assertion surface)."""
    return tuple(_RESERVED)


def is_transport_error(output: str) -> bool:
    return any(sig in output for sig in TRANSPORT_ERROR_SIGNATURES)


def make_child_env(
    port: int,
    pid: int,
    world: int,
    *,
    device_count: int = 4,
    scrub: Sequence[str] = (),
    extra: Optional[Dict] = None,
) -> Dict[str, str]:
    """The standard child environment of the CPU gloo rig, built in ONE
    place: coordinator bootstrap vars scrubbed then (for ``world > 1``)
    set from ``port``/``pid``, CPU platform + repo PYTHONPATH, and the
    virtual-device flag rewritten to ``device_count``.  ``scrub`` names
    extra vars the child must not inherit (a stale ``VESCALE_FAULTSIM``
    from the parent would inject faults into a leg that expects none);
    ``extra`` applies last, stringified."""
    env = dict(os.environ)
    for k in ("VESCALE_COORDINATOR", "VESCALE_NUM_PROCESSES", "VESCALE_PROCESS_ID",
              *scrub):
        env.pop(k, None)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=f"{_REPO}:{env.get('PYTHONPATH', '')}")
    if world > 1:
        env.update(
            VESCALE_COORDINATOR=f"localhost:{port}",
            VESCALE_NUM_PROCESSES=str(world),
            VESCALE_PROCESS_ID=str(pid),
        )
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={device_count}"]
    )
    if extra:
        env.update({k: str(v) for k, v in extra.items()})
    return env


def run_gloo_world(
    spawn: Callable[[int], Sequence[subprocess.Popen]],
    *,
    timeout: float = 420,
    transport_retries: int = 1,
    on_retry: Optional[Callable[[], None]] = None,
) -> List[Tuple[int, str]]:
    """Spawn a world via ``spawn(port)`` and collect ``(returncode,
    output)`` per process, retrying transport-setup failures on a fresh
    port at most ``transport_retries`` times.

    ``spawn`` receives a freshly reserved coordinator port and returns the
    ``Popen`` handles (stdout piped, stderr folded in — the signature scan
    reads the combined stream).  On timeout every child is killed and the
    ``TimeoutExpired`` propagates (a hang is a finding, not a flake)."""
    attempt = 0
    while True:
        port = reserve_port()
        procs = list(spawn(port))
        outs: List[str] = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append(out or "")
        results = [(p.returncode, out) for p, out in zip(procs, outs)]
        if all(rc == 0 for rc, _ in results):
            return results
        transport = any(rc != 0 and is_transport_error(out) for rc, out in results)
        if transport and attempt < transport_retries:
            attempt += 1
            print(
                f"[gloo-rig] transport setup failed on port {port}; "
                f"retry {attempt}/{transport_retries} on a fresh port",
                file=sys.stderr,
            )
            if on_retry is not None:
                on_retry()
            continue
        return results
