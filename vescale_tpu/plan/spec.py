"""Plan enums (reference legacy/vescale/plan/spec.py:34-70)."""

from __future__ import annotations

import dataclasses
import enum

__all__ = [
    "ModeType",
    "PipelineSplitMethodType",
    "PipelineScheduleType",
    "TracerType",
    "PipelineP2PSpec",
]


class ModeType(enum.Enum):
    EAGER = "eager"
    GRAPH_EAGER = "graph_eager"
    COMPILE = "compile"  # TPU-native: whole-pipeline shard_map/jit


class PipelineSplitMethodType(enum.Enum):
    UNIFORM = "uniform"
    MANUAL = "manual"
    PARAMETERS = "parameters"  # balance by param count
    AUTOBALANCE = "autobalance"
    FLOPS = "flops"


class PipelineScheduleType(enum.Enum):
    SIMPLE_1F1B = "1f1b"
    INTERLEAVED_1F1B = "interleaved_1f1b"
    GPIPE = "gpipe"
    ZERO_BUBBLE = "zbv"
    GRAPH_PIPE = "graph_pipe"


class TracerType(enum.Enum):
    """How a model is decomposed into pipeline stages.  The reference's
    fx/HF/dynamo tracers (pipe/tracer.py:81,93) map to two TPU-native modes:
    MODULE_PATH splits an explicit stage-unit list (pipe_stage.py), JAXPR
    traces the model function and cuts its equation graph with a FLOP cost
    model (graph_split.py) — full graph-level auto-split for models that are
    not block lists.  The torch names are kept for plan-compat and alias to
    JAXPR."""

    VESCALE_FX = "vescale_fx"
    HF_FX = "hf_fx"
    TORCH_DYNAMO = "dynamo"
    MODULE_PATH = "module_path"  # explicit stage-unit lists
    JAXPR = "jaxpr"              # graph-level auto-split (pipe/graph_split.py)


@dataclasses.dataclass
class PipelineP2PSpec:
    """Reference plan/spec.py — p2p tensor spec for manual stage IO."""

    peer_stage_idx: int
    peer_output_idx: int = 0
