"""Plan enums (reference legacy/vescale/plan/spec.py:34-70)."""

from __future__ import annotations

import dataclasses
import enum

__all__ = [
    "ModeType",
    "PipelineSplitMethodType",
    "PipelineScheduleType",
    "TracerType",
    "PipelineP2PSpec",
]


class ModeType(enum.Enum):
    EAGER = "eager"
    GRAPH_EAGER = "graph_eager"
    COMPILE = "compile"  # TPU-native: whole-pipeline shard_map/jit


class PipelineSplitMethodType(enum.Enum):
    UNIFORM = "uniform"
    MANUAL = "manual"
    PARAMETERS = "parameters"  # balance by param count
    AUTOBALANCE = "autobalance"
    FLOPS = "flops"


class PipelineScheduleType(enum.Enum):
    SIMPLE_1F1B = "1f1b"
    INTERLEAVED_1F1B = "interleaved_1f1b"
    GPIPE = "gpipe"
    ZERO_BUBBLE = "zbv"
    GRAPH_PIPE = "graph_pipe"


class TracerType(enum.Enum):
    """The reference's fx/HF/dynamo tracers (pipe/tracer.py:81,93) do not
    exist on TPU — module-path splitting covers GRAPH_EAGER (SURVEY §7.6).
    Kept for plan-compat."""

    VESCALE_FX = "vescale_fx"
    HF_FX = "hf_fx"
    TORCH_DYNAMO = "dynamo"
    MODULE_PATH = "module_path"  # the TPU-native mode


@dataclasses.dataclass
class PipelineP2PSpec:
    """Reference plan/spec.py — p2p tensor spec for manual stage IO."""

    peer_stage_idx: int
    peer_output_idx: int = 0
