from .spec import (
    ModeType,
    PipelineP2PSpec,
    PipelineScheduleType,
    PipelineSplitMethodType,
    TracerType,
)
from .pipeline_parallel import PipelineParallelPlan
