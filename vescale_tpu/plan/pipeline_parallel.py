"""PipelineParallelPlan (reference legacy/vescale/plan/pipeline_parallel.py:28)."""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

from .spec import ModeType, PipelineScheduleType, PipelineSplitMethodType, TracerType

__all__ = ["PipelineParallelPlan"]


@dataclasses.dataclass
class PipelineParallelPlan:
    mode: ModeType = ModeType.EAGER
    split_method: PipelineSplitMethodType = PipelineSplitMethodType.UNIFORM
    num_stages: int = 2
    virtual_chunks: int = 1
    split_points: Optional[Sequence[str]] = None  # module names ending each stage
    batch_p2p_comm: bool = True          # parity flags; XLA handles batching
    overlap_p2p_comm: bool = True
    use_zero_bubble: bool = False
    schedule_type: PipelineScheduleType = PipelineScheduleType.SIMPLE_1F1B
    num_model_chunks: int = 1
    tracer_type: TracerType = TracerType.MODULE_PATH
    smallest_unsplittable_units: Optional[Sequence[str]] = None
    uniform_split_ops: bool = False
    p2p_tensor_shapes: Optional[Any] = None
    reuse_p2p_tensor_shape: bool = False
    forward_only: bool = False
    # cost model for ZERO_BUBBLE (pipe.schedules.StageCosts or per-stage
    # weights): routes scheduling through the cost-graph generator, the
    # analog of the reference's profiled CostGraph (zero_bubble_v.py:198)
    schedule_costs: Optional[Any] = None
    # static cross-stage activation layouts (analysis/shardcheck.py VSC106):
    # per boundary i, the placements stage i EMITS and stage i+1 EXPECTS.
    # When both are declared, the pipe engine audits every boundary through
    # the real redistribute dispatch before the first microbatch runs — a
    # boundary whose transition would hit the logical-materializing
    # fallback surfaces as a coded finding, not a silent gather at step 1.
    stage_out_placements: Optional[Sequence[Any]] = None
    stage_in_placements: Optional[Sequence[Any]] = None

    def __post_init__(self):
        if self.schedule_type == PipelineScheduleType.INTERLEAVED_1F1B and self.virtual_chunks < 2:
            self.virtual_chunks = max(2, self.num_model_chunks)
        if self.use_zero_bubble:
            self.schedule_type = PipelineScheduleType.ZERO_BUBBLE
        if (self.stage_out_placements is None) != (self.stage_in_placements is None):
            raise ValueError(
                "stage_out_placements and stage_in_placements must be "
                "declared together (one per stage boundary)"
            )
        if self.stage_out_placements is not None and (
            len(self.stage_out_placements) != len(self.stage_in_placements)
        ):
            raise ValueError(
                "stage_out_placements and stage_in_placements must have "
                "equal length (one entry per stage boundary)"
            )

    def boundary_report(self, mesh, activation_shape, dtype=None):
        """Audit the declared cross-stage activation layouts over ``mesh``
        for ``activation_shape`` p2p tensors: every boundary transition is
        classified through the real redistribute dispatch —
        materializing-fallback boundaries emit VSC106 (with the planner's
        VSC12x decline code), planner-served ones emit the costed VSC108
        info finding.  Returns the FindingReport (empty when no layouts
        are declared)."""
        from ..analysis import check_stage_boundaries
        from ..spec import DArraySpec, TensorMeta

        if self.stage_out_placements is None:
            from ..analysis.findings import FindingReport

            return FindingReport("pipeline boundaries")
        import jax.numpy as jnp

        meta = TensorMeta(tuple(activation_shape), jnp.dtype(dtype or jnp.float32))
        outs = [DArraySpec(mesh, p, meta) for p in self.stage_out_placements]
        ins = [DArraySpec(mesh, p, meta) for p in self.stage_in_placements]
        return check_stage_boundaries(outs, ins, name="pipeline boundaries")
