"""PipelineParallelPlan (reference legacy/vescale/plan/pipeline_parallel.py:28)."""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

from .spec import ModeType, PipelineScheduleType, PipelineSplitMethodType, TracerType

__all__ = ["PipelineParallelPlan"]


@dataclasses.dataclass
class PipelineParallelPlan:
    mode: ModeType = ModeType.EAGER
    split_method: PipelineSplitMethodType = PipelineSplitMethodType.UNIFORM
    num_stages: int = 2
    virtual_chunks: int = 1
    split_points: Optional[Sequence[str]] = None  # module names ending each stage
    batch_p2p_comm: bool = True          # parity flags; XLA handles batching
    overlap_p2p_comm: bool = True
    use_zero_bubble: bool = False
    schedule_type: PipelineScheduleType = PipelineScheduleType.SIMPLE_1F1B
    num_model_chunks: int = 1
    tracer_type: TracerType = TracerType.MODULE_PATH
    smallest_unsplittable_units: Optional[Sequence[str]] = None
    uniform_split_ops: bool = False
    p2p_tensor_shapes: Optional[Any] = None
    reuse_p2p_tensor_shape: bool = False
    forward_only: bool = False
    # cost model for ZERO_BUBBLE (pipe.schedules.StageCosts or per-stage
    # weights): routes scheduling through the cost-graph generator, the
    # analog of the reference's profiled CostGraph (zero_bubble_v.py:198)
    schedule_costs: Optional[Any] = None

    def __post_init__(self):
        if self.schedule_type == PipelineScheduleType.INTERLEAVED_1F1B and self.virtual_chunks < 2:
            self.virtual_chunks = max(2, self.num_model_chunks)
        if self.use_zero_bubble:
            self.schedule_type = PipelineScheduleType.ZERO_BUBBLE
