"""``python -m vescale_tpu.analysis`` — the analysis CLI.

Commands (default with no command: ``lint`` + ``examples``):

  lint [paths...]      vescale-lint over the given paths (default: the
                       whole repo — package, scripts, bench, examples,
                       tests)
  examples             validate the examples/ training configs: every
                       model sharding plan audited (VSC107), and the
                       nanogpt config's forward program shardchecked
                       end-to-end
  demo {good,bad}      built-in shardcheck demo programs: ``bad`` is a
                       program that (a) implicitly materializes a sharded
                       operand (VSC101) and (b) redistributes across a
                       pair the multi-hop planner declines (VSC106 +
                       VSC12x decline code); ``good`` is the clean twin
  envdoc [--write P]   print (or write) the generated configuration doc
  whatif [...]         re-score candidate (dp, tp, pp) meshes against the
                       audited calibration table (telemetry/costaudit.py):
                       predicted step time + audit-backed confidence per
                       layout

Flags: ``--strict`` fails (exit 1) on warning-severity findings too (and
is how CI gates); ``--json`` emits machine-readable reports.
``VESCALE_SHARDCHECK=off`` disables program checks but the CLI still runs
them explicitly — the mode gates *implicit* integration points, not an
explicit invocation.
"""

from __future__ import annotations

# Device env must be decided before the first jax backend query: the demo
# and examples commands build 8-device CPU meshes.
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "cpu" in os.environ.get("JAX_PLATFORMS", "") and (
    "host_platform_device_count" not in os.environ.get("XLA_FLAGS", "")
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import argparse
import json
import sys
from typing import List

from .findings import FindingReport

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


def _default_lint_paths() -> List[str]:
    paths = []
    for rel in ("vescale_tpu", "scripts", "examples", "tests", "bench.py",
                "__graft_entry__.py"):
        p = os.path.join(_REPO, rel)
        if os.path.exists(p):
            paths.append(p)
    return paths or [os.path.dirname(os.path.dirname(__file__))]


def cmd_lint(args) -> List[FindingReport]:
    from .lint import lint_paths

    paths = args.paths or _default_lint_paths()
    return [lint_paths(paths)]


def cmd_demo(args) -> List[FindingReport]:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from . import check_transition, shardcheck
    from ..mesh import DeviceMesh
    from ..placements import RaggedShard, Shard
    from ..spec import DArraySpec, TensorMeta

    axis_sizes = {"dp": 2, "tp": 4}
    x = jax.ShapeDtypeStruct((1024, 4096), jnp.float32)

    if args.which == "bad":
        # (a) flattening (B, H) with H tp-sharded merges the sharded dim
        # under the batch dim: GSPMD must all-gather x on every device
        def flatten_hidden(a):
            return jnp.reshape(a, (1024 * 4096,))

        report = shardcheck(
            flatten_hidden, x, in_specs=[P(None, "tp")], mesh=axis_sizes,
            name="demo-known-bad", min_bytes=0, check_source=False,
        )
        # (b) the redistribute pair that used to hit (and still declines
        # into) the logical-materializing fallback: skewed ragged -> even
        # Shard, whose only bridge is full replication (over budget)
        mesh8 = DeviceMesh(("x",), (8,))
        meta = TensorMeta((1 << 20,), jnp.float32)
        src = DArraySpec(mesh8, (RaggedShard((0,), (1, 2, 1, 2, 1, 3, 3, 3)),), meta)
        dst = DArraySpec(mesh8, (Shard(0),), meta)
        report.extend(check_transition(src, dst, where="demo ragged -> Shard(0)"))
        return [report]

    # good: batch-dp elementwise + mean over the (replicated) hidden dim,
    # sharding preserved end to end — and the same-spec redistribute is free
    def clean(a):
        return jnp.mean(a * 2.0, axis=1)

    report = shardcheck(
        clean, x, in_specs=[P("dp", None)], mesh=axis_sizes,
        name="demo-known-good", min_bytes=0, check_source=False,
    )
    mesh8 = DeviceMesh(("x",), (8,))
    meta = TensorMeta((1 << 20,), jnp.float32)
    src = DArraySpec(mesh8, (Shard(0),), meta)
    report.extend(check_transition(src, src.with_placements((Shard(0),)), where="demo no-op"))
    return [report]


def cmd_examples(args) -> List[FindingReport]:
    import jax
    import jax.numpy as jnp

    from . import check_param_plan, shardcheck
    from ..mesh import DeviceMesh

    reports: List[FindingReport] = []
    mesh = DeviceMesh(("dp", "tp"), (2, 4))

    from ..models.llama import llama_plan
    from ..models.mixtral import mixtral_plan
    from ..models.nanogpt import GPT, GPTConfig, nanogpt_plan

    for label, plan in (
        ("nanogpt_plan", nanogpt_plan(mesh)),
        ("nanogpt_plan[sp]", nanogpt_plan(mesh, sequence_parallel=True)),
        ("llama_plan", llama_plan(mesh)),
        ("llama_plan[scan]", llama_plan(mesh, scanned=True)),
    ):
        reports.append(check_param_plan(plan.get("parameter", {}), mesh, name=label))
    mesh_ep = DeviceMesh(("dp", "ep"), (2, 4))
    reports.append(check_param_plan(
        mixtral_plan(mesh_ep).get("parameter", {}), mesh_ep, name="mixtral_plan"
    ))

    # end-to-end: trace the nanogpt example's forward+loss under its plan
    # and shardcheck the program (the same trace jit/AOT lowering sees)
    from ..dmodule import parallelize_module
    from ..models.nanogpt import cross_entropy_loss

    cfg = GPTConfig(block_size=64, vocab_size=256, n_layer=2, n_head=4,
                    n_embd=64, dropout=0.0)
    dm = parallelize_module(GPT(cfg), mesh, nanogpt_plan(mesh))
    idx = jnp.ones((8, 64), jnp.int32)
    variables = jax.eval_shape(lambda: GPT(cfg).init(jax.random.key(0), idx))

    def fwd(params, batch_idx, batch_tgt):
        logits = dm.apply({"params": params}, batch_idx, deterministic=True)
        return cross_entropy_loss(logits, batch_tgt)

    reports.append(shardcheck(
        fwd, variables["params"], idx, jnp.zeros((8, 64), jnp.int32),
        mesh=mesh, name="examples/nanogpt_4d_finetune forward",
        check_source=False,
    ))
    return reports


def cmd_whatif(args) -> int:
    """Re-score candidate (dp, tp, pp) meshes against the live audited
    calibration table (telemetry/costaudit.py) — predicted step time per
    layout plus audit-backed confidence per collective term."""
    from ..telemetry import costaudit
    from ..telemetry.calibrate import load_table, set_active

    if args.table:
        set_active(load_table(args.table))
    num = args.devices
    if not num:
        import jax

        num = len(jax.devices())
    device = None
    if args.device:
        # a named generation ("v5p", "v6e", ...) instead of the local chip:
        # a shim carrying just the two attrs device_peak_flops reads
        device = type("_Dev", (), {"device_kind": args.device,
                                   "platform": "tpu"})()
    cands = costaudit.mesh_candidates(num)
    ranked = costaudit.score_candidates(
        cands,
        params_bytes=args.params_bytes,
        activation_bytes=args.activation_bytes,
        flops_per_step=args.flops,
        device=device,
    )
    if args.top:
        ranked = ranked[: args.top]
    if args.json:
        print(json.dumps({"num_devices": num, "candidates": ranked}, indent=2))
        return 0
    print(f"what-if plan scores over {num} devices "
          f"({len(cands)} (dp, tp, pp) layouts):")
    print(f"  {'mesh':>14} {'step_us':>12} {'compute_us':>12} "
          f"{'comm_us':>10} {'conf':>5}  sources")
    for r in ranked:
        m = r["mesh"]
        srcs = ",".join(sorted({t["source"] for t in r["terms"]})) or "-"
        print(f"  ({m['dp']:>3},{m['tp']:>3},{m['pp']:>3}) "
              f"{r['predicted_step_us']:>12.1f} {r['compute_us']:>12.1f} "
              f"{r['comm_us']:>10.1f} {r['confidence']:>5.2f}  {srcs}")
    return 0


def cmd_envdoc(args) -> List[FindingReport]:
    from .envreg import configuration_markdown

    doc = configuration_markdown()
    if args.write:
        with open(args.write, "w", encoding="utf-8") as f:
            f.write(doc)
        print(f"wrote {args.write}")
    else:
        print(doc)
    return []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m vescale_tpu.analysis")
    ap.add_argument("--strict", action="store_true",
                    help="fail on warning-severity findings too")
    ap.add_argument("--json", action="store_true", help="JSON reports")
    sub = ap.add_subparsers(dest="cmd")
    p_lint = sub.add_parser("lint", help="vescale-lint over paths")
    p_lint.add_argument("paths", nargs="*", default=None)
    sub.add_parser("examples", help="validate examples/ training configs")
    p_demo = sub.add_parser("demo", help="built-in shardcheck demo programs")
    p_demo.add_argument("which", choices=("good", "bad"))
    p_env = sub.add_parser("envdoc", help="generated configuration doc")
    p_env.add_argument("--write", default=None, metavar="PATH")
    p_wi = sub.add_parser(
        "whatif", help="re-score candidate (dp, tp, pp) meshes against the "
        "audited calibration table")
    p_wi.add_argument("--devices", type=int, default=0,
                      help="world size (default: local device count)")
    p_wi.add_argument("--params-bytes", type=float, default=1e9)
    p_wi.add_argument("--activation-bytes", type=float, default=1e8)
    p_wi.add_argument("--flops", type=float, default=1e12,
                      help="model FLOPs per step")
    p_wi.add_argument("--table", default=None, metavar="PATH",
                      help="calibration table JSON (default: active table)")
    p_wi.add_argument("--device", default=None,
                      help='chip generation for the compute roofline '
                      '(e.g. "v5p"; default: local device)')
    p_wi.add_argument("--top", type=int, default=0,
                      help="print only the best N layouts")
    args = ap.parse_args(argv)

    if args.cmd == "lint":
        reports = cmd_lint(args)
    elif args.cmd == "examples":
        reports = cmd_examples(args)
    elif args.cmd == "demo":
        reports = cmd_demo(args)
    elif args.cmd == "envdoc":
        cmd_envdoc(args)
        return 0
    elif args.cmd == "whatif":
        return cmd_whatif(args)
    else:
        args.paths = None
        reports = cmd_lint(args) + cmd_examples(args)

    ok = True
    for r in reports:
        if args.json:
            print(json.dumps(r.to_dict(), indent=2))
        else:
            print(r.format())
        ok = ok and r.ok(strict=args.strict)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
