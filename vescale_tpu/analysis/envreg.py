"""Central registry of every ``VESCALE_*`` environment variable.

PRs 1-5 grew ~30 env knobs by convention — each module parsed its own
``os.environ`` with its own truthiness rules, and nothing said which vars
exist, what type they are, or what they default to.  This module is the
single source of truth: every var is declared once (name, type, default,
one-line doc), reads go through the typed accessors here, and
``vescale-lint`` (analysis/lint.py, code VSC201) rejects any direct
``os.environ`` read of a ``VESCALE_*`` name elsewhere in the repo.
``docs/configuration.md`` is GENERATED from this table
(``markdown_table()``); a test asserts the doc and the registry agree and
that no ``VESCALE_*`` string in the package is unregistered (VSC202).

Semantics:

  * Reads are LIVE — accessors hit ``os.environ`` at call time, never a
    cached snapshot, so tests monkeypatching env vars and runs flipping a
    knob between phases keep working.
  * ``bool`` parsing is uniform: unset -> default; "", "0", "false",
    "off", "no" (case-insensitive) -> False; anything else -> True.
  * ``default=None`` means "unset": typed accessors return None and the
    caller owns the fallback (documented in the var's doc line).

This module imports only the stdlib on purpose: it must be importable from
``__graft_entry__`` bootstrap code and signal-adjacent paths before jax is.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional

__all__ = [
    "EnvVar",
    "register",
    "lookup",
    "is_registered",
    "all_vars",
    "get_raw",
    "get_bool",
    "get_int",
    "get_float",
    "get_str",
    "markdown_table",
]

_FALSE = ("", "0", "false", "off", "no")


def coerce_bool(raw: Optional[str], default: bool) -> bool:
    """The registry's uniform bool parse applied to a raw string — for
    tri-state knobs whose UNSET default is computed by the caller (e.g.
    platform-dependent)."""
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSE


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One registered knob: declaration only — the value lives in the
    process environment and is re-read on every access."""

    name: str
    type: str  # "bool" | "int" | "float" | "str"
    default: Any
    doc: str

    def __post_init__(self):
        if not self.name.startswith("VESCALE_"):
            raise ValueError(f"env registry is for VESCALE_* vars, got {self.name!r}")
        if self.type not in ("bool", "int", "float", "str"):
            raise ValueError(f"{self.name}: unsupported type {self.type!r}")


_REGISTRY: Dict[str, EnvVar] = {}


def register(name: str, type: str, default: Any, doc: str) -> EnvVar:
    """Declare a var.  Idempotent for identical declarations; a conflicting
    re-declaration raises — two modules must not disagree about a knob."""
    var = EnvVar(name, type, default, doc)
    prev = _REGISTRY.get(name)
    if prev is not None and prev != var:
        raise ValueError(
            f"conflicting registration for {name}: {prev} vs {var}"
        )
    _REGISTRY[name] = var
    return var


def lookup(name: str) -> EnvVar:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not registered in vescale_tpu.analysis.envreg — "
            "declare it there (name/type/default/doc) before reading it"
        ) from None


def is_registered(name: str) -> bool:
    return name in _REGISTRY


def all_vars() -> List[EnvVar]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


# ------------------------------------------------------------- accessors
def get_raw(name: str) -> Optional[str]:
    """The raw env string, or None when unset.  Registration enforced."""
    lookup(name)
    return os.environ.get(name)


def get_bool(name: str) -> bool:
    var = lookup(name)
    raw = os.environ.get(name)
    if raw is None:
        return bool(var.default)
    return raw.strip().lower() not in _FALSE


def get_int(name: str) -> Optional[int]:
    """Unset/empty -> the declared default (None when the default is None);
    a malformed value raises LOUDLY — silently falling back would disable
    the very feature the operator tried to configure (a watchdog deadline
    of "5s" must fail at startup, not quietly never arm)."""
    var = lookup(name)
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return None if var.default is None else int(var.default)
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r}: expected an int (see docs/configuration.md)"
        ) from None


def get_float(name: str) -> Optional[float]:
    """Same contract as :func:`get_int` (loud on malformed values)."""
    var = lookup(name)
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return None if var.default is None else float(var.default)
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r}: expected a float (see docs/configuration.md)"
        ) from None


def get_str(name: str) -> Optional[str]:
    var = lookup(name)
    raw = os.environ.get(name)
    if raw is None:
        return var.default
    return raw


# ------------------------------------------------------------ doc output
def markdown_table() -> str:
    """The docs/configuration.md variable table (generated, not hand-kept).
    A test asserts the committed doc matches this output byte-for-byte."""
    lines = [
        "| Variable | Type | Default | Effect |",
        "| --- | --- | --- | --- |",
    ]
    for v in all_vars():
        default = "unset" if v.default is None else repr(v.default).strip("'\"") or '""'
        lines.append(f"| `{v.name}` | {v.type} | `{default}` | {v.doc} |")
    return "\n".join(lines)


def configuration_markdown() -> str:
    """The full docs/configuration.md document (header + generated table).
    ``python -m vescale_tpu.analysis envdoc --write docs/configuration.md``
    regenerates it; tests/test_analysis.py asserts the committed file
    matches this output exactly."""
    head = (
        "# Configuration — `VESCALE_*` environment variables\n"
        "\n"
        "<!-- GENERATED FILE — do not edit by hand.\n"
        "     Regenerate: python -m vescale_tpu.analysis envdoc --write docs/configuration.md\n"
        "     Source of truth: vescale_tpu/analysis/envreg.py -->\n"
        "\n"
        "Every knob is declared in `vescale_tpu.analysis.envreg` (name, type,\n"
        "default, effect) and read through its typed accessors; `vescale-lint`\n"
        "rejects direct `os.environ` reads of `VESCALE_*` names (VSC201) and\n"
        "unregistered names (VSC202), so this table is complete by\n"
        "construction.  Reads are live: flipping a variable between phases\n"
        "(or monkeypatching it in a test) takes effect on the next read.\n"
        "Booleans: unset uses the default; `\"\"`, `0`, `false`, `off`, `no`\n"
        "(case-insensitive) are false; anything else is true.\n"
        "\n"
    )
    return head + markdown_table() + "\n"


# =====================================================================
# Registrations — the full knob surface of the framework, one block per
# subsystem.  Keep doc lines to one sentence; they become the Effect
# column of docs/configuration.md verbatim.
# =====================================================================

# --- analysis --------------------------------------------------------
register("VESCALE_SHARDCHECK", "str", "warn",
         "Static-analysis mode: `off` disables, `warn` emits warnings, `strict` raises on error-severity findings (docs/observability.md).")

# --- Pallas kernel layer ---------------------------------------------
register("VESCALE_KERNELS", "str", "off",
         "Pallas kernel dispatch (docs/kernels.md): `off` = the pre-kernel XLA paths byte-identical, `interpret` = run the kernels through the pallas interpreter on any backend (bit-parity testing), `on` = compiled kernels on TPU (falls back to XLA off-TPU, counted in kernel_fallback_total).")

# --- gradient compression / quantized collectives --------------------
register("VESCALE_GRAD_COMPRESS", "str", "",
         "Gradient-compression codec for DDP/ZeRO grad reduction: empty = off, `int8` = block-scaled int8 quantized collectives (docs/observability.md).")
register("VESCALE_GRAD_COMPRESS_BLOCK", "int", 64,
         "Block size (elements per fp32 scale) for the int8 gradient quantizer.")
register("VESCALE_GRAD_COMPRESS_SR", "bool", False,
         "Use seeded stochastic rounding (unbiased in expectation) instead of round-to-nearest-even for quantized gradient collectives.")
register("VESCALE_GRAD_COMPRESS_SEED", "int", 0,
         "Seed for the stochastic-rounding PRNG of quantized collectives; each eager call folds in a process-wide call counter and each rank its mesh position, so noise is fresh per step/leaf yet replayable from (seed, call order).")

# --- redistribution --------------------------------------------------
register("VESCALE_REDISTRIBUTE_QUANT", "bool", False,
         "Let the multi-hop redistribution planner take a LOSSY quantize-move-dequantize int8 hop where the cost model says it wins; declines are recorded as VSC127 (docs/redistribute.md).")
register("VESCALE_REDISTRIBUTE_MEM_FACTOR", "float", 4.0,
         "Per-shard memory budget for multi-hop plan intermediates, as a multiple of the larger endpoint shard.")
register("VESCALE_REDISTRIBUTE_MAX_HOPS", "int", 3,
         "Hop bound for the multi-hop redistribution planner's lattice search.")
register("VESCALE_STRICT_REDISTRIBUTE", "bool", False,
         "Raise instead of warn when redistribute() would take the logical-materializing pack/unpack fallback.")

# --- distributed bootstrap -------------------------------------------
register("VESCALE_COORDINATOR", "str", None,
         "Coordinator address (host:port) for jax.distributed.initialize; unset on TPU pods (auto-detected).")
register("VESCALE_NUM_PROCESSES", "int", None,
         "World size for multi-process initialization; unset = auto-detect.")
register("VESCALE_PROCESS_ID", "int", None,
         "This process's rank for multi-process initialization and the faultsim `rank=` selector; unset = auto-detect.")
register("VESCALE_BARRIER_TIMEOUT", "float", None,
         "Deadline in seconds for barrier/all_processes_ok (BarrierTimeout past it); unset or <=0 disables.")
register("VESCALE_CONSISTENCY_EVERY", "int", None,
         "Cross-rank state-fingerprint cadence in steps for run_resilient; unset = 32 (armed only when coordinating).")

# --- debug -----------------------------------------------------------
register("VESCALE_DEBUG_MODE", "str", "",
         "DebugLogger gate: `1` logs on every rank, `rank0,1` restricts to listed ranks, empty/0 disables.")

# --- checkpoint / IO retry -------------------------------------------
register("VESCALE_NATIVE_CKPT_IO", "bool", True,
         "Use the native (nogil) checkpoint write pool; `0` forces the Python thread pool (required for storage fault injection).")
register("VESCALE_CKPT_RETRIES", "int", 3,
         "Max attempts for checkpoint storage read/write under the retry policy.")
register("VESCALE_LOADER_RETRIES", "int", 3,
         "Max attempts for a data-loader batch fetch under the retry policy.")
register("VESCALE_IO_BACKOFF_BASE", "float", 0.05,
         "First retry backoff sleep in seconds (exponential from here).")
register("VESCALE_IO_BACKOFF_MAX", "float", 5.0,
         "Retry backoff ceiling in seconds.")
register("VESCALE_IO_BACKOFF_JITTER", "float", 0.25,
         "Seeded jitter fraction applied to each backoff sleep.")
register("VESCALE_IO_ATTEMPT_TIMEOUT", "float", 0.0,
         "Per-attempt timeout in seconds for retried IO (helper thread); 0 disables.")

# --- resilience ------------------------------------------------------
register("VESCALE_FAULTSIM", "str", None,
         'Deterministic fault-injection schedule, e.g. `storage_write:call=3;preempt:step=10` (resilience/faultsim.py grammar).')
register("VESCALE_FAULTSIM_HANG_S", "float", 3600.0,
         "Stall duration in seconds for the faultsim `hang` kind (watchdog test fodder).")
register("VESCALE_FAULTSIM_SLOW_DECODE_S", "float", 0.05,
         "Stall duration in seconds for the faultsim `slow_decode` kind (serve-loop straggler injection).")
register("VESCALE_FAULTSIM_KILL_EXIT_CODE", "int", 29,
         "Process exit code of the faultsim `replica_kill` kind (an abrupt os._exit mid-decode — the fleet failover test substrate).")
register("VESCALE_WATCHDOG_TIMEOUT", "float", 0.0,
         "Hang-watchdog step-progress deadline in seconds; unset or <=0 disables the watchdog.")
register("VESCALE_WATCHDOG_ABORT", "bool", True,
         "On a detected hang, os._exit after the stack dump so a supervisor can restart (disable to only dump).")
register("VESCALE_WATCHDOG_EXIT_CODE", "int", 17,
         "Process exit code used by the watchdog abort path.")
register("VESCALE_WATCHDOG_DIR", "str", None,
         "Directory for watchdog hang dumps when telemetry has no out_dir; unset disables dumping.")

# --- elastic world size ----------------------------------------------
register("VESCALE_ELASTIC_LOADER", "bool", False,
         "Sample the token stream by GLOBAL row index so it is invariant to the (dp_world, per-rank batch) split — required on both runs for an elastic world-size resume (docs/resilience.md).")
register("VESCALE_ELASTIC_RESTORE", "bool", True,
         "Allow restoring a checkpoint written by a different mesh/world size (reshard-on-load, VSC130); `0` refuses cross-world restores with a VSC132 finding.")

# --- serving ---------------------------------------------------------
register("VESCALE_SERVE_SLOTS", "int", 8,
         "Decode-slot count of the serving KV cache (max concurrent in-flight requests; static shapes, so changing it recompiles the decode step).")
register("VESCALE_SERVE_PAGE_SIZE", "int", 16,
         "Tokens per KV-cache page (the paged-attention block size).")
register("VESCALE_SERVE_PAGES_PER_SLOT", "int", 4,
         "Max pages one request may hold; page_size x pages_per_slot is the serving max sequence length.")
register("VESCALE_SERVE_MAX_QUEUE", "int", 64,
         "Bounded admission queue depth; submissions beyond it are shed with a retry-after hint (docs/serving.md).")
register("VESCALE_SERVE_SLO_TTFT_S", "float", 0.0,
         "p99 time-to-first-token SLO budget in seconds; while the rolling p99 exceeds it new submissions are shed (0 disables).")
register("VESCALE_SERVE_DEADLINE_S", "float", 0.0,
         "Default per-request wall-clock deadline in seconds (timeout cancellation); 0 disables (requests may still carry explicit deadlines).")
register("VESCALE_SERVE_OPS_PORT", "int", None,
         "Localhost port for the serve loop's live ops endpoints (`/metrics`, `/healthz`, `/router`): unset = endpoints off (no thread, no socket), 0 = auto-assign a free port (docs/serving.md).")
register("VESCALE_SERVE_REPLICA_ID", "str", None,
         "Stable replica identity published in the `/router` v2 feed (`replica_id`) and used by the fleet router's affinity ring; unset = `rank<process_index>`.")
register("VESCALE_SERVE_IDLE_S", "float", 0.002,
         "Step-boundary sleep of an inbox-fed serve loop with nothing queued or in flight (keeps an idle replica from spinning a core while staying responsive to new submissions).")
register("VESCALE_SERVE_PREFIX_CACHE", "bool", False,
         "Radix-tree prefix caching over the paged KV pool: admission maps cached prompt-prefix pages (page-granular, refcounted) into the new slot and prefills only the suffix; eviction is deterministic LRU over unreferenced leaves (docs/serving.md).")
register("VESCALE_SERVE_PREFIX_CACHE_PAGES", "int", 0,
         "Cap on pages the prefix-cache radix tree may retain (LRU leaves are evicted to fit); 0 = bounded only by the page pool itself.")
register("VESCALE_SPEC_K", "int", 4,
         "Speculative decoding draft length: tokens the drafter proposes per decode iteration, verified by the target in ONE batched multi-token paged step (compile-time constant — each distinct k compiles once).")
register("VESCALE_SPEC_DRAFTER_LAYERS", "int", 1,
         "Decoder-block depth of the speculative drafter: the SAME checkpoint restored at reduced depth (first N blocks + shared embedding/norm/head, params-only through the elastic preflight).")

# --- fleet router (multi-replica serving) ----------------------------
register("VESCALE_FLEET_POLL_S", "float", 0.05,
         "Fleet router poll cadence in seconds for each replica's `/router` feed (docs/serving.md fleet section).")
register("VESCALE_FLEET_POLL_TIMEOUT_S", "float", 2.0,
         "Per-request HTTP timeout in seconds for fleet router polls and submits; a slower reply counts as a breaker failure.")
register("VESCALE_FLEET_BREAKER_FAILURES", "int", 3,
         "Consecutive poll/submit failures that open a replica's circuit breaker (dispatch stops until a half-open probe succeeds).")
register("VESCALE_FLEET_BREAKER_COOLDOWN_S", "float", 1.0,
         "Seconds an open breaker waits before its next poll becomes the half-open readmission probe; a failed probe re-opens with a fresh cooldown.")
register("VESCALE_FLEET_HEALTH_STALE_S", "float", 10.0,
         "A reachable replica whose `/router` `serve_step` has not advanced for this long is treated as wedged (breaker failure); 0 disables staleness detection.")
register("VESCALE_FLEET_RETRIES", "int", 3,
         "Bounded dispatch attempts per request placement (first dispatch, failover and spill-over alike) before the fleet sheds it.")
register("VESCALE_FLEET_BACKOFF_S", "float", 0.05,
         "First retry backoff sleep in seconds for fleet dispatch (exponential from here).")
register("VESCALE_FLEET_BACKOFF_MAX_S", "float", 2.0,
         "Fleet dispatch backoff ceiling in seconds.")
register("VESCALE_FLEET_HEDGE_S", "float", 0.0,
         "Tail-latency hedge bound in seconds: a request unresolved this long after dispatch is sent to a SECOND replica (first terminal outcome wins — decode determinism keeps the answers identical); 0 disables hedging.")
register("VESCALE_FLEET_TRACE_DIR", "str", None,
         "Directory where fleet-traced serve replicas persist their ndtimeline span streams (`<dir>/<replica_id>.spans.jsonl`, flushed per boundary) for the fleet timeline assembler; unset disables replica-side trace persistence (docs/observability.md fleet tracing).")
register("VESCALE_FLEET_TRACE_FLUSH_EVERY", "int", 1,
         "Boundary cadence at which a fleet-traced replica flushes its span ring to the trace stream (1 = every boundary; higher trades crash-durability of the newest spans for fewer writes).")
register("VESCALE_FLEET_OPS_PORT", "int", None,
         "Localhost port for the fleet ROUTER's own ops endpoints (`/fleet` aggregate rollup, `/healthz`, router-process `/metrics`): unset = off (no socket, no thread), 0 = auto-assign (docs/serving.md).")
# --- router high availability (serve/journal.py) ---------------------
register("VESCALE_FLEET_JOURNAL_DIR", "str", None,
         "Directory for the fleet router's write-ahead journal (CRC-framed JSONL of every ledger transition + compacted snapshots): a FleetRouter constructed without an explicit journal opens one here, enabling crash recovery and warm-standby takeover; unset = journaling off, pre-HA behavior byte-identical (docs/serving.md router HA).")
register("VESCALE_FLEET_JOURNAL_FSYNC", "str", "flush",
         "Journal durability policy: `none` (OS page cache only), `flush` (fsync at flush boundaries — poll/snapshot/terminal-ack, the default), `always` (fsync every write; the paranoid setting the <1% overhead bar is measured against).")
register("VESCALE_FLEET_JOURNAL_ROTATE_BYTES", "int", 1048576,
         "Journal segment size in bytes past which the next snapshot rotates to a fresh `wal-NNNNNN.log` segment (older segments beyond the last two are pruned — the snapshot makes them dead weight).")
register("VESCALE_FLEET_JOURNAL_SNAPSHOT_EVERY", "int", 256,
         "Appended records between compacted journal snapshots (each folds ledger counts, pending rids, affinity ring, breaker states, autoscaler clocks and rollout stage into ONE record so recovery replays snapshot+tail, not history).")
register("VESCALE_FLEET_LEASE_PATH", "str", None,
         "Path of the fenced leader-lease file ({epoch, holder, expires_at}, written atomically): a FleetRouter constructed without an explicit lease acquires one here, stamping its epoch into every dispatch tag so a deposed leader's stale placements can never double-resolve a rid; unset = no fencing (single-router deployments).")
register("VESCALE_FLEET_LEASE_TTL_S", "float", 2.0,
         "Leader-lease time-to-live in seconds: the leader renews at TTL/3 on its poll cadence, and a warm standby whose poll finds the lease expired takes over by acquiring epoch+1 (docs/serving.md router HA).")

register("VESCALE_SERVE_TENANT_WEIGHTS", "str", None,
         "Per-tenant SLO-class weights as `tenant:weight[,tenant:weight...]` (e.g. `gold:3,free:1`): each tenant's share of the admission queue is capped at max_queue x weight/total (unlisted tenants weigh 1.0), so an overloaded tenant sheds before it can starve the others; unset disables tenant-weighted shedding entirely (docs/serving.md).")

# --- autoscaler (serve/autoscale.py) ---------------------------------
register("VESCALE_AUTOSCALE_MIN", "int", 1,
         "Lower replica-count bound of the fleet autoscaler: scale-down never drains below this many live replicas.")
register("VESCALE_AUTOSCALE_MAX", "int", 4,
         "Upper replica-count bound of the fleet autoscaler: scale-up never spawns past this many live replicas.")
register("VESCALE_AUTOSCALE_UP_BURN", "float", 1.0,
         "Scale-up threshold on the windowed `fleet_timeline_slo_burn_rate` average (>= 1 means the fleet is burning p99-TTFT error budget).")
register("VESCALE_AUTOSCALE_DOWN_BURN", "float", 0.5,
         "Scale-down threshold on the windowed burn-rate average; the gap up to VESCALE_AUTOSCALE_UP_BURN is the hysteresis dead zone where the fleet stays put.")
register("VESCALE_AUTOSCALE_UP_QUEUE", "int", 4,
         "Aggregate fleet queue depth (router-pending + replica queues) at or above which a rising queue trend also triggers scale-up, independent of the SLO burn signal.")
register("VESCALE_AUTOSCALE_UP_HOLD_S", "float", 1.0,
         "Seconds the scale-up condition must hold continuously before a replica is spawned (transient spikes don't scale).")
register("VESCALE_AUTOSCALE_DOWN_HOLD_S", "float", 5.0,
         "Seconds the scale-down condition must hold continuously before a replica is drained (asymmetric with up-hold: scaling down is the cautious direction).")
register("VESCALE_AUTOSCALE_COOLDOWN_S", "float", 5.0,
         "Seconds after ANY scale action during which the autoscaler makes no further decisions — the just-changed fleet must re-converge before its signals mean anything.")
register("VESCALE_AUTOSCALE_WINDOW_S", "float", 10.0,
         "Time-series window in seconds over which the autoscaler's burn-rate average and queue-depth slope are reduced.")
register("VESCALE_AUTOSCALE_TICK_S", "float", 0.25,
         "Autoscaler control-loop cadence in seconds: tick() calls arriving inside this interval return the cached last decision without recomputing signals, bounding autoscaler overhead in tight serve loops.")

# --- trace timeline / cost calibration -------------------------------
register("VESCALE_COST_CALIBRATION", "str", None,
         "Path to a measured collective-cost table (collective_calibration.json): planner/scheduler/cost functions answer from interpolated measured wall-times, falling back to the analytic model with a one-time warning per missing bucket; unset (or an empty/stale table) keeps the analytic bandwidth-factor model bit-identically (docs/observability.md).")
register("VESCALE_CLOCK_SYNC_ROUNDS", "int", 8,
         "Rounds of allgather wall-clock exchange used by telemetry.trace.estimate_clock_offsets to estimate per-rank clock offsets (more rounds tighten the residual).")

# --- time-series store / alert engine --------------------------------
register("VESCALE_TIMESERIES", "bool", True,
         "Arm the metric time-series store at telemetry.init(): registry counters/gauges/histogram-percentiles gain bounded ring history with tiered downsampling; off = the sample hook stays the dormant no-op reference (docs/observability.md).")
register("VESCALE_TIMESERIES_CADENCE_S", "float", 1.0,
         "Minimum seconds between accepted time-series samples — the loops call `timeseries.sample()` every step/poll and the store keeps at most one per cadence.")
register("VESCALE_TIMESERIES_BASE_LEN", "int", 512,
         "Ring capacity per downsampling tier, in samples (memory bound per metric = base_len x tiers).")
register("VESCALE_TIMESERIES_TIER_FACTOR", "int", 8,
         "How many tier-k samples collapse into one tier-(k+1) sample (mean for value series, last for cumulative series).")
register("VESCALE_TIMESERIES_TIERS", "int", 3,
         "Number of downsampling tiers; with the defaults tier 2 retains ~9 hours of history per metric.")
register("VESCALE_ALERTS", "bool", True,
         "Arm the SLO alert engine at telemetry.init(): declarative rules evaluate over the time-series store with the pending->firing->resolved lifecycle; off = raise_alert degrades to the legacy one-shot warning (docs/observability.md).")
register("VESCALE_ALERTS_HISTORY", "int", 256,
         "Bounded ring of retained alert lifecycle transitions (the `/alerts` history tail).")
register("VESCALE_ALERTS_EVAL_INTERVAL_S", "float", 0.25,
         "Minimum seconds between alert-engine evaluations — the per-step evaluate() hook rate-limits itself to this.")
register("VESCALE_ALERTS_BURN_WINDOWS", "str", None,
         "Override the SLO burn-rate rule windows as `long:short:factor[,long:short:factor...]` seconds (default 3600:300:14.4,21600:1800:6 — the SRE multi-window pairs).")
register("VESCALE_ALERTS_BURN_FOR_S", "float", 0.0,
         "Hold seconds before a burn-rate rule transitions pending -> firing (0 = fire on first evaluation where both windows burn).")

# --- cost audit (plan-vs-reality) ------------------------------------
register("VESCALE_COSTAUDIT", "bool", True,
         "Arm the plan-vs-reality cost auditor at telemetry.init(): priced plans (redistribution, quant edges, pipe schedules, AOT budgets, serve steps) ledger their predictions, a per-step join publishes `cost_model_*` divergence gauges + the `cost-model-drift` rule, and the online harvest folds measured spans back into the calibration table; off = the hooks stay dormant no-op references (docs/observability.md).")
register("VESCALE_COSTAUDIT_DEPTH", "int", 256,
         "Bounded prediction-ledger ring depth — oldest predictions fall off once this many are outstanding (late measurements against an evicted plan id are ignored).")
register("VESCALE_COSTAUDIT_THRESHOLD", "float", 3.0,
         "Divergence ratio (decayed mean of max(measured/predicted, predicted/measured)) above which the `cost-model-drift` alert rule fires.")
register("VESCALE_COSTAUDIT_DECAY", "float", 0.25,
         "EWMA weight of the online calibration harvest and the divergence aggregates: each measured span moves its table bucket this fraction of the way to the new wall time (the sweep's plain 1/n running mean is unchanged).")
register("VESCALE_COSTAUDIT_CADENCE_S", "float", 30.0,
         "Minimum seconds between atomic persists of the harvested calibration table to the VESCALE_COST_CALIBRATION path (no path = no persistence; digest rotation still re-plans in-process).")
register("VESCALE_COSTAUDIT_HARVEST", "bool", True,
         "Let the per-step auditor harvest tagged ndtimeline spans into the active calibration table (online recalibration); off = audit-only (divergence is reported but the table never moves).")

# --- bench harness ---------------------------------------------------
register("VESCALE_BENCH", "str", None,
         "Which bench rung to run (e.g. `serve`, `redistribute`, `memtrack`, `watchdog`); unset = default MFU line.")
register("VESCALE_BENCH_RUNG", "str", "1.3b",
         "Model size rung for the 1B-sweep bench script.")
register("VESCALE_BENCH_STEP_REPORT", "bool", None,
         "Write a compile-time step report during bench runs; unset = on for CPU, off on TPU.")
register("VESCALE_BENCH_NO_REGISTER", "bool", False,
         "Skip BENCH_r*.json registration (set for child/sub-bench processes).")
register("VESCALE_BENCH_BUDGET_S", "float", 1200.0,
         "Wall-clock budget in seconds for the bench driver.")
register("VESCALE_BENCH_CHILD", "bool", False,
         "Marks a bench subprocess (internal; set by the bench driver).")
register("VESCALE_BENCH_CPU_FALLBACK", "bool", False,
         "Marks the orchestrator's last-resort CPU bench child (internal); the "
         "child flags the stale TPU record through the alert engine "
         "(bench-tpu-stale).")

# --- AOT report scripts ----------------------------------------------
register("VESCALE_AOT_MODEL", "str", "8b",
         "Model config for scripts/aot_8b_report.py (`8b`, `70b`, `405b`, `mixtral`).")
register("VESCALE_AOT_FP8", "bool", False,
         "AOT-report the fp8 variant.")
register("VESCALE_AOT_ZB", "bool", False,
         "AOT-report the zero-bubble schedule variant.")
register("VESCALE_AOT_CHILD", "bool", False,
         "Marks an AOT-report subprocess (internal; set by the driver).")
register("VESCALE_AOT_DEBUG", "bool", False,
         "Verbose AOT-report debugging output.")

# --- entry / misc ----------------------------------------------------
register("VESCALE_DRYRUN_VIRTUAL_CHILD", "bool", False,
         "Marks a virtual-device dry-run subprocess (internal; set by __graft_entry__).")
register("VESCALE_FP8_ON_TPU", "bool", False,
         "Allow the fp8 example on real TPU backends (off = CPU emulation only).")
