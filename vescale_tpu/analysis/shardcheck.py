"""shardcheck — trace-time SPMD/placement analysis over a jaxpr.

The premise (Mesh-TensorFlow arXiv:1811.02084, "On Optimizing the
Communication of Model Parallelism" arXiv:2211.05322): a layout is a static
object over the program graph, so layout *mistakes* are statically
decidable.  GSPMD will silently repair a bad layout at runtime — by
all-gathering a sharded operand into every device (the materialization this
framework exists to avoid) or by inserting resharding collectives — and the
first sign is an OOM or a 4x step time at step 10k.  shardcheck walks the
traced jaxpr (``jax.make_jaxpr`` — the same trace the AOT lowering path
takes) with a symbolic sharding per variable and emits coded findings
(analysis/findings.py) *before* anything runs:

  VSC101  an op forces implicit full materialization of a sharded operand
          (reshape merging a sharded dim under an outer factor, concatenate
          along a sharded dim, gather/sort along a sharded dim)
  VSC102  sharding conflict between operands forces a reshard
  VSC103  Partial placement consumed by a non-linear op (silently wrong
          numerics under veScale semantics)
  VSC105  donation miss: a step input rebuilt as an output but not donated
          (double-buffers params/optimizer state)

plus the source-level VSC104 (collectives under rank-divergent Python
control flow — shared with vescale-lint) when the checked callable's source
is retrievable.

Byte/cost estimates price the implied movement with the SAME per-collective
cost functions auto-plan uses (``collectives.allgather_cost`` et al.), so a
finding's cost column and the planner's objective agree by construction.

The propagation is deliberately conservative: unknown primitives propagate
"replicated, no finding" — shardcheck under-reports rather than cry wolf.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .findings import CODES, Finding, FindingReport, Severity

__all__ = [
    "SymSharding",
    "shardcheck",
    "shardcheck_jaxpr",
    "sym_from_spec",
    "check_transition",
    "check_stage_boundaries",
    "check_param_plan",
]


# ---------------------------------------------------------------- symbolic
@dataclasses.dataclass(frozen=True)
class SymSharding:
    """Symbolic sharding of one intermediate: per tensor dim, the mesh axis
    names sharding it; plus pending-reduction (Partial) axes with their
    reduce op.  The trace-time mirror of a ``DArraySpec``.

    ``partial`` holds DECLARED partials — a veScale ``Partial`` placement on
    an input spec, where the program itself owns the reduction; consuming
    one non-linearly is the VSC103 bug.  ``auto_partial`` holds partials the
    program DERIVES (a dot_general contracting a sharded dim, a reduce over
    a sharded dim): inside a jit program GSPMD inserts the all-reduce at the
    point of use — correct numerics, the expected TP boundary collective —
    so these propagate silently and are cleared at consumption."""

    axes: Tuple[Tuple[str, ...], ...]
    partial: Tuple[Tuple[str, str], ...] = ()  # declared (mesh_axis, reduce_op)
    auto_partial: Tuple[Tuple[str, str], ...] = ()  # derived; GSPMD-resolved

    @staticmethod
    def replicated(ndim: int) -> "SymSharding":
        return SymSharding(tuple(() for _ in range(ndim)))

    @property
    def ndim(self) -> int:
        return len(self.axes)

    def is_sharded(self) -> bool:
        return any(self.axes) or bool(self.partial) or bool(self.auto_partial)

    def sharded_axes(self) -> Tuple[str, ...]:
        out: List[str] = []
        for dims in self.axes:
            out.extend(dims)
        return tuple(out)

    def partial_axes(self) -> Tuple[str, ...]:
        return tuple(a for a, _ in self.partial)

    def drop_partial(self) -> "SymSharding":
        return SymSharding(self.axes, (), ())

    def __str__(self) -> str:
        dims = ",".join("+".join(a) if a else "-" for a in self.axes)
        p = "".join(f" partial({a}:{op})" for a, op in self.partial)
        p += "".join(f" auto({a}:{op})" for a, op in self.auto_partial)
        return f"[{dims}]{p}"


def sym_from_spec(spec, ndim: Optional[int] = None) -> SymSharding:
    """SymSharding from a DArraySpec / placements+mesh / PartitionSpec.

    Accepts a ``DArraySpec`` (uses LOGICAL dims: interleave and ragged
    approximate to their leading dim), a ``jax.sharding.NamedSharding``, or
    a bare ``PartitionSpec`` (with ``ndim``)."""
    from jax.sharding import NamedSharding, PartitionSpec

    if isinstance(spec, PartitionSpec):
        return _sym_from_pspec(spec, ndim if ndim is not None else len(spec))
    if isinstance(spec, NamedSharding):
        return _sym_from_pspec(spec.spec, ndim if ndim is not None else len(spec.spec))
    # DArraySpec
    from ..placements import InterleavedShard, RaggedShard, Shard

    axes: List[List[str]] = [[] for _ in range(spec.ndim)]
    partial: List[Tuple[str, str]] = []
    for i, p in enumerate(spec.placements):
        name = spec.mesh.dim_name(i)
        if type(p) is Shard or isinstance(p, InterleavedShard):
            axes[p.dim].append(name)
        elif isinstance(p, RaggedShard):
            axes[p.dims[0]].append(name)
        elif p.is_partial():
            partial.append((name, p.reduce_op))
    return SymSharding(tuple(tuple(a) for a in axes), tuple(partial))


def _sym_from_pspec(pspec, ndim: int) -> SymSharding:
    axes: List[Tuple[str, ...]] = []
    entries = list(pspec) + [None] * (ndim - len(pspec))
    for e in entries[:ndim]:
        if e is None:
            axes.append(())
        elif isinstance(e, (tuple, list)):
            axes.append(tuple(str(n) for n in e))
        else:
            axes.append((str(e),))
    return SymSharding(tuple(axes))


# ------------------------------------------------------------- primitives
# elementwise-linear in each operand: Partial flows through
_LINEAR_ELTWISE = {
    "add", "sub", "neg", "convert_element_type", "copy", "real", "imag",
    "reduce_precision", "stop_gradient", "cumsum",
}
# nonlinear / order-sensitive elementwise: Partial consumed here is wrong
_NONLINEAR_ELTWISE = {
    "exp", "log", "log1p", "expm1", "tanh", "sin", "cos", "tan", "logistic",
    "erf", "erfc", "erf_inv", "rsqrt", "sqrt", "cbrt", "square", "sign",
    "floor", "ceil", "round", "integer_pow", "pow", "abs", "is_finite",
    "max", "min", "rem", "atan2", "nextafter", "clamp", "and", "or", "xor",
    "not", "eq", "ne", "lt", "le", "gt", "ge", "select_n", "cummax",
    "cummin", "cumprod", "erf_inv", "digamma", "lgamma",
}
_PASSTHROUGH_PARTIAL = {"transpose", "reshape", "broadcast_in_dim", "squeeze",
                        "slice", "expand_dims", "rev", "pad"}
_INNER_JAXPR_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "remat": "jaxpr",
    "remat2": "jaxpr",
    "checkpoint": "jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_jvp_call_jaxpr": "fun_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
}


def _axis_prod(axis_sizes: Dict[str, int], names) -> int:
    out = 1
    for n in names:
        out *= int(axis_sizes.get(n, 1))
    return out


def _full_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape, dtype=np.int64)) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _gather_cost_us(full_bytes: int, axis_sizes: Dict[str, int], axes) -> float:
    """Price an all-gather of a (currently sharded) operand back to full,
    using collectives.py's cost model — the analysis and auto-plan read the
    same objective."""
    from ..collectives import allgather_cost

    cost = 0.0
    for a in axes:
        cost += allgather_cost(full_bytes / 1e9, int(axis_sizes.get(a, 1)))
    return cost


def _src(eqn) -> str:
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return f"{eqn.primitive.name} @ {frame.file_name}:{frame.start_line}"
    except Exception:
        pass
    return eqn.primitive.name


class _Checker:
    def __init__(self, axis_sizes: Dict[str, int], report: FindingReport,
                 min_bytes: int):
        self.axis_sizes = dict(axis_sizes)
        self.report = report
        self.min_bytes = int(min_bytes)
        self._flagged: set = set()  # dedup (code, where) pairs

    # ------------------------------------------------------------- helpers
    def _emit(self, code: str, message: str, eqn=None, *, mesh_dim=None,
              bytes_est=None, cost_us=None, severity=None) -> None:
        where = _src(eqn) if eqn is not None else None
        key = (code, where, message)
        if key in self._flagged:
            return
        self._flagged.add(key)
        self.report.add(Finding(CODES[code], message, where=where,
                                mesh_dim=mesh_dim, bytes_est=bytes_est,
                                cost_us=cost_us, severity=severity))

    def _materialize(self, eqn, aval, sym: SymSharding, why: str,
                     severity: Optional[Severity] = None) -> None:
        full = _full_bytes(aval)
        if full < self.min_bytes:
            return
        axes = sym.sharded_axes()
        n = _axis_prod(self.axis_sizes, axes)
        if n <= 1:
            return
        self._emit(
            "VSC101",
            f"{why}: a {tuple(aval.shape)} {np.dtype(aval.dtype).name} operand "
            f"sharded {n}-way over {list(axes)} must be gathered to full size "
            "on every device",
            eqn,
            mesh_dim=axes[0] if axes else None,
            bytes_est=full,
            cost_us=_gather_cost_us(full, self.axis_sizes, axes),
            severity=severity,
        )

    def _partial_misuse(self, eqn, sym: SymSharding, why: str) -> None:
        axes = sym.partial_axes()
        self._emit(
            "VSC103",
            f"{why}: the operand is Partial({','.join(axes)}) — pending "
            "reduction; applying a non-linear op before reducing computes "
            "f(x_i) per replica instead of f(sum_i x_i)",
            eqn,
            mesh_dim=axes[0] if axes else None,
        )

    # ------------------------------------------------------------ the walk
    def run(self, closed_jaxpr, in_syms: Sequence[SymSharding]) -> List[SymSharding]:
        jaxpr = closed_jaxpr.jaxpr
        env: Dict[Any, SymSharding] = {}

        def write(var, sym: SymSharding) -> None:
            env[var] = sym

        def read(atom) -> SymSharding:
            if hasattr(atom, "val"):  # Literal
                ndim = getattr(np.asarray(atom.val), "ndim", 0)
                return SymSharding.replicated(ndim)
            return env.get(atom, SymSharding.replicated(len(getattr(atom.aval, "shape", ()))))

        for var in jaxpr.constvars:
            write(var, SymSharding.replicated(len(getattr(var.aval, "shape", ()))))
        for var, sym in zip(jaxpr.invars, in_syms):
            write(var, sym)
        for extra in jaxpr.invars[len(in_syms):]:
            write(extra, SymSharding.replicated(len(getattr(extra.aval, "shape", ()))))

        for eqn in jaxpr.eqns:
            outs = self._eqn(eqn, [read(v) for v in eqn.invars])
            for var, sym in zip(eqn.outvars, outs):
                nd = len(getattr(var.aval, "shape", ()))
                if sym.ndim != nd:  # defensive: never poison the env
                    sym = SymSharding.replicated(nd)
                write(var, sym)
        return [read(v) for v in jaxpr.outvars]

    def _sub(self, closed, in_syms) -> List[SymSharding]:
        try:
            return self.run(closed, in_syms)
        except Exception:
            return [
                SymSharding.replicated(len(getattr(v.aval, "shape", ())))
                for v in closed.jaxpr.outvars
            ]

    # ------------------------------------------------------- per-primitive
    def _eqn(self, eqn, ins: List[SymSharding]) -> List[SymSharding]:
        name = eqn.primitive.name
        out_avals = [v.aval for v in eqn.outvars]

        try:
            if name in _INNER_JAXPR_PRIMS:
                closed = eqn.params.get(_INNER_JAXPR_PRIMS[name])
                if closed is None:
                    return self._default(eqn, ins)
                return self._sub(closed, ins)
            if name == "scan":
                return self._scan(eqn, ins)
            if name == "while":
                return self._while(eqn, ins)
            if name == "cond":
                return self._cond(eqn, ins)
            if name in ("sharding_constraint", "device_put"):
                return self._constraint(eqn, ins)
            if name == "dot_general":
                return [self._dot_general(eqn, ins)]
            if name == "reshape":
                return [self._reshape(eqn, ins[0])]
            if name == "transpose":
                perm = eqn.params["permutation"]
                return [SymSharding(tuple(ins[0].axes[p] for p in perm),
                                    ins[0].partial, ins[0].auto_partial)]
            if name == "broadcast_in_dim":
                return [self._broadcast(eqn, ins[0])]
            if name == "squeeze":
                dims = set(eqn.params["dimensions"])
                axes = tuple(a for d, a in enumerate(ins[0].axes) if d not in dims)
                return [SymSharding(axes, ins[0].partial, ins[0].auto_partial)]
            if name == "expand_dims":
                dims = set(eqn.params["dimensions"])
                nd = len(out_avals[0].shape)
                it = iter(ins[0].axes)
                axes = tuple(() if d in dims else next(it) for d in range(nd))
                return [SymSharding(axes, ins[0].partial, ins[0].auto_partial)]
            if name == "concatenate":
                return [self._concatenate(eqn, ins)]
            if name.startswith("reduce_") or name in ("argmax", "argmin"):
                return [self._reduce(eqn, ins, name)]
            if name in ("sort", "top_k"):
                return self._sort(eqn, ins, out_avals)
            if name == "gather":
                return [self._gather(eqn, ins)]
            if name in ("slice", "dynamic_slice", "dynamic_update_slice", "pad", "rev"):
                return [self._slicelike(eqn, ins, out_avals[0])]
            if name == "iota":
                return [SymSharding.replicated(len(out_avals[0].shape))]
            return self._default(eqn, ins)
        except Exception:
            return [
                SymSharding.replicated(len(getattr(a, "shape", ())))
                for a in out_avals
            ]

    # --- generic elementwise --------------------------------------------
    def _default(self, eqn, ins: List[SymSharding]) -> List[SymSharding]:
        name = eqn.primitive.name
        out_avals = [v.aval for v in eqn.outvars]
        nd = len(getattr(out_avals[0], "shape", ()))

        arrayish = [s for s in ins if s.ndim == nd]
        partial_ins = [s for s in ins if s.partial]

        known_eltwise = (
            name in _LINEAR_ELTWISE or name in _NONLINEAR_ELTWISE or name in ("mul", "div")
        )
        if partial_ins and known_eltwise:
            if name in _NONLINEAR_ELTWISE:
                self._partial_misuse(eqn, partial_ins[0], f"non-linear op `{name}`")
            elif name in ("add", "sub"):
                # additive mix of Partial and non-Partial inflates the sum
                # n-fold; Partial+Partial on the same axes is fine (linear)
                psets = {s.partial for s in ins if s.ndim > 0 or s.partial}
                if any(not s.partial for s in ins) or len({s.partial for s in partial_ins}) > 1:
                    if any(not s.partial and (s.ndim == nd) for s in ins):
                        self._partial_misuse(
                            eqn, partial_ins[0],
                            f"additive op `{name}` mixing Partial and non-Partial operands",
                        )
            elif name in ("mul", "div") and len(partial_ins) > 1:
                self._partial_misuse(eqn, partial_ins[0], f"product of two Partial operands in `{name}`")
            elif name == "div" and ins[-1].partial:
                self._partial_misuse(eqn, ins[-1], "Partial operand as divisor")

        if not arrayish:
            return [SymSharding.replicated(len(getattr(a, "shape", ()))) for a in out_avals]

        if not known_eltwise and name not in ("select_n",):
            # unknown primitive: stay silent and conservative
            return [SymSharding.replicated(len(getattr(a, "shape", ()))) for a in out_avals]

        # merge aligned dims; conflicting non-empty axis sets => reshard
        axes: List[Tuple[str, ...]] = []
        for d in range(nd):
            cands = [s.axes[d] for s in arrayish if s.axes[d]]
            uniq = {c for c in cands}
            if len(uniq) > 1:
                shapes = tuple(getattr(out_avals[0], "shape", ()))
                n0 = _axis_prod(self.axis_sizes, next(iter(uniq)))
                full = _full_bytes(out_avals[0])
                if full >= self.min_bytes:
                    self._emit(
                        "VSC102",
                        f"operands of `{name}` disagree on dim {d} sharding "
                        f"({sorted(','.join(u) for u in uniq)}); the partitioner "
                        "must reshard one operand",
                        eqn,
                        bytes_est=full // max(1, n0),
                    )
            axes.append(cands[0] if cands else ())
        partial = partial_ins[0].partial if partial_ins else ()
        # derived partials: nonlinear consumption is where GSPMD inserts the
        # implicit all-reduce — the value is fully reduced downstream
        auto: Dict[str, str] = {}
        if name not in _NONLINEAR_ELTWISE:
            for s in arrayish:
                for a, op in s.auto_partial:
                    auto.setdefault(a, op)
        out = SymSharding(tuple(axes), partial, tuple(sorted(auto.items())))
        return [out if len(getattr(a, "shape", ())) == nd
                else SymSharding.replicated(len(getattr(a, "shape", ())))
                for a in out_avals]

    # --- structured ops ---------------------------------------------------
    def _dot_general(self, eqn, ins: List[SymSharding]) -> SymSharding:
        lhs, rhs = ins[0], ins[1]
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        if lhs.partial and rhs.partial:
            self._partial_misuse(eqn, lhs, "dot_general of two Partial operands")
        partial: List[Tuple[str, str]] = list(lhs.partial) + list(rhs.partial)
        # a contraction over a sharded dim yields a DERIVED partial: GSPMD
        # all-reduces it at the point of use (the expected TP collective)
        auto: List[Tuple[str, str]] = list(lhs.auto_partial) + list(rhs.auto_partial)
        for dl, dr in zip(lc, rc):
            for a in set(lhs.axes[dl]) | set(rhs.axes[dr]):
                auto.append((a, "sum"))
        lhs_free = [d for d in range(lhs.ndim) if d not in lc and d not in lb]
        rhs_free = [d for d in range(rhs.ndim) if d not in rc and d not in rb]
        axes: List[Tuple[str, ...]] = []
        for dl, dr in zip(lb, rb):
            both = lhs.axes[dl] or rhs.axes[dr]
            if lhs.axes[dl] and rhs.axes[dr] and lhs.axes[dl] != rhs.axes[dr]:
                self._emit(
                    "VSC102",
                    f"batch dims of dot_general sharded differently "
                    f"({list(lhs.axes[dl])} vs {list(rhs.axes[dr])})",
                    eqn,
                )
            axes.append(both)
        axes.extend(lhs.axes[d] for d in lhs_free)
        axes.extend(rhs.axes[d] for d in rhs_free)
        # same mesh axis appearing on two output dims (or output + partial):
        # the partitioner must reshard one usage
        seen: Dict[str, int] = {}
        for dims in axes:
            for a in dims:
                seen[a] = seen.get(a, 0) + 1
        for a, _op in partial + auto:
            seen[a] = seen.get(a, 0) + 1
        dup = [a for a, k in seen.items() if k > 1]
        if dup:
            self._emit(
                "VSC102",
                f"mesh axis {dup[0]!r} used by multiple dot_general operands "
                "in conflicting roles; a reshard will be inserted",
                eqn,
                mesh_dim=dup[0],
            )
            axes = [tuple(a for a in dims if a not in dup) for dims in axes]
            partial = [(a, op) for a, op in partial if a not in dup]
            auto = [(a, op) for a, op in auto if a not in dup]
        pdict: Dict[str, str] = {}
        for a, op in partial:
            pdict.setdefault(a, op)
        adict: Dict[str, str] = {}
        for a, op in auto:
            if a not in pdict:
                adict.setdefault(a, op)
        return SymSharding(tuple(axes), tuple(sorted(pdict.items())),
                           tuple(sorted(adict.items())))

    def _reshape(self, eqn, x: SymSharding) -> SymSharding:
        aval_in = eqn.invars[0].aval
        aval_out = eqn.outvars[0].aval
        if eqn.params.get("dimensions") is not None:
            if x.is_sharded():
                self._materialize(eqn, aval_in, x, "permuting reshape of a sharded operand",
                                  severity=Severity.WARNING)
            return SymSharding.replicated(len(aval_out.shape))
        in_shape = tuple(aval_in.shape)
        out_shape = tuple(aval_out.shape)
        groups = _reshape_groups(in_shape, out_shape)
        axes: List[Tuple[str, ...]] = [() for _ in out_shape]
        for in_dims, out_dims in groups:
            for pos, d in enumerate(in_dims):
                if not x.axes[d]:
                    continue
                n = _axis_prod(self.axis_sizes, x.axes[d])
                outer_extent = int(np.prod([in_shape[q] for q in in_dims[:pos]], dtype=np.int64)) if pos else 1
                lead_out = out_dims[0]
                if pos == 0 or outer_extent == 1:
                    # outermost factor of the group: block order is preserved;
                    # sharding lands on the group's leading output dim
                    if out_shape[lead_out] % n == 0:
                        axes[lead_out] = tuple(axes[lead_out]) + x.axes[d]
                        continue
                self._materialize(
                    eqn, aval_in, SymSharding(
                        tuple(x.axes[q] if q == d else () for q in range(x.ndim))
                    ),
                    f"reshape {in_shape} -> {out_shape} merges sharded dim {d} "
                    "under an outer factor (shard block order not preserved)",
                )
        return SymSharding(tuple(axes), x.partial, x.auto_partial)

    def _broadcast(self, eqn, x: SymSharding) -> SymSharding:
        bd = eqn.params["broadcast_dimensions"]
        aval_in = eqn.invars[0].aval
        aval_out = eqn.outvars[0].aval
        axes: List[Tuple[str, ...]] = [() for _ in aval_out.shape]
        for i, d in enumerate(bd):
            if aval_in.shape[i] == aval_out.shape[d]:
                axes[d] = x.axes[i]
        return SymSharding(tuple(axes), x.partial, x.auto_partial)

    def _concatenate(self, eqn, ins: List[SymSharding]) -> SymSharding:
        dim = eqn.params["dimension"]
        aval_out = eqn.outvars[0].aval
        for i, s in enumerate(ins):
            if s.axes[dim]:
                self._materialize(
                    eqn, eqn.invars[i].aval,
                    SymSharding(tuple(s.axes[q] if q == dim else () for q in range(s.ndim))),
                    f"concatenate along sharded dim {dim}",
                )
        axes = []
        for d in range(len(aval_out.shape)):
            if d == dim:
                axes.append(())
            else:
                cands = [s.axes[d] for s in ins if s.axes[d]]
                axes.append(cands[0] if cands else ())
        return SymSharding(tuple(axes))

    def _reduce(self, eqn, ins: List[SymSharding], name: str) -> SymSharding:
        x = ins[0]
        dims = set(eqn.params.get("axes", ()))
        if x.partial and name in ("reduce_max", "reduce_min", "reduce_prod",
                                  "argmax", "argmin", "reduce_and", "reduce_or"):
            self._partial_misuse(eqn, x, f"non-linear reduction `{name}`")
        reduced_axes: List[str] = []
        axes: List[Tuple[str, ...]] = []
        for d in range(x.ndim):
            if d in dims:
                reduced_axes.extend(x.axes[d])
            else:
                axes.append(x.axes[d])
        partial = list(x.partial)
        auto = list(x.auto_partial)
        op = {"reduce_sum": "sum", "reduce_max": "max", "reduce_min": "min"}.get(name)
        if reduced_axes:
            if op is not None:
                # reducing over a sharded dim derives a partial GSPMD will
                # all-reduce at the point of use — auto, not declared
                auto.extend((a, op) for a in reduced_axes)
            elif name in ("argmax", "argmin"):
                self._materialize(
                    eqn, eqn.invars[0].aval,
                    SymSharding(tuple(x.axes[d] if d in dims else () for d in range(x.ndim))),
                    f"`{name}` over a sharded dim",
                    severity=Severity.WARNING,
                )
        pdict: Dict[str, str] = {}
        for a, o in partial:
            pdict.setdefault(a, o)
        adict: Dict[str, str] = {}
        for a, o in auto:
            if a not in pdict:
                adict.setdefault(a, o)
        return SymSharding(tuple(axes), tuple(sorted(pdict.items())),
                           tuple(sorted(adict.items())))

    def _sort(self, eqn, ins, out_avals) -> List[SymSharding]:
        dim = eqn.params.get("dimension", len(getattr(out_avals[0], "shape", ())) - 1)
        for i, s in enumerate(ins):
            if s.ndim > dim and s.axes[dim]:
                self._materialize(
                    eqn, eqn.invars[i].aval,
                    SymSharding(tuple(s.axes[q] if q == dim else () for q in range(s.ndim))),
                    f"`{eqn.primitive.name}` along sharded dim {dim}",
                    severity=Severity.WARNING,
                )
        return [SymSharding.replicated(len(getattr(a, "shape", ()))) for a in out_avals]

    def _gather(self, eqn, ins: List[SymSharding]) -> SymSharding:
        operand = ins[0]
        aval_out = eqn.outvars[0].aval
        dnums = eqn.params["dimension_numbers"]
        slice_sizes = eqn.params.get("slice_sizes", ())
        operand_aval = eqn.invars[0].aval
        for d in getattr(dnums, "start_index_map", ()):
            if d < operand.ndim and operand.axes[d] and (
                not slice_sizes or slice_sizes[d] != operand_aval.shape[d]
            ):
                self._materialize(
                    eqn, operand_aval,
                    SymSharding(tuple(operand.axes[q] if q == d else () for q in range(operand.ndim))),
                    f"gather indexes into sharded dim {d}",
                    severity=Severity.WARNING,
                )
        return SymSharding.replicated(len(aval_out.shape))

    def _slicelike(self, eqn, ins: List[SymSharding], out_aval) -> SymSharding:
        x = ins[0]
        nd = len(getattr(out_aval, "shape", ()))
        if x.ndim != nd:
            return SymSharding.replicated(nd)
        in_shape = tuple(eqn.invars[0].aval.shape)
        out_shape = tuple(out_aval.shape)
        axes = tuple(
            x.axes[d] if d < len(in_shape) and in_shape[d] == out_shape[d] else ()
            for d in range(nd)
        )
        return SymSharding(axes, x.partial, x.auto_partial)

    def _constraint(self, eqn, ins: List[SymSharding]) -> List[SymSharding]:
        shd = eqn.params.get("sharding")
        if shd is None:
            shardings = eqn.params.get("devices") or eqn.params.get("shardings")
            shd = shardings[0] if isinstance(shardings, (tuple, list)) and shardings else None
        out_nd = len(getattr(eqn.outvars[0].aval, "shape", ()))
        sym = None
        try:
            pspec = getattr(shd, "spec", None)
            if pspec is not None:
                sym = _sym_from_pspec(pspec, out_nd)
        except Exception:
            sym = None
        if sym is None:
            sym = ins[0] if ins and ins[0].ndim == out_nd else SymSharding.replicated(out_nd)
        return [sym]

    # --- control flow -----------------------------------------------------
    def _scan(self, eqn, ins: List[SymSharding]) -> List[SymSharding]:
        closed = eqn.params["jaxpr"]
        n_consts = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        body_in: List[SymSharding] = []
        for i, s in enumerate(ins):
            if i < n_consts + n_carry:
                body_in.append(s)
            else:  # xs: leading scan dim stripped
                body_in.append(
                    SymSharding(s.axes[1:], s.partial, s.auto_partial) if s.ndim else s
                )
        outs = self._sub(closed, body_in)
        carry_out = outs[:n_carry]
        ys = [SymSharding(((),) + s.axes, s.partial, s.auto_partial)
              for s in outs[n_carry:]]
        return carry_out + ys

    def _while(self, eqn, ins: List[SymSharding]) -> List[SymSharding]:
        closed = eqn.params["body_jaxpr"]
        n_cconst = eqn.params["cond_nconsts"]
        n_bconst = eqn.params["body_nconsts"]
        carry = ins[n_cconst + n_bconst:]
        return self._sub(closed, list(ins[n_cconst:n_cconst + n_bconst]) + list(carry))

    def _cond(self, eqn, ins: List[SymSharding]) -> List[SymSharding]:
        branches = eqn.params["branches"]
        outs = None
        for br in branches:
            o = self._sub(br, ins[1:])
            outs = o if outs is None else outs
        return outs if outs is not None else [
            SymSharding.replicated(len(getattr(v.aval, "shape", ())))
            for v in eqn.outvars
        ]


def _reshape_groups(in_shape, out_shape):
    """Balanced factor groups of a reshape: list of (in_dims, out_dims) with
    equal products (the standard merge/split decomposition)."""
    i = j = 0
    groups = []
    ni, nj = len(in_shape), len(out_shape)
    while i < ni and j < nj:
        in_dims, out_dims = [i], [j]
        pi, pj = in_shape[i], out_shape[j]
        while pi != pj:
            if pi < pj:
                i += 1
                in_dims.append(i)
                pi *= in_shape[i]
            else:
                j += 1
                out_dims.append(j)
                pj *= out_shape[j]
        groups.append((in_dims, out_dims))
        i += 1
        j += 1
    while i < ni:
        groups.append(([i], []))
        i += 1
    while j < nj:
        groups.append(([], [j]))
        j += 1
    return groups


# ------------------------------------------------------------ entry points
def _leaf_sym(leaf, entry, ndim: int) -> SymSharding:
    from jax.sharding import NamedSharding, PartitionSpec

    if entry is not None:
        if isinstance(entry, SymSharding):
            return entry
        if isinstance(entry, (PartitionSpec, NamedSharding)):
            return sym_from_spec(entry, ndim)
        return sym_from_spec(entry, ndim)  # DArraySpec
    shd = getattr(leaf, "sharding", None)
    if shd is not None and isinstance(shd, NamedSharding):
        return sym_from_spec(shd, ndim)
    return SymSharding.replicated(ndim)


def _axis_sizes_from(args, in_specs, mesh) -> Dict[str, int]:
    import jax

    if mesh is not None:
        if isinstance(mesh, dict):  # bare axis-size map: no devices needed
            return {str(k): int(v) for k, v in mesh.items()}
        jm = getattr(mesh, "jax_mesh", mesh)
        return dict(zip(jm.axis_names, jm.devices.shape))
    sizes: Dict[str, int] = {}
    for leaf in jax.tree_util.tree_leaves(args):
        shd = getattr(leaf, "sharding", None)
        jm = getattr(shd, "mesh", None)
        if jm is not None and hasattr(jm, "axis_names"):
            try:
                sizes.update(dict(zip(jm.axis_names, jm.devices.shape)))
            except Exception:
                sizes.update(getattr(jm, "shape", {}) or {})
    for entry in jax.tree_util.tree_leaves(
        in_specs, is_leaf=lambda x: hasattr(x, "mesh")
    ) if in_specs is not None else []:
        m = getattr(entry, "mesh", None)
        if m is not None and hasattr(m, "mesh_dim_names"):
            sizes.update(dict(zip(m.mesh_dim_names, m.shape)))
    return sizes


def shardcheck(
    fn,
    *args,
    in_specs=None,
    donate_argnums: Optional[Sequence[int]] = (),
    static_argnums: Sequence[int] = (),
    mesh=None,
    name: Optional[str] = None,
    min_bytes: int = 1 << 20,
    check_source: bool = True,
    **kwargs,
) -> FindingReport:
    """Statically analyze ``fn(*args, **kwargs)`` for placement hazards.

    ``args`` may be real (sharded) jax arrays, ``ShapeDtypeStruct``s, or any
    pytrees thereof.  Input shardings come from, in priority order:
    ``in_specs`` (a pytree matching ``args`` whose leaves are DArraySpec /
    PartitionSpec / SymSharding / None), then each array leaf's own
    ``NamedSharding``, else replicated.  ``mesh`` (a DeviceMesh or jax Mesh)
    supplies axis sizes when no sharded leaf carries one.

    ``donate_argnums``: the donation the caller's jit uses — inputs that are
    rebuilt as same-shape outputs but NOT donated raise VSC105 (they double
    the resident footprint of params/optimizer state).  Pass ``None`` when
    the caller's donation is UNKNOWN (e.g. analyzing someone else's jitted
    fn): the donation check is skipped rather than guessed.

    ``static_argnums``: the caller's jit static args — excluded from the
    trace (and from the input-leaf/spec alignment), exactly as the caller's
    ``jax.jit(fn, static_argnums=...)`` treats them.

    ``min_bytes``: findings about operands smaller than this are suppressed
    (default 1 MiB — a gathered scalar is not a hazard).
    """
    import jax

    report = FindingReport(name or getattr(fn, "__name__", "program"))

    inner = getattr(fn, "_jitted", fn)  # make_train_step exposes the raw jit
    static_set = set(static_argnums or ())
    try:
        closed = jax.make_jaxpr(inner, static_argnums=tuple(static_set))(*args, **kwargs)
    except Exception as e:
        report.add(Finding(
            CODES["VSC109"],
            f"could not trace program for shardcheck: {e!r}",
            severity=Severity.INFO,
        ))
        return report

    # dynamic input leaves, in invar order (static args produce no invars)
    dyn_leaves: List[Any] = []
    arg_of_leaf: List[int] = []
    for i, a in enumerate(args):
        if i in static_set:
            continue
        ls = jax.tree_util.tree_leaves(a)
        dyn_leaves.extend(ls)
        arg_of_leaf.extend([i] * len(ls))
    kw_leaves = jax.tree_util.tree_leaves(kwargs)
    dyn_leaves.extend(kw_leaves)
    arg_of_leaf.extend([-1] * len(kw_leaves))

    spec_leaves: List[Any]
    if in_specs is not None:
        spec_leaves = jax.tree_util.tree_leaves(
            in_specs, is_leaf=lambda x: x is None or not isinstance(x, (list, dict, tuple))
        )
        if len(spec_leaves) != len(dyn_leaves):
            spec_leaves = list(spec_leaves) + [None] * (len(dyn_leaves) - len(spec_leaves))
    else:
        spec_leaves = [None] * len(dyn_leaves)

    in_syms = [
        _leaf_sym(leaf, entry, len(getattr(leaf, "shape", ())))
        for leaf, entry in zip(dyn_leaves, spec_leaves)
    ]
    axis_sizes = _axis_sizes_from((args, kwargs), in_specs, mesh)

    checker = _Checker(axis_sizes, report, min_bytes)
    try:
        checker.run(closed, in_syms)
    except Exception as e:
        report.add(Finding(
            CODES["VSC109"],
            f"shardcheck walk aborted: {e!r}",
            severity=Severity.INFO,
        ))

    if donate_argnums is not None:
        _check_donation(report, closed, arg_of_leaf, donate_argnums, min_bytes)

    if check_source:
        _check_fn_source(report, fn)
    return report


def shardcheck_jaxpr(
    closed_jaxpr,
    in_syms: Sequence[SymSharding],
    axis_sizes: Dict[str, int],
    name: str = "jaxpr",
    min_bytes: int = 1 << 20,
) -> FindingReport:
    """The raw engine: analyze an already-traced ClosedJaxpr with explicit
    per-invar symbolic shardings (what auto-plan v2's scorer calls)."""
    report = FindingReport(name)
    checker = _Checker(axis_sizes, report, min_bytes)
    try:
        checker.run(closed_jaxpr, list(in_syms))
    except Exception as e:
        report.add(Finding(
            CODES["VSC109"], f"shardcheck walk aborted: {e!r}",
            severity=Severity.INFO,
        ))
    return report


def _check_donation(report, closed, arg_of_leaf, donate_argnums, min_bytes) -> None:
    donate_argnums = set(donate_argnums or ())
    invars = closed.jaxpr.invars
    outvars = closed.jaxpr.outvars
    out_sigs: Dict[Tuple, int] = {}
    for v in outvars:
        aval = getattr(v, "aval", None)
        if aval is None or not getattr(aval, "shape", None):
            continue
        key = (tuple(aval.shape), np.dtype(aval.dtype).str)
        out_sigs[key] = out_sigs.get(key, 0) + 1
    missed = 0
    missed_bytes = 0
    for idx, v in enumerate(invars):
        if idx >= len(arg_of_leaf):
            break
        argnum = arg_of_leaf[idx]
        if argnum < 0 or argnum in donate_argnums:
            continue
        aval = v.aval
        if not getattr(aval, "shape", None):
            continue
        b = _full_bytes(aval)
        if b < min_bytes:
            continue
        key = (tuple(aval.shape), np.dtype(aval.dtype).str)
        if out_sigs.get(key, 0) > 0:
            out_sigs[key] -= 1
            missed += 1
            missed_bytes += b
    if missed:
        report.add(Finding(
            CODES["VSC105"],
            f"{missed} large input buffer(s) (~{missed_bytes / 2**20:.1f} MiB "
            "logical) are rebuilt as same-shape outputs but not donated — "
            "each lives twice during the step (pass donate_argnums)",
            bytes_est=missed_bytes,
        ))


def _check_fn_source(report, fn) -> None:
    """VSC104 on the checked callable's own source, when retrievable."""
    import inspect

    try:
        target = getattr(fn, "__wrapped__", fn)
        src = inspect.getsource(inspect.unwrap(target))
        filename = inspect.getsourcefile(inspect.unwrap(target)) or "<source>"
    except (OSError, TypeError):
        return
    import textwrap

    from .lint import rank_divergence_findings

    try:
        report.extend(rank_divergence_findings(textwrap.dedent(src), filename))
    except SyntaxError:
        pass


# ------------------------------------------------- redistribute / pipeline
def check_transition(src_spec, dst_spec, where: Optional[str] = None) -> List[Finding]:
    """Findings for one ``redistribute(src -> dst)``: VSC106 (error, with
    the planner's structured decline code in the message) when the move
    would hit the logical-materializing fallback; VSC108 (info, costed)
    when the multi-hop planner serves it.  With the quant-hop gate on
    (``VESCALE_REDISTRIBUTE_QUANT``) the quantized-route decision is
    surfaced like every other hop: VSC128 (info) when the cost model takes
    the lossy int8 hop, the recorded VSC127 decline otherwise."""
    from ..redistribute import classify_transition
    from ..redistribute_plan import decline_finding, plan_redistribute, quant_outcome

    if src_spec == dst_spec:
        return []
    quant_findings: List[Finding] = []
    label = where or f"{list(map(str, src_spec.placements))} -> {list(map(str, dst_spec.placements))}"
    qo = quant_outcome(src_spec, dst_spec)
    if qo is not None:
        verdict, payload = qo
        if verdict == "taken":
            quant_findings.append(Finding(
                CODES["VSC128"],
                f"cost model routes this transition through a lossy "
                f"int8-quantized {'/'.join(payload.collectives)} hop "
                f"(~{payload.bytes_moved / 2**20:.2f} MiB packed vs "
                f"~{payload.bytes_raw / 2**20:.2f} MiB raw per device)",
                where=label,
                bytes_est=payload.bytes_moved,
            ))
        elif payload is not None:
            qf = payload.finding()
            qf.where = label
            quant_findings.append(qf)
    tier = classify_transition(src_spec, dst_spec)
    if tier == "fallback":
        decline = decline_finding(src_spec, dst_spec)
        df = decline.finding()
        df.where = label
        return quant_findings + [Finding(
            CODES["VSC106"],
            f"transition would materialize the logical tensor "
            f"(~{src_spec.logical_bytes() / 2**20:.1f} MiB vs "
            f"~{max(src_spec.per_shard_bytes(), dst_spec.per_shard_bytes()) / 2**20:.1f} MiB "
            f"per shard); planner declined [{decline.code}]: {decline.message}",
            where=label,
            bytes_est=src_spec.logical_bytes(),
        ), df]
    if tier == "planned":
        plan = plan_redistribute(src_spec, dst_spec)
        if plan is not None:
            n_quant = sum(1 for h in plan.hops if h.kind == "quant")
            return quant_findings + [Finding(
                CODES["VSC108"],
                f"resolved by a {len(plan.hops)}-hop plan moving "
                f"~{plan.bytes_moved / 2**20:.2f} MiB per device"
                + (f" ({n_quant} int8-quantized hop(s))" if n_quant else ""),
                where=label,
                bytes_est=plan.bytes_moved,
            )]
    return quant_findings


def check_stage_boundaries(
    out_specs: Sequence,
    in_specs: Sequence,
    labels: Optional[Sequence[str]] = None,
    name: str = "pipeline",
) -> FindingReport:
    """Cross-stage resharding audit for a pipeline split: stage i's output
    spec vs stage i+1's input spec, each boundary classified through the
    REAL redistribute dispatch (VSC106 on fallback, VSC108 info when the
    multi-hop planner carries it)."""
    report = FindingReport(name)
    for i, (o, nxt) in enumerate(zip(out_specs, in_specs)):
        if o is None or nxt is None:
            continue
        label = labels[i] if labels and i < len(labels) else f"stage{i}->stage{i + 1}"
        report.extend(check_transition(o, nxt, where=label))
    return report


def check_param_plan(param_plan: Dict[str, Any], mesh, name: str = "param_plan") -> FindingReport:
    """VSC107 audit of a dmodule parameter plan: placements that are never
    right for parameters (Partial — a param is a value, not a pending
    reduction) or that cannot bind to the mesh (axis index out of range)."""
    from ..placements import normalize_placements

    report = FindingReport(name)
    for pattern, placements in (param_plan or {}).items():
        try:
            normalized = normalize_placements(placements, mesh.ndim, None)
        except ValueError as e:
            report.add(Finding(
                CODES["VSC107"],
                f"plan entry {pattern!r} does not normalize: {e}",
                where=pattern,
                severity=Severity.ERROR,
            ))
            continue
        for i, p in enumerate(normalized):
            if p.is_partial():
                report.add(Finding(
                    CODES["VSC107"],
                    f"plan entry {pattern!r} places a parameter as Partial on "
                    f"mesh dim {i} — parameters are values, not pending "
                    "reductions; use Replicate (grads sync via GSPMD)",
                    where=pattern,
                    mesh_dim=mesh.mesh_dim_names[i] if i < len(mesh.mesh_dim_names) else None,
                    severity=Severity.ERROR,
                ))
    return report
