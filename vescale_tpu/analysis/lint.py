"""vescale-lint — AST enforcement of the framework invariants PRs 1-5
established by convention.

Five rules, each a lesson this codebase already paid for once:

  VSC201  every ``VESCALE_*`` env READ goes through ``analysis.envreg``
          (``os.environ.get``/``os.getenv``/``[...]``/``in`` of a
          VESCALE name outside the registry module).  Writes —
          ``os.environ[...] = ``, ``setdefault``, ``pop``, ``del`` — are
          config propagation to children and stay legal.
  VSC202  every ``VESCALE_*`` string literal names a REGISTERED var (or a
          prefix of one, for docstring families like VESCALE_IO_BACKOFF_*)
          — unregistered knobs are undocumented knobs.
  VSC203  a rebindable module hook slot (any name declared ``global`` in
          some function, or containing "hook") must never be bound to a
          lambda: the gating contract asserts dormant hooks by IDENTITY
          against module-level named no-op functions.
  VSC204  a function installed via ``signal.signal`` must stay
          async-signal-safe: no lock construction/acquisition, no IO, no
          logging, no array allocation in the handler frame.
  VSC205  no bare ``except:`` (or ``except BaseException:``) without a
          re-raise inside a loop — retry loops that swallow
          ``KeyboardInterrupt`` cannot be Ctrl-C'd out of.
  VSC206  every ``pallas_call`` lives under ``vescale_tpu/kernels/`` —
          kernels reached any other way bypass the ``VESCALE_KERNELS``
          dispatch contract (off-mode byte-identity, interpret-mode
          parity coverage, dispatch/fallback telemetry; docs/kernels.md).
  VSC207  no ad-hoc warn-once latches: a function that both calls
          ``warnings.warn``/``<log>.warning`` AND touches a "warned"
          latch is a hand-rolled alert with no lifecycle — no resolve,
          no dedup window, no /alerts visibility.  Route it through
          ``telemetry.alerts.raise_alert`` (the engine dedups and
          resolves) or annotate the legacy fallback.  The alert engine
          itself (telemetry/alerts.py owns the ONE sanctioned fallback
          latch) is exempt.

  VSC208  a priced decision must enter the cost-audit ledger: PACKAGE
          code (files under vescale_tpu/ — tests, scripts and bench
          call the cost model to inspect it, not to decide) that calls
          ``simulate_schedule``/``estimate_stage_costs`` inside a
          function with no ``record_prediction`` reference is choosing
          by a prediction nobody will ever audit against reality
          (telemetry/costaudit.py).  Record the prediction, or annotate
          the site.

Plus VSC104 (shared with shardcheck): collective calls under
rank-divergent ``if``/``while`` conditions — the classic SPMD deadlock.

Suppression: append ``# vescale-lint: disable=VSC201`` (comma-separated
codes, or ``disable=all``) to the offending line.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import CODES, Finding, FindingReport

__all__ = [
    "lint_source",
    "lint_paths",
    "rank_divergence_findings",
    "iter_python_files",
]

_ENV_NAME = re.compile(r"VESCALE_[A-Z0-9_]+")
_DISABLE = re.compile(r"#\s*vescale-lint:\s*disable=([A-Za-z0-9,_ ]+|all)")

# names whose call inside a signal handler frame is unsafe (locks, IO,
# logging, allocation); attribute calls checked against the same set
_SIGNAL_UNSAFE = {
    "acquire", "wait", "join", "Lock", "RLock", "Condition", "Semaphore",
    "BoundedSemaphore", "open", "print", "log", "debug", "info", "warning",
    "error", "exception", "write", "flush", "array", "asarray", "zeros",
    "ones", "empty",
}

# rank-ish identifiers in a condition that make control flow rank-divergent
_RANK_TOKENS = {
    "rank", "process_id", "process_index", "coordinate_of_rank",
    "local_rank", "host_id", "is_coordinator",
}
# collective entry points whose divergent execution deadlocks the mesh
_COLLECTIVE_CALLS = {
    "barrier", "all_processes_ok", "allgather_ints", "mesh_all_reduce",
    "mesh_all_gather", "mesh_reduce_scatter", "mesh_all_to_all",
    "mesh_broadcast", "mesh_scatter", "mesh_ppermute", "psum", "pmean",
    "pmax", "pmin", "psum_scatter", "all_gather", "all_to_all", "ppermute",
    "all_gather_object", "all_reduce", "reduce_scatter", "broadcast",
}
# rank-guarded SINGLE-WRITER idioms that are fine (no collective inside)
_CALLS_EXEMPT_FROM_RANK_GUARD: Set[str] = set()

# cost-model entry points whose callers are PRICING a decision (VSC208):
# a package function that ranks/chooses by these without recording the
# prediction produces a cost nobody ever audits
_PRICED_CALLS = {"simulate_schedule", "estimate_stage_costs"}


def _disabled_codes(lines: Sequence[str], lineno: int) -> Set[str]:
    if 1 <= lineno <= len(lines):
        m = _DISABLE.search(lines[lineno - 1])
        if m:
            raw = m.group(1)
            if raw.strip() == "all":
                return {"all"}
            return {c.strip().upper() for c in raw.split(",") if c.strip()}
    return set()


class _Lint(ast.NodeVisitor):
    def __init__(self, filename: str, source: str, registered) -> None:
        self.filename = filename
        self.lines = source.splitlines()
        self.registered = registered
        self.findings: List[Finding] = []
        self._global_slots: Set[str] = set()
        self._handler_names: Set[str] = set()
        self._loop_depth = 0
        self._is_envreg = os.path.basename(filename) == "envreg.py"
        parts = os.path.normpath(filename).split(os.sep)
        # VSC207 exemption: the alert engine owns the one sanctioned
        # warn-once latch (its dormant-mode raise_alert fallback)
        self._is_alerts = any(
            a == "telemetry" and b == "alerts.py"
            for a, b in zip(parts, parts[1:])
        )
        self._vsc207_seen: Set[int] = set()
        self._vsc208_seen: Set[int] = set()
        # VSC208 applies only to package code: tests/scripts/bench call
        # the cost model to inspect it, not to decide by it
        self._in_package = "vescale_tpu" in parts
        # exempt ONLY the vescale_tpu/kernels package itself — a nested
        # .../kernels/ directory elsewhere is still subject to VSC206
        self._in_kernels = any(
            a == "vescale_tpu" and b == "kernels"
            for a, b in zip(parts, parts[1:])
        )

    # ------------------------------------------------------------ plumbing
    def emit(self, code: str, message: str, node: ast.AST) -> None:
        lineno = getattr(node, "lineno", 0)
        disabled = _disabled_codes(self.lines, lineno)
        if "all" in disabled or code in disabled:
            return
        self.findings.append(Finding(
            CODES[code], message, where=f"{self.filename}:{lineno}"
        ))

    # two-pass: collect global-slot names and signal handlers first
    def prepass(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                self._global_slots.update(node.names)
            if isinstance(node, ast.Call) and _dotted(node.func) in (
                "signal.signal", "signal"
            ):
                if len(node.args) >= 2:
                    h = node.args[1]
                    name = h.attr if isinstance(h, ast.Attribute) else (
                        h.id if isinstance(h, ast.Name) else None
                    )
                    if name:
                        self._handler_names.add(name)

    # -------------------------------------------------------- VSC201 / 202
    def _check_env_name(self, name: str, node: ast.AST) -> None:
        ok = self.registered(name)
        if not ok:
            self.emit(
                "VSC202",
                f"{name} is not registered in analysis.envreg — declare it "
                "(name/type/default/doc) or fix the name",
                node,
            )

    def _flag_env_read(self, name: str, node: ast.AST) -> None:
        if self._is_envreg:
            return
        self.emit(
            "VSC201",
            f"direct environment read of {name}; use "
            "vescale_tpu.analysis.envreg accessors (get_bool/get_int/"
            "get_float/get_str/get_raw)",
            node,
        )

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        # ------------------------------------------------------- VSC206
        # any `pallas_call` spelling (pl.pallas_call, pallas.pallas_call,
        # bare pallas_call) outside the kernels package
        if not self._in_kernels and (
            dotted == "pallas_call" or dotted.endswith(".pallas_call")
        ):
            self.emit(
                "VSC206",
                "direct pallas_call outside vescale_tpu/kernels/ bypasses "
                "the VESCALE_KERNELS dispatch contract; move the kernel "
                "into the kernels package and dispatch through it",
                node,
            )
        # os.getenv("X") / os.environ.get("X") / os.environ.pop (write-ish: pop allowed)
        if dotted in ("os.getenv", "getenv", "os.environ.get", "environ.get"):
            if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
                name = node.args[0].value
                if name.startswith("VESCALE_"):
                    self._flag_env_read(name, node)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # os.environ["X"] in Load context is a read; Store/Del are writes
        if isinstance(node.ctx, ast.Load) and _dotted(node.value) in ("os.environ", "environ"):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str) and sl.value.startswith("VESCALE_"):
                self._flag_env_read(sl.value, node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # "X" in os.environ is a read probe
        if (
            isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)
            and node.left.value.startswith("VESCALE_")
            and any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops)
            and any(_dotted(c) in ("os.environ", "environ") for c in node.comparators)
        ):
            self._flag_env_read(node.left.value, node)
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str):
            for name in _ENV_NAME.findall(node.value):
                self._check_env_name(name, node)
        self.generic_visit(node)

    # ------------------------------------------------------------- VSC203
    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Lambda):
            for t in node.targets:
                name = t.id if isinstance(t, ast.Name) else (
                    t.attr if isinstance(t, ast.Attribute) else None
                )
                if name and (name in self._global_slots or "hook" in name.lower()):
                    self.emit(
                        "VSC203",
                        f"hook slot {name!r} bound to a lambda; bind a "
                        "module-level named no-op function so dormant hooks "
                        "can be identity-asserted",
                        node,
                    )
        self.generic_visit(node)

    # ------------------------------------------------------------- VSC207
    def _check_warn_latch(self, node: ast.FunctionDef) -> None:
        """A function that both warns and reads/writes a "warned" latch is
        rolling its own alert lifecycle.  The finding anchors to the warn
        call (that's the line to migrate or annotate)."""
        if self._is_alerts:
            return
        warn_calls = []
        has_latch = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func).rsplit(".", 1)[-1]
                if name in ("warn", "warning"):
                    warn_calls.append(sub)
            ident = None
            if isinstance(sub, ast.Name):
                ident = sub.id
            elif isinstance(sub, ast.Attribute):
                ident = sub.attr
            elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                ident = sub.value
            if ident is not None and "warned" in ident.lower():
                has_latch = True
        if has_latch:
            for call in warn_calls:
                # a def nested in a flagged def would re-flag the same
                # call — one finding per warn site
                if id(call) in self._vsc207_seen:
                    continue
                self._vsc207_seen.add(id(call))
                self.emit(
                    "VSC207",
                    f"warn-once latch in {node.name!r}: a hand-rolled alert "
                    "with no lifecycle (no resolve, no dedup window, no "
                    "/alerts visibility) — raise it through telemetry.alerts."
                    "raise_alert, or annotate the legacy fallback",
                    call,
                )

    # ------------------------------------------------------------- VSC208
    def _check_priced_decision(self, node: ast.FunctionDef) -> None:
        """A package function that calls a cost-model entry point but never
        references ``record_prediction`` is pricing a decision outside the
        audit ledger.  The finding anchors to the priced call; a function
        that records (or a delegating wrapper that does) is clean by the
        same reference check."""
        if not self._in_package:
            return
        priced = []
        has_record = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func).rsplit(".", 1)[-1]
                if name in _PRICED_CALLS:
                    priced.append((name, sub))
            ident = None
            if isinstance(sub, ast.Name):
                ident = sub.id
            elif isinstance(sub, ast.Attribute):
                ident = sub.attr
            if ident == "record_prediction":
                has_record = True
        if has_record:
            return
        for name, call in priced:
            if id(call) in self._vsc208_seen:
                continue
            self._vsc208_seen.add(id(call))
            self.emit(
                "VSC208",
                f"`{name}` priced a decision in {node.name!r} with no "
                "record_prediction in scope — the prediction never enters "
                "the cost-audit ledger (telemetry/costaudit.py), so it can "
                "never be checked against reality; record it or annotate "
                "the site",
                call,
            )

    # ------------------------------------------------------------- VSC204
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_warn_latch(node)
        self._check_priced_decision(node)
        if node.name in self._handler_names:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    callee = sub.func
                    name = callee.attr if isinstance(callee, ast.Attribute) else (
                        callee.id if isinstance(callee, ast.Name) else None
                    )
                    if name in _SIGNAL_UNSAFE:
                        self.emit(
                            "VSC204",
                            f"`{name}` called inside signal handler "
                            f"{node.name!r} — handlers must only set flags "
                            "(locks/IO/allocation can deadlock the "
                            "interrupted frame)",
                            sub,
                        )
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    # ------------------------------------------------------------- VSC205
    def _visit_loop(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _visit_loop  # type: ignore[assignment]

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self._loop_depth > 0:
            bare = node.type is None
            base = isinstance(node.type, ast.Name) and node.type.id == "BaseException"
            # a handler that binds the exception AND uses it is transporting,
            # not swallowing (e.g. boxing it for re-raise on another thread)
            uses_exc = node.name is not None and any(
                isinstance(sub, ast.Name) and sub.id == node.name
                for sub in ast.walk(node)
            )
            if (bare or base) and not uses_exc and not any(
                isinstance(sub, ast.Raise) for sub in ast.walk(node)
            ):
                self.emit(
                    "VSC205",
                    ("bare `except:`" if bare else "`except BaseException:`")
                    + " inside a loop with no re-raise swallows "
                    "KeyboardInterrupt — catch Exception (or re-raise)",
                    node,
                )
        self.generic_visit(node)


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('os.environ.get')."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# --------------------------------------------------------------- VSC104
def _condition_is_rankish(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name and name.lower() in _RANK_TOKENS:
            return True
    return False


def rank_divergence_findings(source: str, filename: str = "<source>") -> List[Finding]:
    """VSC104: collective calls syntactically under an ``if``/``while``
    whose condition involves a rank-like value — every rank must reach
    every collective, or the mesh deadlocks at that collective."""
    tree = ast.parse(source)
    lines = source.splitlines()
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        if not _condition_is_rankish(node.test):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            callee = sub.func
            name = callee.attr if isinstance(callee, ast.Attribute) else (
                callee.id if isinstance(callee, ast.Name) else None
            )
            if name in _COLLECTIVE_CALLS:
                lineno = getattr(sub, "lineno", getattr(node, "lineno", 0))
                disabled = _disabled_codes(lines, lineno)
                if "all" in disabled or "VSC104" in disabled:
                    continue
                findings.append(Finding(
                    CODES["VSC104"],
                    f"collective `{name}` is executed only under a "
                    "rank-dependent condition (line "
                    f"{getattr(node, 'lineno', '?')}); ranks that skip it "
                    "deadlock the ones that reach it",
                    where=f"{filename}:{lineno}",
                ))
    return findings


# ------------------------------------------------------------ file driver
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build",
              "dist", ".pytest_cache", "legacy"}


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path) and path.endswith(".py"):
            out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _SKIP_DIRS]
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return out


def _default_registered(name: str) -> bool:
    from . import envreg

    if envreg.is_registered(name):
        return True
    # docstring families: "VESCALE_IO_BACKOFF_" style prefixes are legal
    # when at least one registered var extends them
    return any(v.name.startswith(name) for v in envreg.all_vars())


def lint_source(
    source: str,
    filename: str = "<source>",
    registered=None,
) -> List[Finding]:
    """Lint one source blob; ``registered`` is the name -> bool predicate
    for VSC202 (defaults to the envreg registry with prefix tolerance)."""
    registered = registered or _default_registered
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(
            CODES["VSC202"],
            f"file does not parse: {e}",
            where=f"{filename}:{getattr(e, 'lineno', 0)}",
        )]
    linter = _Lint(filename, source, registered)
    linter.prepass(tree)
    linter.visit(tree)
    findings = linter.findings
    findings.extend(rank_divergence_findings(source, filename))
    return findings


def lint_paths(paths: Sequence[str], name: str = "vescale-lint") -> FindingReport:
    report = FindingReport(name)
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path)
        report.extend(lint_source(src, rel))
    return report
