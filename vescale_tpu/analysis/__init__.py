"""vescale_tpu.analysis — the static-analysis layer.

Two engines, one findings model (docs/observability.md "Static analysis"):

  * **shardcheck** (shardcheck.py): symbolic sharding propagation over a
    traced jaxpr + darray placements — implicit materialization, Partial
    misuse, donation misses, rank-divergent collectives, pipeline stage
    boundary misfits.  The VSC12x decline codes are shared with the
    multi-hop redistribution planner (``redistribute_plan``).
  * **vescale-lint** (lint.py): AST enforcement of framework invariants —
    env reads via the central registry (envreg.py), identity-assertable
    no-op hooks, async-signal-safe handlers, KeyboardInterrupt-safe retry
    loops.

Mode: ``VESCALE_SHARDCHECK`` = ``off`` | ``warn`` (default) | ``strict``.
``warn`` surfaces error-severity findings as Python warnings at the
integration points (dmodule plan validation, the telemetry step report);
``strict`` raises ``ShardcheckError``.  CLI: ``python -m vescale_tpu.analysis``.
"""

from __future__ import annotations

import warnings as _warnings

from . import envreg
from .findings import CODES, Finding, FindingCode, FindingReport, Severity, code
from .lint import lint_paths, lint_source, rank_divergence_findings
from .shardcheck import (
    SymSharding,
    check_param_plan,
    check_stage_boundaries,
    check_transition,
    shardcheck,
    shardcheck_jaxpr,
    sym_from_spec,
)

__all__ = [
    "CODES",
    "Finding",
    "FindingCode",
    "FindingReport",
    "Severity",
    "code",
    "envreg",
    "lint_paths",
    "lint_source",
    "rank_divergence_findings",
    "SymSharding",
    "sym_from_spec",
    "shardcheck",
    "shardcheck_jaxpr",
    "check_transition",
    "check_stage_boundaries",
    "check_param_plan",
    "mode",
    "enabled",
    "is_strict",
    "ShardcheckError",
    "dispatch_report",
]


class ShardcheckError(RuntimeError):
    """Raised in strict mode when a report carries error-severity findings."""

    def __init__(self, report: FindingReport):
        self.report = report
        super().__init__(report.format())


def mode() -> str:
    """The active analysis mode: ``off`` | ``warn`` | ``strict``
    (``VESCALE_SHARDCHECK``; unknown values read as ``warn``)."""
    m = (envreg.get_str("VESCALE_SHARDCHECK") or "warn").strip().lower()
    return m if m in ("off", "warn", "strict") else "warn"


def enabled() -> bool:
    return mode() != "off"


def is_strict() -> bool:
    return mode() == "strict"


def dispatch_report(report: FindingReport, stacklevel: int = 2) -> FindingReport:
    """Route a report per the active mode: strict raises ShardcheckError on
    any error-severity finding, warn emits ONE aggregated warning, off (or
    a clean report) is silent.  Returns the report for chaining."""
    if not enabled() or not report.findings:
        return report
    if report.count(Severity.ERROR) and is_strict():
        raise ShardcheckError(report)
    if report.count(Severity.WARNING):
        _warnings.warn(
            "shardcheck: " + report.format(), stacklevel=stacklevel + 1
        )
    return report
