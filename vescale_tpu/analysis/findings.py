"""The findings model — one coded vocabulary for every static diagnostic.

Every diagnostic the analysis layer emits — shardcheck program findings,
``vescale-lint`` framework-invariant violations, and the redistribution
planner's decline reasons — is a :class:`Finding` carrying a stable
``VSC###`` code, a severity, optional mesh-dim / op provenance, and (for
data-movement findings) an estimated byte count priced by the collective
cost model in ``collectives.py``.  Stable codes are the contract: the CLI
greps them, tests assert them, docs/known_failures.md indexes by them, and
``redistribute_plan`` reuses the VSC12x block as its structured decline
reasons instead of free-form strings.

Code blocks:

  VSC10x  shardcheck — sharded-program hazards (materialization, Partial
          misuse, donation misses, divergent control flow, stage misfits)
  VSC12x  redistribute planner decline reasons (shared with
          ``redistribute_plan.decline_reason`` / ``_warn_fallback``)
  VSC13x  elastic restore — cross-world checkpoint compatibility
          (``checkpoint.elastic`` preflight, raised BEFORE chunk bytes are
          read; the loader's global-cursor re-split shares the block)
  VSC20x  vescale-lint — framework invariants established by PRs 1-5
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Severity",
    "FindingCode",
    "Finding",
    "FindingReport",
    "CODES",
    "code",
]


class Severity(enum.IntEnum):
    """Ordered so ``max(findings)`` and threshold comparisons read naturally."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error", not "Severity.ERROR" — for CLI lines
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class FindingCode:
    """A stable diagnostic code: identity + default severity + title."""

    code: str  # "VSC101"
    severity: Severity
    title: str

    def __str__(self) -> str:
        return self.code


_CODE_DEFS: Tuple[Tuple[str, Severity, str], ...] = (
    # --- VSC10x: shardcheck program findings -----------------------------
    ("VSC101", Severity.ERROR,
     "implicit full materialization of a sharded operand"),
    ("VSC102", Severity.WARNING,
     "sharding conflict forces a reshard between operands"),
    ("VSC103", Severity.ERROR,
     "Partial placement consumed by a non-linear op"),
    ("VSC104", Severity.ERROR,
     "collective under rank-divergent Python control flow (deadlock hazard)"),
    ("VSC105", Severity.WARNING,
     "donation miss: step input is rebuilt as an output but not donated"),
    ("VSC106", Severity.ERROR,
     "cross-stage resharding mismatch would hit the materializing fallback"),
    ("VSC107", Severity.WARNING,
     "suspicious parameter placement in a sharding plan"),
    ("VSC108", Severity.INFO,
     "cross-stage resharding resolved by the multi-hop planner (costed)"),
    ("VSC109", Severity.INFO,
     "analysis could not run (untraceable program or aborted walk)"),
    # --- VSC12x: redistribute planner decline reasons --------------------
    ("VSC120", Severity.ERROR,
     "every candidate path needs an intermediate above the per-shard memory budget"),
    ("VSC121", Severity.ERROR,
     "no per-shard hop sequence within the hop bound over the candidate lattice"),
    ("VSC122", Severity.ERROR,
     "cross-mesh: a side has no plain unpadded per-shard bridge form"),
    ("VSC123", Severity.ERROR,
     "cross-mesh: the unpadded bridge spec exceeds the per-shard memory budget"),
    ("VSC124", Severity.ERROR,
     "cross-mesh: source-side strip to the bridge form failed"),
    ("VSC125", Severity.ERROR,
     "cross-mesh: destination-side dress from the bridge form failed"),
    ("VSC126", Severity.INFO,
     "planner was not consulted for this spec pair"),
    ("VSC127", Severity.INFO,
     "quantized (int8) redistribution hop declined: cost model or layout does not favor it"),
    ("VSC128", Severity.INFO,
     "transition routed through a LOSSY int8-quantized hop (gated by VESCALE_REDISTRIBUTE_QUANT)"),
    # --- VSC13x: elastic restore (cross-world checkpoint compatibility) --
    ("VSC130", Severity.INFO,
     "checkpoint written by a different mesh/world size; resharding on load"),
    ("VSC131", Severity.ERROR,
     "checkpoint/template logical shape mismatch (not a reshardable layout change)"),
    ("VSC132", Severity.ERROR,
     "elastic restore disabled (VESCALE_ELASTIC_RESTORE=0) but writer mesh differs"),
    ("VSC133", Severity.ERROR,
     "loader position cannot be re-split: global batch shape changed across the resume"),
    # --- VSC20x: vescale-lint framework invariants -----------------------
    ("VSC201", Severity.ERROR,
     "direct os.environ read of a VESCALE_* variable outside analysis.envreg"),
    ("VSC202", Severity.ERROR,
     "VESCALE_* variable not registered in analysis.envreg"),
    ("VSC203", Severity.ERROR,
     "disarmed hook bound to a non-module-level callable (gating contract)"),
    ("VSC204", Severity.ERROR,
     "lock/allocation/IO inside a signal-handler frame"),
    ("VSC205", Severity.ERROR,
     "bare except in a retry loop swallows KeyboardInterrupt"),
    ("VSC206", Severity.ERROR,
     "direct pallas_call outside vescale_tpu/kernels (kernel dispatch contract)"),
    ("VSC207", Severity.WARNING,
     "ad-hoc warn-once latch outside the alert engine (telemetry/alerts.py)"),
    ("VSC208", Severity.WARNING,
     "priced decision (simulate_schedule/estimate_stage_costs) without a cost-audit record_prediction"),
)

CODES: Dict[str, FindingCode] = {
    c: FindingCode(c, sev, title) for c, sev, title in _CODE_DEFS
}


def code(name: str) -> FindingCode:
    """Look up a code by its ``VSC###`` name (KeyError on unknown — codes
    are a closed vocabulary; adding one is a doc-visible event)."""
    return CODES[name]


@dataclasses.dataclass
class Finding:
    """One diagnostic instance.

    ``where`` is op provenance — a jaxpr equation summary, a ``file:line``,
    or a stage/boundary label, whichever the emitting engine has.
    ``mesh_dim`` names the mesh axis involved (when one axis is at fault).
    ``bytes_est`` / ``cost_us`` price the implied data movement using the
    per-collective cost functions in ``collectives.py``.
    """

    code: FindingCode
    message: str
    where: Optional[str] = None
    mesh_dim: Optional[str] = None
    bytes_est: Optional[int] = None
    cost_us: Optional[float] = None
    severity: Optional[Severity] = None  # override; defaults to code severity

    def __post_init__(self):
        if isinstance(self.code, str):
            self.code = CODES[self.code]
        if self.severity is None:
            self.severity = self.code.severity

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "code": self.code.code,
            "severity": str(self.severity),
            "title": self.code.title,
            "message": self.message,
        }
        for k in ("where", "mesh_dim", "bytes_est", "cost_us"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    def format(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        dim = f" (mesh dim {self.mesh_dim!r})" if self.mesh_dim else ""
        size = ""
        if self.bytes_est is not None:
            size = f" ~{self.bytes_est / 2**20:.2f} MiB"
            if self.cost_us is not None:
                size += f" / ~{self.cost_us:.0f}us"
        return f"{self.code.code} {self.severity}: {self.message}{dim}{size}{loc}"


@dataclasses.dataclass
class FindingReport:
    """A named batch of findings with severity roll-ups (the unit the CLI
    prints, the step report embeds, and strict mode gates on)."""

    name: str
    findings: List[Finding] = dataclasses.field(default_factory=list)

    def add(self, *findings: Finding) -> "FindingReport":
        self.findings.extend(findings)
        return self

    def extend(self, findings) -> "FindingReport":
        self.findings.extend(findings)
        return self

    def by_code(self, c) -> List[Finding]:
        want = c.code if isinstance(c, FindingCode) else c
        return [f for f in self.findings if f.code.code == want]

    def codes(self) -> List[str]:
        return sorted({f.code.code for f in self.findings})

    @property
    def max_severity(self) -> Optional[Severity]:
        return max((f.severity for f in self.findings), default=None)

    def count(self, at_least: Severity = Severity.INFO) -> int:
        return sum(1 for f in self.findings if f.severity >= at_least)

    def ok(self, strict: bool = False) -> bool:
        """Gate: non-strict passes unless an ERROR finding exists; strict
        also fails on WARNING (INFO findings never fail a run)."""
        threshold = Severity.WARNING if strict else Severity.ERROR
        return self.count(threshold) == 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "n_findings": len(self.findings),
            "max_severity": str(self.max_severity) if self.findings else None,
            "codes": self.codes(),
            "findings": [f.to_dict() for f in self.findings],
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def format(self) -> str:
        if not self.findings:
            return f"{self.name}: clean (0 findings)"
        lines = [f"{self.name}: {len(self.findings)} finding(s)"]
        for f in sorted(self.findings, key=lambda f: (-int(f.severity), f.code.code)):
            lines.append("  " + f.format())
        return "\n".join(lines)
