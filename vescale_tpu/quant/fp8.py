"""fp8 quantized training — delayed-scaling float8 matmul (SURVEY.md:17
new-gen capability set: "RaggedShard ... for FSDP, quantized training,
Muon-style optimizers"; the reference's scope marker for fp8 training).

TPU-first FUNCTIONAL design: no module state, no dispatch interception —
an ``fp8_dot`` whose scaling state threads explicitly through the jitted
step, so it composes with pjit/GSPMD sharding, the compiled pipeline, and
``jax.grad`` without framework hooks.  (The module-level path —
``LlamaConfig.use_fp8`` — rides flax's ``Fp8DotGeneralOp`` instead, which
keeps the same state in the ``_overwrite_with_gradient`` collection;
``make_train_step`` understands that collection.)

The recipe (standard transformer-engine-style delayed scaling):

  * forward operands quantize to **e4m3** (max 448, more mantissa), the
    backward cotangent to **e5m2** (max 57344, more range) — gradients
    need range, activations need precision.
  * per-tensor scale is DELAYED: computed from a rolling amax history of
    the last H steps, never from the current tensor — so quantize is a
    static elementwise op with no data-dependent reduction in front of the
    matmul (XLA fuses it into the dot's prologue).
  * the matmul accumulates in fp32 and the result is de-scaled by
    ``1/(sx*sw)``.

Getting the GRADIENT amax out of backward is done the functional way: the
state is an ARGUMENT of a ``custom_vjp``, and its cotangent carries the
updated gradient-side state ("overwrite with gradient" — the same trick
flax's fp8_ops uses, expressed as plain function composition).  A train
step therefore:

    (loss, state_fwd), (gp, gstate) = value_and_grad(
        loss_fn, argnums=(0, 1), has_aux=True)(params, state, batch)
    state = merge_fp8_state(state_fwd, gstate)   # x/w from fwd, g from bwd

Loss-scaling composition: amax is recorded on the SCALED gradients, so the
delayed scale absorbs the loss scale automatically; non-finite amax values
(overflow steps the DistributedOptimizer skips) are dropped by
``merge_fp8_state``'s finite guard rather than poisoning the history.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .blockscale import quantize_clip, scale_from_amax

__all__ = [
    "Fp8TensorState",
    "Fp8DotState",
    "init_fp8_dot_state",
    "fp8_dot",
    "merge_fp8_state",
]

E4M3_MAX = float(jnp.finfo(jnp.float8_e4m3fn).max)  # 448
E5M2_MAX = float(jnp.finfo(jnp.float8_e5m2).max)    # 57344


class Fp8TensorState(NamedTuple):
    """Delayed-scaling state of ONE tensor slot (x, w, or g)."""

    amax_history: jax.Array  # (H,) fp32, rolling; [0] is most recent


class Fp8DotState(NamedTuple):
    x: Fp8TensorState
    w: Fp8TensorState
    g: Fp8TensorState


def init_fp8_dot_state(history_len: int = 16) -> Fp8DotState:
    one = Fp8TensorState(jnp.zeros((history_len,), jnp.float32))
    return Fp8DotState(one, one, one)


def _delayed_scale(st: Fp8TensorState, fp8_max: float) -> jax.Array:
    """fp8_max / max(history): the scale that would have put the largest
    recent value at the format edge.  Empty history (all zeros — the first
    steps) -> scale 1.0.  (scale-from-amax rule shared with the int8 block
    quantizer — quant/blockscale.py.)"""
    return scale_from_amax(jnp.max(st.amax_history), fp8_max)


def _roll(st: Fp8TensorState, amax_now: jax.Array) -> Fp8TensorState:
    """Push the current amax into the history (finite values only: an
    overflow step must not poison the delayed scale)."""
    amax_now = jnp.where(jnp.isfinite(amax_now), amax_now, st.amax_history[0])
    return Fp8TensorState(jnp.concatenate([amax_now[None], st.amax_history[:-1]]))


# scale + saturate + cast: the shared quantize kernel (blockscale.py)
_quantize = quantize_clip


@jax.custom_vjp
def _fp8_dot_core(x, w, state: Fp8DotState):
    y, _ = _core_fwd(x, w, state)
    return y


def _core_fwd(x, w, state: Fp8DotState):
    sx = _delayed_scale(state.x, E4M3_MAX)
    sw = _delayed_scale(state.w, E4M3_MAX)
    qx = _quantize(x, sx, jnp.float8_e4m3fn, E4M3_MAX)
    qw = _quantize(w, sw, jnp.float8_e4m3fn, E4M3_MAX)
    # fp32 accumulation; on fp8-capable hardware XLA lowers the fp8 x fp8
    # dot natively, elsewhere it upcasts — numerics (the quantization) are
    # identical either way
    y = jnp.dot(
        qx.astype(jnp.float32), qw.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST
    ) * (1.0 / (sx * sw))
    # zero-size dtype sentinels: the primal dtypes must survive into the
    # backward (dtype objects are not JAX types, so they ride as empty
    # arrays in the residuals)
    return y.astype(x.dtype), (
        qx, qw, sx, sw, jnp.zeros((0,), x.dtype), jnp.zeros((0,), w.dtype), state,
    )


def _core_bwd(res, dy):
    qx, qw, sx, sw, x_sent, w_sent, state = res
    x_dtype, w_dtype = x_sent.dtype, w_sent.dtype
    sg = _delayed_scale(state.g, E5M2_MAX)
    qg = _quantize(dy, sg, jnp.float8_e5m2, E5M2_MAX)
    g32 = qg.astype(jnp.float32)
    dx = (g32 @ qw.astype(jnp.float32).T) * (1.0 / (sg * sw))
    dw = (qx.astype(jnp.float32).T @ g32) * (1.0 / (sx * sg))
    # the state's cotangent IS the updated gradient-side state: amax of the
    # RAW (pre-quantize) cotangent rolls into g's history; x/w slots pass
    # through unchanged (merge_fp8_state takes them from the forward)
    g_new = _roll(state.g, jnp.max(jnp.abs(dy.astype(jnp.float32))))
    dstate = Fp8DotState(state.x, state.w, g_new)
    # each grad returns in its PRIMAL's dtype: bf16 activations with fp32
    # master weights must not round dw down to the cotangent's bf16
    return dx.astype(x_dtype), dw.astype(w_dtype), dstate


_fp8_dot_core.defvjp(lambda x, w, s: _core_fwd(x, w, s), _core_bwd)


def fp8_dot(x, w, state: Fp8DotState):
    """``x @ w`` through delayed-scaling fp8 quantization.

    Returns ``(y, state_after_forward)``: the forward-side state has x/w
    amax histories rolled; the GRADIENT side arrives as ``state``'s
    cotangent under ``jax.grad`` (see module docstring / merge_fp8_state).
    ``x``: (..., K) flattened to 2-D for the dot; ``w``: (K, N)."""
    lead = x.shape[:-1]
    x2 = x.reshape((-1, x.shape[-1]))
    y = _fp8_dot_core(x2, w, state)
    y = y.reshape(lead + (w.shape[-1],))
    # x/w histories roll in the forward (stop_gradient: bookkeeping, not math)
    new_state = Fp8DotState(
        _roll(state.x, jax.lax.stop_gradient(jnp.max(jnp.abs(x2.astype(jnp.float32))))),
        _roll(state.w, jax.lax.stop_gradient(jnp.max(jnp.abs(w.astype(jnp.float32))))),
        state.g,
    )
    return y, new_state


def merge_fp8_state(state_fwd, state_cotangent):
    """Combine a pytree of forward-updated ``Fp8DotState`` with the same
    tree's cotangents from ``jax.grad``: x/w slots from the forward, g
    slots from the cotangent — with a finite guard so an overflow step
    (skipped by the optimizer) cannot poison the histories."""

    def one(fwd: Fp8DotState, cot: Fp8DotState) -> Fp8DotState:
        g_hist = jnp.where(jnp.isfinite(cot.g.amax_history), cot.g.amax_history, 0.0)
        return Fp8DotState(fwd.x, fwd.w, Fp8TensorState(g_hist))

    return jax.tree_util.tree_map(
        one,
        state_fwd,
        state_cotangent,
        is_leaf=lambda n: isinstance(n, Fp8DotState),
    )
