from .fp8 import (
    Fp8DotState,
    Fp8TensorState,
    fp8_dot,
    init_fp8_dot_state,
    merge_fp8_state,
)

__all__ = [
    "Fp8DotState",
    "Fp8TensorState",
    "fp8_dot",
    "init_fp8_dot_state",
    "merge_fp8_state",
]
