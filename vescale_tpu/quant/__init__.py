from .blockscale import (
    DEFAULT_BLOCK,
    INT8_MAX,
    QuantizedBlocks,
    block_amax,
    dequantize_int8_blocks,
    pack_int8_payload,
    packed_nbytes,
    quantize_clip,
    quantize_int8_blocks,
    scale_from_amax,
    unpack_int8_payload,
)
from .fp8 import (
    Fp8DotState,
    Fp8TensorState,
    fp8_dot,
    init_fp8_dot_state,
    merge_fp8_state,
)

__all__ = [
    "Fp8DotState",
    "Fp8TensorState",
    "fp8_dot",
    "init_fp8_dot_state",
    "merge_fp8_state",
    "DEFAULT_BLOCK",
    "INT8_MAX",
    "QuantizedBlocks",
    "block_amax",
    "dequantize_int8_blocks",
    "pack_int8_payload",
    "packed_nbytes",
    "quantize_clip",
    "quantize_int8_blocks",
    "scale_from_amax",
    "unpack_int8_payload",
]
