"""Block-scaled integer quantization — the shared scaling core.

One scaling implementation for every quantized path in the framework
(ROADMAP item 2; EQuARX, arXiv:2506.17615):

  * the fp8 delayed-scaling matmul (``quant/fp8.py``) consumes
    :func:`scale_from_amax` / :func:`quantize_clip` for its per-tensor
    scales, and
  * the int8 gradient collectives (``collectives.all_reduce_q`` /
    ``reduce_scatter_q``) and the redistribution planner's
    quantize→move→dequantize hop consume the per-BLOCK machinery here
    (:func:`quantize_int8_blocks` / :func:`dequantize_int8_blocks` and the
    wire-format pack/unpack).

Block scaling: the flattened tensor is split into fixed-size blocks
(default 64 elements, ``VESCALE_GRAD_COMPRESS_BLOCK``); each block gets
its own scale from its own amax, so one outlier only costs ITS block
precision — the per-tensor failure mode of naive int8.

The scale is the smallest POWER OF TWO >= ``amax / 127`` — the OCP
Microscaling (MX) block-format rule, stored as one E8M0 exponent byte per
block on the wire.  Power-of-two scales are load-bearing for correctness,
not just for the extra 3 bytes/block: ``q * 2^e`` is an EXACT f32
exponent shift, so the dequantize multiply can be contracted into an FMA
by any backend (XLA CPU's LLVM codegen does) without changing a single
bit — which is what makes the collective's result deterministic across
fusion decisions and bit-for-bit replayable by the emulator.  A
free-mantissa scale (``amax/127`` exactly) was measured to diverge by
1 ulp under FMA contraction.  The cost: up to 2x the rounding step of an
ideal scale (bound ``amax/127`` per element instead of ``amax/254``).

Rounding: ``"nearest"`` (IEEE round-half-to-even — deterministic, bitwise
replayable by the emulator) or ``"stochastic"`` (``floor(x/s + u)`` with
``u ~ U[0,1)`` from a threefry key — unbiased in expectation, seeded and
replayable; the framework RNG's counter design means the same key gives
the same mask on every backend).

Non-finite contract (documented, tested): quantize/dequantize are traced
jax ops, so they cannot raise on data — a block containing ANY non-finite
element instead gets a non-finite scale, which poisons the ENTIRE block to
NaN/Inf on dequantize.  Non-finite gradients therefore still trip
``found_inf``/loss-scale skip logic after a quantized reduction; they are
never silently laundered into finite values.  Host-side callers that want
an eager error can pass ``validate=True`` (raises ``ValueError`` on
non-finite input when called with concrete arrays).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "INT8_MAX",
    "DEFAULT_BLOCK",
    "scale_from_amax",
    "pow2_scale_from_amax",
    "quantize_clip",
    "block_amax",
    "QuantizedBlocks",
    "quantize_int8_blocks",
    "dequantize_int8_blocks",
    "packed_nbytes",
    "pack_int8_payload",
    "unpack_int8_payload",
]

INT8_MAX = 127.0
DEFAULT_BLOCK = 64


# ---------------------------------------------------------- shared helpers
def scale_from_amax(amax, qmax: float):
    """``qmax / amax``: the QUANTIZE scale that puts the largest observed
    value at the format edge; an empty/zero amax gives scale 1.0.  This is
    the fp8 delayed-scaling rule (fp8.py) and the per-block int8 rule —
    factored here so both formats share one definition."""
    return jnp.where(amax > 0.0, qmax / amax, 1.0)


def quantize_clip(x, scale, dtype, qmax: float):
    """Scale, saturate to ±qmax, cast — the shared quantize kernel (fp8
    uses it with a per-tensor delayed scale; int8 with per-block scales)."""
    q = jnp.clip(x.astype(jnp.float32) * scale, -qmax, qmax)
    return q.astype(dtype)


def block_amax(x, block: int = DEFAULT_BLOCK):
    """Per-block max|x| of the flattened input, fp32, shape ``(n_blocks,)``
    (zero-padded tail).  NaN propagates (jnp.max) — see the non-finite
    contract in the module docstring."""
    blocks = _to_blocks(x, block)
    return jnp.max(jnp.abs(blocks), axis=1)


def _to_blocks(x, block: int):
    flat = jnp.ravel(x).astype(jnp.float32)
    n = flat.size
    nb = -(-n // block) if n else 1
    flat = jnp.pad(flat, (0, nb * block - n))
    return flat.reshape(nb, block)


# ------------------------------------------------------------- int8 blocks
class QuantizedBlocks(NamedTuple):
    """A block-quantized tensor: int8 codes + per-block power-of-two
    dequantize scales (``value ≈ q * scales[block]``; each scale is
    exactly ``2^e`` and travels as one E8M0 exponent byte)."""

    q: jax.Array       # (n_blocks, block) int8, zero-padded tail
    scales: jax.Array  # (n_blocks,) fp32, each exactly a power of two


def pow2_scale_from_amax(amax):
    """The smallest power of two >= ``amax / 127`` (MX/E8M0 rule), as an
    exact-f32 dequantize multiplier.  Zero amax gets the rule applied to a
    placeholder amax of 1.0 (scale ``2^-6``; all codes are zero, so the
    block round-trips exactly regardless); non-finite amax -> +inf (the
    block-poisoning contract).  Pure bit manipulation — ceil on the
    exponent field — so eager and compiled execution agree bitwise."""
    target = jnp.where(amax > 0.0, amax, 1.0).astype(jnp.float32) * jnp.float32(
        1.0 / INT8_MAX
    )
    bits = jax.lax.bitcast_convert_type(target, jnp.int32)
    exp = (bits >> 23) & 0xFF
    mant = bits & 0x7FFFFF
    # ceil to the next power of two; clamp to the normal range so the
    # reciprocal stays finite, and force the infinity encoding (e=255)
    # for non-finite amax
    e = jnp.clip(exp + (mant != 0).astype(jnp.int32), 1, 254)
    e = jnp.where(jnp.isfinite(amax), e, 255)
    return jax.lax.bitcast_convert_type((e << 23).astype(jnp.int32), jnp.float32)


def quantize_int8_blocks(
    x,
    block: int = DEFAULT_BLOCK,
    rounding: str = "nearest",
    key: Optional[jax.Array] = None,
    validate: bool = False,
) -> QuantizedBlocks:
    """Quantize ``x`` to block-scaled int8.

    Round-trip bound (tested): with ``rounding="nearest"``,
    ``|x - dequantize(quantize(x))| <= amax_block / 127`` elementwise (the
    power-of-two scale is at most 2x the ideal ``amax/127`` step);
    stochastic rounding doubles the per-element bound but is unbiased in
    expectation.  All-zero blocks round-trip exactly; non-finite blocks
    poison to non-finite (module docstring contract)."""
    if rounding not in ("nearest", "stochastic"):
        raise ValueError(f"rounding must be 'nearest' or 'stochastic', got {rounding!r}")
    if rounding == "stochastic" and key is None:
        raise ValueError("stochastic rounding needs an explicit PRNG key")
    if validate:
        concrete = not isinstance(x, jax.core.Tracer)
        if concrete and not bool(jnp.all(jnp.isfinite(x))):
            raise ValueError("quantize_int8_blocks(validate=True): non-finite input")
    blocks = _to_blocks(x, block)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    # exact power-of-two dequantize multiplier; non-finite amax -> inf so
    # the whole block dequantizes non-finite (0 * inf = nan) instead of
    # silently wrong
    scales = pow2_scale_from_amax(amax)
    v = blocks * (1.0 / scales)[:, None]  # exact: reciprocal of 2^e
    if rounding == "nearest":
        q = jnp.round(v)  # half-to-even: bitwise replayable host-side
    else:
        u = jax.random.uniform(key, blocks.shape, jnp.float32)
        q = jnp.floor(v + u)
    q = jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return QuantizedBlocks(q, scales)


def dequantize_int8_blocks(qb: QuantizedBlocks, shape, dtype, acc_dtype=jnp.float32):
    """Reconstruct the tensor: ``q * scale`` per block in ``acc_dtype``,
    trimmed to ``shape`` and cast to ``dtype``."""
    full = qb.q.astype(acc_dtype) * qb.scales.astype(acc_dtype)[:, None]
    n = 1
    for s in shape:
        n *= int(s)
    return full.reshape(-1)[:n].reshape(shape).astype(dtype)


# ------------------------------------------------------------- wire format
def packed_nbytes(n_elements: int, block: int = DEFAULT_BLOCK) -> int:
    """Bytes of the packed int8 payload for ``n_elements``: one byte per
    (padded) element plus ONE E8M0 exponent byte per block — the quantity
    the byte-savings telemetry and the planner cost model charge."""
    nb = -(-max(1, n_elements) // block)
    return nb * block + nb


def pack_int8_payload(qb: QuantizedBlocks) -> jax.Array:
    """One flat int8 buffer ``[codes | E8M0 scale bytes]`` — a quantized
    collective moves a SINGLE int8 array on the wire (payload and scales
    together), so comm accounting sees exactly one s8 op and the scales
    cannot be reordered relative to their codes.  Each power-of-two scale
    packs to its f32 exponent byte (E8M0: ``2^(e-127)``; 255 = the
    non-finite poison marker)."""
    bits = jax.lax.bitcast_convert_type(qb.scales, jnp.int32)
    e = ((bits >> 23) & 0xFF).astype(jnp.uint8)
    return jnp.concatenate(
        [qb.q.reshape(-1), jax.lax.bitcast_convert_type(e, jnp.int8)]
    )


def unpack_int8_payload(buf, n_blocks: int, block: int) -> QuantizedBlocks:
    q = buf[: n_blocks * block].reshape(n_blocks, block)
    e = jax.lax.bitcast_convert_type(buf[n_blocks * block :], jnp.uint8)
    scales = jax.lax.bitcast_convert_type(
        (e.astype(jnp.int32) << 23), jnp.float32
    )
    return QuantizedBlocks(q, scales)
