"""VeDeviceMesh — the global nD-mesh singleton API.

Capability parity with the reference VeDeviceMesh
(legacy/vescale/devicemesh_api/api.py:28,48,188,221,290-361,380-388,475):
one process-global mesh with named strategy dims (PP/DP/TP/...) and
convenience rank/submesh lookups used by the trainers.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

from .mesh import DeviceMesh, init_device_mesh as _init

__all__ = ["VeDeviceMesh", "VESCALE_DEVICE_MESH"]  # vescale-lint: disable=VSC202 (API singleton name, not an env var)


class VeDeviceMesh:
    PP, DP, TP = "PP", "DP", "TP"

    def __init__(self) -> None:
        self._mesh: Optional[DeviceMesh] = None

    # ------------------------------------------------------------- init
    def init_device_mesh(
        self,
        device_type: str = "tpu",
        mesh_shape: Sequence[int] = (),
        mesh_dim_names: Optional[Sequence[str]] = None,
        check_uniqueness: bool = False,
    ) -> DeviceMesh:
        """(reference api.py:48) — create & register the global mesh."""
        if check_uniqueness and self._mesh is not None:
            raise RuntimeError("device mesh already initialized")
        self._mesh = _init(device_type, mesh_shape, mesh_dim_names=mesh_dim_names)
        return self._mesh

    def get(self) -> DeviceMesh:
        if self._mesh is None:
            raise RuntimeError("call init_device_mesh first")
        return self._mesh

    @property
    def ndim(self) -> int:
        return self.get().ndim

    def size(self, dim: Optional[Union[int, str]] = None) -> int:
        return self.get().size(dim)

    # ------------------------------------------------------ coordinates
    def get_strategy_coordinate(self, local_rank: Optional[int] = None) -> Tuple[int, ...]:
        """(api.py:188) n-D coordinate of a flat rank."""
        mesh = self.get()
        r = local_rank if local_rank is not None else mesh.get_rank()
        return mesh.coordinate_of_rank(r)

    def lookup_rank(self, dim: Union[int, str]) -> int:
        """(api.py:221) this process's index along one strategy dim."""
        mesh = self.get()
        return self.get_strategy_coordinate()[mesh._dim_index(dim)]

    def get_local_rank(self) -> int:
        return self.get().get_rank()

    # ------------------------------------------------- PP/DP/TP helpers
    def _dim_or_none(self, name: str):
        mesh = self.get()
        lowered = [d.lower() for d in mesh.mesh_dim_names]
        return lowered.index(name.lower()) if name.lower() in lowered else None

    def get_pipeline_parallel_rank(self) -> int:
        d = self._dim_or_none("pp")
        return 0 if d is None else self.get_strategy_coordinate()[d]

    def get_data_parallel_rank(self) -> int:
        d = self._dim_or_none("dp")
        return 0 if d is None else self.get_strategy_coordinate()[d]

    def get_tensor_parallel_rank(self) -> int:
        d = self._dim_or_none("tp")
        return 0 if d is None else self.get_strategy_coordinate()[d]

    def get_pipeline_parallel_mesh(self) -> DeviceMesh:
        return self.get()["pp" if self._dim_or_none("pp") is not None else self.get().mesh_dim_names[0]]

    def get_data_parallel_mesh(self) -> DeviceMesh:
        return self.get()["dp" if self._dim_or_none("dp") is not None else self.get().mesh_dim_names[0]]

    def get_tensor_parallel_mesh(self) -> DeviceMesh:
        return self.get()["tp" if self._dim_or_none("tp") is not None else self.get().mesh_dim_names[-1]]

    def get_global_tensor_parallel_meshes(self):
        """All TP submeshes (api.py:290-361)."""
        mesh = self.get()
        import numpy as np

        tp_dim = self._dim_or_none("tp")
        if tp_dim is None:
            return [mesh]
        out = []
        other_shape = [s for i, s in enumerate(mesh.shape) if i != tp_dim]
        for flat in range(int(np.prod(other_shape)) if other_shape else 1):
            coord = list(np.unravel_index(flat, other_shape)) if other_shape else []
            index = []
            k = 0
            for i in range(mesh.ndim):
                if i == tp_dim:
                    index.append(slice(None))
                else:
                    index.append(int(coord[k]))
                    k += 1
            sub = mesh.devices[tuple(index)]
            from jax.sharding import Mesh as JaxMesh

            out.append(DeviceMesh((mesh.mesh_dim_names[tp_dim],), _jax_mesh=JaxMesh(sub, axis_names=(mesh.mesh_dim_names[tp_dim],))))
        return out

    def is_first_stage(self) -> bool:
        """(api.py:380)"""
        return self.get_pipeline_parallel_rank() == 0

    def is_last_stage(self) -> bool:
        """(api.py:388)"""
        d = self._dim_or_none("pp")
        n = 1 if d is None else self.get().shape[d]
        return self.get_pipeline_parallel_rank() == n - 1


VESCALE_DEVICE_MESH = VeDeviceMesh()
