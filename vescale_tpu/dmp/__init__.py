from .dmp import auto_parallelize_module, PlanGenerator
from .policies.registry import register_policy, get_policy
