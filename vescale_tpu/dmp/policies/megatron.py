"""MEGATRON auto-plan policy (reference legacy/vescale/dmp/policies/
megatron.py:33-218: mlp/attention/layernorm/embedding/lm-head/dropout
providers).

The reference introspects torch module classes; TPU-native introspection
walks the *abstract param tree* (names + shapes), classifying each 2-D
kernel as column- or row-parallel by Megatron naming conventions and pairing
within a block: projections INTO the hidden bottleneck are rows, expansions
are columns.  Falls back to replicate when unsure — always correct, just
not sharded.

Forward plans are derived PER MODULE from the same tree (reference
per-module providers, legacy/vescale/dmp/policies/megatron.py:33-218:
mlp/attention in/out, LayerNorm SP regions):
  - a module with both column- and row-parallel child projections is a TP
    region (attention/mlp) -> inputs/outputs batch-sharded (gather the seq
    dim at the region boundary);
  - a norm module that is a SIBLING of a TP region is a Megatron-SP norm ->
    inputs/outputs additionally seq-sharded over tp;
  - a top-level norm (the final norm) runs SP in, batch-sharded out;
  - the root reshards inputs/outputs batch-sharded over dp.
(The reference also plans dropout modules for RNG alignment; flax dropout
is parameterless and our threefry-partitionable RNG needs no plan.)
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

import jax

from ...placements import Replicate, Shard
from .registry import register_policy

_COL_HINTS = (
    "c_attn", "q_proj", "k_proj", "v_proj", "query", "key", "value",
    "c_fc", "gate_proj", "up_proj", "fc1", "w1", "w3", "wi",
)
_ROW_HINTS = ("c_proj", "o_proj", "down_proj", "fc2", "w2", "wo", "dense_4h_to_h", "out_proj")
_EMBED_HINTS = ("embedding",)
_HEAD_HINTS = ("lm_head",)
_NORM_HINTS = ("ln", "layernorm", "norm")


def _path_str(kp) -> str:
    return ".".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp)


def _parent(path: str) -> str:
    return path.rsplit(".", 1)[0] if "." in path else ""


@register_policy("MEGATRON")
def megatron_policy(
    abstract_params,
    mesh,
    tp_dim: str = "tp",
    dp_dim: str = "dp",
    sequence_parallel: bool = True,
) -> Dict[str, Any]:
    """Derive {parameter, forward} plans from param names/shapes."""
    names = mesh.mesh_dim_names
    tp_i = names.index(tp_dim) if tp_dim in names else None
    n_tp = mesh.shape[tp_i] if tp_i is not None else 1

    def pl(shard_dim=None):
        out: List[Any] = [Replicate()] * mesh.ndim
        if shard_dim is not None and tp_i is not None:
            out[tp_i] = Shard(shard_dim)
        return out

    param_plan: Dict[str, Any] = {}
    col_parents: set = set()   # module paths owning a column-parallel kernel
    row_parents: set = set()   # ... a row-parallel kernel
    norm_modules: set = set()  # module paths of norm layers

    def classify(kp, leaf):
        path = _path_str(kp)
        low = path.lower()
        key = re.escape(path)
        shape = tuple(leaf.shape)
        mod = _parent(path)
        if any(h in low for h in _NORM_HINTS) or len(shape) == 0:
            if any(h in mod.lower().rsplit(".", 1)[-1] for h in _NORM_HINTS):
                norm_modules.add(mod)
            param_plan[key] = pl()
            return leaf
        if low.endswith(".embedding") or any(h in low for h in _HEAD_HINTS):
            # hidden- or vocab-shard if divisible
            d = 1 if len(shape) > 1 and shape[1] % n_tp == 0 else None
            param_plan[key] = pl(d)
            return leaf
        if len(shape) in (2, 3) and low.endswith("kernel"):
            # 3-D = lax.scan-stacked blocks (L, in, out): the leading stack
            # axis is never a tp dim, so col/row shard dims shift by one
            off = len(shape) - 2
            parent = low.rsplit(".", 2)[-2] if "." in low else low
            if any(h in parent for h in _COL_HINTS) and shape[1 + off] % n_tp == 0:
                param_plan[key] = pl(1 + off)
                col_parents.add(_parent(mod))
                return leaf
            if any(h in parent for h in _ROW_HINTS) and shape[0 + off] % n_tp == 0:
                param_plan[key] = pl(0 + off)
                row_parents.add(_parent(mod))
                return leaf
            param_plan[key] = pl()
            return leaf
        if len(shape) == 1 and low.endswith("bias"):
            parent = low.rsplit(".", 2)[-2] if "." in low else low
            if any(h in parent for h in _COL_HINTS) and shape[0] % n_tp == 0:
                param_plan[key] = pl(0)
                return leaf
            param_plan[key] = pl()
            return leaf
        param_plan[key] = pl()
        return leaf

    jax.tree_util.tree_map_with_path(classify, abstract_params)

    # ---------------- per-module forward plan (reference megatron.py:33-218)
    dp_i = names.index(dp_dim) if dp_dim in names else None

    def act(seq: bool = False):
        out: List[Any] = [Replicate()] * mesh.ndim
        if dp_i is not None:
            out[dp_i] = Shard(0)  # batch dim
        if seq and tp_i is not None:
            out[tp_i] = Shard(1)  # Megatron-SP: sequence dim over tp
        return out

    dp_only = act()
    seq_par = act(seq=sequence_parallel)
    # TP regions: a module (not the root) holding BOTH column- and
    # row-parallel projections — the attention / mlp "enter replicated,
    # leave partial" blocks of the reference providers
    regions = {m for m in (col_parents & row_parents) if m}
    region_parents = {_parent(m) for m in regions}
    fwd_plan: Dict[str, Any] = {r"": {"input": [dp_only], "output": [dp_only]}}
    for m in sorted(regions):
        fwd_plan[re.escape(m)] = {"input": [dp_only], "output": [dp_only]}
    for m in sorted(norm_modules):
        par = _parent(m)
        if par in regions or m in regions:
            continue  # q/k-norms inside attention: the region boundary rules
        if par in region_parents and par != "":
            # block norm, sibling of a TP region -> SP in/out
            fwd_plan[re.escape(m)] = {"input": [seq_par], "output": [seq_par]}
        elif par == "" and regions:
            # final norm: SP in, gathered (batch-only) out for the head
            fwd_plan[re.escape(m)] = {"input": [seq_par], "output": [dp_only]}
    return {"parameter": param_plan, "forward": fwd_plan}
