"""Auto-plan policy registry (reference legacy/vescale/dmp/policies/
registry.py:22): named policies mapping an abstract param tree to
parameter/forward plan fragments."""

from __future__ import annotations

from typing import Callable, Dict

__all__ = ["register_policy", "get_policy", "POLICIES"]

POLICIES: Dict[str, Callable] = {}


def register_policy(name: str):
    def deco(fn: Callable):
        POLICIES[name.upper()] = fn
        return fn

    return deco


def get_policy(name: str) -> Callable:
    key = name.upper()
    if key not in POLICIES:
        raise KeyError(f"unknown auto-plan policy {name!r}; have {sorted(POLICIES)}")
    return POLICIES[key]
