from . import megatron, registry
