"""auto_parallelize_module (reference legacy/vescale/dmp/dmp.py:185) —
zero-plan entry point: derive the sharding plan from the model itself via a
policy, then parallelize.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from ..dmodule.api import DModule, parallelize_module
from ..mesh import DeviceMesh
from .policies.registry import get_policy
from . import policies  # noqa: F401  (registers built-ins)

__all__ = ["auto_parallelize_module", "PlanGenerator"]


class PlanGenerator:
    """(reference dmp.py:61) — policy-driven plan derivation from an
    abstract init."""

    def __init__(self, policy: str = "MEGATRON"):
        self.policy = policy

    def generate(self, module, mesh: DeviceMesh, *example_args, **example_kwargs):
        abstract = jax.eval_shape(
            lambda: module.init(jax.random.key(0), *example_args, **example_kwargs)
        )
        params = abstract.get("params", abstract)
        return get_policy(self.policy)(params, mesh)


def auto_parallelize_module(
    module,
    device_mesh: DeviceMesh,
    *example_args,
    policy: str = "MEGATRON",
    **example_kwargs,
) -> DModule:
    """One-call parallelization: introspect -> plan -> parallelize_module
    (reference auto_parallelize_module, dmp.py:185)."""
    plan = PlanGenerator(policy).generate(module, device_mesh, *example_args, **example_kwargs)
    return parallelize_module(module, device_mesh, plan)
