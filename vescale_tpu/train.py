"""Jitted train-step assembly.

The reference's training loop composes DModule forward + DDP backward +
DistributedOptimizer step as three separately-hooked eager phases (SURVEY
§3.3).  TPU-native, the whole step is ONE jit-compiled program: GSPMD
inserts the DP grad all-reduce, TP boundary collectives and ZeRO
reduce-scatter/all-gather, and XLA's latency-hiding scheduler overlaps them
with compute (the role of the reference's async bucket machinery).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from .dmodule.api import DModule

__all__ = ["make_train_step", "make_eval_step"]

# double-increment guard (ADVICE): with auto_inc_step (default), a loop that
# ALSO advances the ndtimeline counter manually (inc_step() /
# flush(next_iteration=True)) per step silently double-counts the global
# step.  SHARED across every make_train_step fn: any auto-inc step records
# the counter value it produced here, so a second auto-inc fn (train + eval
# loops sharing one manager) is recognized as legitimate — only a counter
# move no auto-inc step made triggers the one-time warning.
_AUTO_STEP_GUARD: Dict[str, Any] = {"mgr": None, "step": None, "warned": False}


def make_train_step(
    dmodel: DModule,
    tx,
    loss_fn: Callable,
    *,
    has_aux: bool = False,
    donate: bool = True,
    rng_streams: tuple = ("dropout",),
    grad_accum_steps: int = 1,
    auto_inc_step: bool = True,
    with_metrics: Optional[bool] = None,
):
    """Build ``train_step(params, opt_state, batch, step_key) ->
    (params, opt_state, loss)``.

    ``tx`` may be an ``optax.GradientTransformation`` OR a
    ``DistributedOptimizer``/``BasicOptimizer`` — with a DistributedOptimizer
    the step scales the loss by the live loss scale before ``grad``,
    unscales/clips/skips inside ``dopt.step``, and reports the UNSCALED
    loss, so mixed-precision overflow protection needs no hand wiring
    (examples/resilient_train shows the manual equivalent).

    ``loss_fn(logits_or_outputs, batch)`` computes the scalar loss from the
    model output.  Dropout etc. draw from ``step_key`` folded per stream —
    deterministic and bitwise-identical under any sharding.

    ``grad_accum_steps`` > 1 splits the batch into micro-batches accumulated
    in fp32 via ``lax.scan`` (the reference DDP's main_grad accumulation,
    ddp/grad_buffer.py, expressed functionally) before one optimizer update.
    The accumulated grads/loss are averaged over micro-batches, so
    ``loss_fn`` must be MEAN-reduced for step-1 equivalence (a sum-reduced
    loss would be scaled by 1/grad_accum_steps).

    ``has_aux=True``: ``loss_fn`` returns ``(loss, aux)`` — a metrics pytree
    carried through every path (r5, VERDICT r4 next #8): with a
    DistributedOptimizer only the LOSS is scaled (aux stays raw), and under
    grad accumulation float aux leaves are MEAN-reduced across micro-batches
    while integer leaves (counts) are SUMMED.

    fp8 models (``LlamaConfig.use_fp8`` — flax ``Fp8DotGeneralOp``) carry an
    ``_overwrite_with_gradient`` variable collection (delayed-scaling amax
    histories + scales).  Pass ``params`` as the TWO-collection bundle
    ``{"params": ..., "_overwrite_with_gradient": ...}`` (and init the
    optimizer on the ``params`` subtree only): the step threads the
    collection through apply, keeps it away from the optimizer, and
    OVERWRITES it with its gradient (the fp8 delayed-scaling update) under
    a finite guard so skipped overflow steps cannot poison the histories.

    ``with_metrics``: the telemetry feed (telemetry/).  When True the
    compiled step additionally computes per-step scalars — grad-norm, and
    with a DistributedOptimizer the live loss-scale value and skipped-step
    count — returned OUT-OF-BAND: the wrapper strips them from the public
    return and forwards them (plus wall-clock step time, loss, tokens/sec)
    to ``telemetry.record_step``.  ``None`` (default) resolves to
    ``telemetry.is_active()`` at BUILD time, so a run that calls
    ``telemetry.init()`` before ``make_train_step`` gets the full feed and
    an un-instrumented run compiles the exact unchanged program — the
    zero-overhead gating contract.
    """
    from . import telemetry as _tel
    from .parallel.optimizer import BasicOptimizer, DistributedOptimizer

    if with_metrics is None:
        with_metrics = _tel.is_active()
    dopt = tx if isinstance(tx, (BasicOptimizer, DistributedOptimizer)) else None
    OWG = "_overwrite_with_gradient"

    def micro_loss(p, micro_batch, step_key, opt_state=None):
        rngs = (
            {name: jax.random.fold_in(step_key, i) for i, name in enumerate(rng_streams)}
            if step_key is not None
            else None
        )
        variables = (
            {"params": p["params"], OWG: p[OWG]}
            if isinstance(p, dict) and OWG in p
            else {"params": p}
        )
        out = dmodel.apply(
            variables, micro_batch["input"], deterministic=step_key is None, rngs=rngs
        )
        res = loss_fn(out, micro_batch)
        loss, aux = res if has_aux else (res, None)
        if isinstance(dopt, DistributedOptimizer) and opt_state is not None:
            loss = dopt.scale_loss(loss, opt_state)
        return (loss, aux) if has_aux else loss

    def _reduce_aux_leaf(a):
        # a: (grad_accum_steps, ...) stacked metric — means for measures,
        # sums for integer counts
        if jnp.issubdtype(a.dtype, jnp.inexact):
            return jnp.mean(a, axis=0).astype(a.dtype)
        return jnp.sum(a, axis=0)

    def step(params, opt_state, batch, step_key=None):
        fp8_bundle = isinstance(params, dict) and OWG in params
        if grad_accum_steps <= 1:
            if has_aux:
                (loss, aux), grads = jax.value_and_grad(
                    lambda p: micro_loss(p, batch, step_key, opt_state), has_aux=True
                )(params)
            else:
                loss, grads = jax.value_and_grad(
                    lambda p: micro_loss(p, batch, step_key, opt_state)
                )(params)
                aux = None
        else:
            b0 = jax.tree_util.tree_leaves(batch)[0].shape[0]
            if b0 % grad_accum_steps != 0:
                raise ValueError(
                    f"batch dim {b0} not divisible by grad_accum_steps={grad_accum_steps}"
                )
            micros = jax.tree_util.tree_map(
                lambda x: x.reshape(grad_accum_steps, x.shape[0] // grad_accum_steps, *x.shape[1:]),
                batch,
            )

            def accum(carry, inputs):
                g_acc, l_acc = carry
                mb, i = inputs
                key_i = jax.random.fold_in(step_key, 1000 + i) if step_key is not None else None
                if has_aux:
                    (l, aux_i), g = jax.value_and_grad(
                        lambda p: micro_loss(p, mb, key_i, opt_state), has_aux=True
                    )(params)
                else:
                    l, g = jax.value_and_grad(lambda p: micro_loss(p, mb, key_i, opt_state))(params)
                    aux_i = None
                if fp8_bundle:
                    # OWG "grads" are next-values, not gradients.  amax
                    # histories combine by elementwise MAX across the
                    # micro-batches (every micro-batch rolled the SAME
                    # pre-step history, so max captures the true per-step
                    # amax — a spike in micro-batch 1 must not be dropped
                    # because micro-batch N was calm); derived scale leaves
                    # take the latest (identical across micro-batches: all
                    # computed from the pre-step history).
                    def owg_one(kp, a, b):
                        leaf = str(getattr(kp[-1], "key", kp[-1]))
                        return jnp.maximum(a, b) if "amax_history" in leaf else b

                    g_acc = {
                        "params": jax.tree_util.tree_map(
                            lambda a, b: a + b.astype(a.dtype), g_acc["params"], g["params"]
                        ),
                        OWG: jax.tree_util.tree_map_with_path(owg_one, g_acc[OWG], g[OWG]),
                    }
                else:
                    g_acc = jax.tree_util.tree_map(lambda a, b: a + b.astype(a.dtype), g_acc, g)
                return (g_acc, l_acc + l), aux_i

            g0 = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (g_sum, l_sum), aux_stack = jax.lax.scan(
                accum, (g0, 0.0), (micros, jnp.arange(grad_accum_steps))
            )
            if fp8_bundle:
                grads = {
                    "params": jax.tree_util.tree_map(
                        lambda g, p: (g / grad_accum_steps).astype(p.dtype),
                        g_sum["params"],
                        params["params"],
                    ),
                    OWG: g_sum[OWG],
                }
            else:
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g / grad_accum_steps).astype(p.dtype), g_sum, params
                )
            loss = l_sum / grad_accum_steps
            aux = (
                jax.tree_util.tree_map(_reduce_aux_leaf, aux_stack) if has_aux else None
            )
        if fp8_bundle:
            # the OWG collection never meets the optimizer: its "gradient"
            # IS its next value (delayed-scaling histories/scales), applied
            # under a finite guard — an overflow step's inf amax must not
            # poison the rolling history
            owg_new = jax.tree_util.tree_map(
                lambda new, old: jnp.where(jnp.isfinite(new), new, old),
                grads[OWG],
                params[OWG],
            )
            params_p, grads_p = params["params"], grads["params"]
        else:
            params_p, grads_p = params, grads
        if dopt is not None:
            new_params_p, new_opt_state = dopt.step(params_p, opt_state, grads_p)
            if isinstance(dopt, DistributedOptimizer):
                # report the UNSCALED loss (pre-step scale — the one
                # micro_loss multiplied by; the post-step scale differs on
                # backoff/growth steps)
                loss = loss / dopt.current_scale(opt_state)
        else:
            updates, new_opt_state = tx.update(grads_p, opt_state, params_p)
            new_params_p = optax.apply_updates(params_p, updates)
        new_params = {"params": new_params_p, OWG: owg_new} if fp8_bundle else new_params_p
        if with_metrics:
            # out-of-band telemetry scalars (stripped by the wrapper below).
            # grad-norm is reported UNSCALED — grads under loss scaling carry
            # the scale factor, which is an implementation detail, not signal.
            gnorm = optax.global_norm(
                jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads_p)
            )
            if isinstance(dopt, DistributedOptimizer):
                gnorm = gnorm / dopt.current_scale(opt_state)
            tmetrics = {"grad_norm": gnorm}
            if isinstance(new_opt_state, dict) and "loss_scale" in new_opt_state:
                ls = new_opt_state["loss_scale"]
                tmetrics["loss_scale"] = ls["scale"]
                if "skip_count" in ls:
                    tmetrics["skip_count"] = ls["skip_count"]
            if has_aux:
                return new_params, new_opt_state, loss, aux, tmetrics
            return new_params, new_opt_state, loss, tmetrics
        if has_aux:
            return new_params, new_opt_state, loss, aux
        return new_params, new_opt_state, loss

    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    # runtime profiler wiring (VERDICT r4 next #5): when ndtimeline is
    # initialized, every call emits a TRAIN_STEP span (host region —
    # brackets dispatch; XLA's profiler owns on-device timing, and the
    # TraceAnnotation threads the span into its captures) and — with
    # ``auto_inc_step`` (default) — advances the global step counter, so a
    # loop using make_train_step must NOT also call inc_step() (or pass
    # auto_inc_step=False to keep manual control).  Un-initialized
    # profiler: ndtimeit is a nullcontext and nothing is recorded.
    from .ndtimeline import api as _nd
    from .ndtimeline.predefined import TRAIN_STEP

    from .telemetry import memtrack as _memtrack

    @functools.wraps(jitted)
    def timed_step(*args, **kwargs):
        t0 = time.perf_counter()
        try:
            with _nd.ndtimeit(TRAIN_STEP):
                out = jitted(*args, **kwargs)
        except BaseException as e:
            # OOM flight recorder (telemetry/memtrack.py): a
            # RESOURCE_EXHAUSTED at step 40k leaves a forensic bundle
            # (tagged census, device stats, last reports) instead of a bare
            # stack trace.  Gated — dormant runs pay this try frame only.
            _memtrack.maybe_dump_oom(e)
            raise
        # re-tag the donated/updated outputs: each jitted call returns FRESH
        # arrays, and without this the whole model would age into the
        # untagged bucket after one step (and trip the leak detector)
        _memtrack.tag_tree(out[0], "params")
        if len(out) > 1:
            _memtrack.tag_tree(out[1], "optimizer_state")
        if auto_inc_step and _nd.is_active():
            mgr = _nd.get_manager()
            g = _AUTO_STEP_GUARD
            if g["mgr"] is not mgr:  # manager re-init: restart tracking
                g["mgr"], g["step"] = mgr, None
            if not g["warned"] and g["step"] is not None and mgr.step > g["step"]:
                import warnings

                g["warned"] = True
                # a caller-contract misuse notice (fix the call site), not
                # a runtime health signal — stays a warn-once
                warnings.warn(  # vescale-lint: disable=VSC207
                    "make_train_step(auto_inc_step=True) advances the "
                    "ndtimeline step counter itself, but it was ALSO advanced "
                    "externally (manual inc_step() or flush(next_iteration="
                    "True)) within one training step — steps are being "
                    "double-counted.  Pass auto_inc_step=False to keep manual "
                    "control, or drop the manual increment.",
                    stacklevel=2,
                )
            mgr.inc_step()
            g["step"] = mgr.step
        if with_metrics:
            # the telemetry scalars ride as a trailing pytree; strip them
            # unconditionally so the public return shape never depends on
            # whether telemetry is live at CALL time
            tmetrics = out[-1]
            out = out[:-1]
        else:
            tmetrics = None
        if _tel.is_active():
            # host-fetching the loss forces this step's completion, so the
            # recorded time is true wall clock, not async dispatch time —
            # the observability trade a telemetry-on run opts into
            loss_val = float(out[2])
            dt = time.perf_counter() - t0
            rec: Dict[str, Any] = {"step_time_s": dt, "loss": loss_val}
            batch = args[2] if len(args) > 2 else kwargs.get("batch")
            leaf = batch.get("input") if isinstance(batch, dict) else None
            if leaf is not None and hasattr(leaf, "shape"):
                tokens = 1
                for s in leaf.shape:
                    tokens *= int(s)
                rec["tokens"] = tokens
                if dt > 0:
                    rec["tokens_per_sec"] = tokens / dt
            if tmetrics:
                rec.update({k: float(v) for k, v in tmetrics.items()})
            # default train rule pack (loss anomaly, grad-norm spike,
            # step-time regression, memory growth): armed lazily at the
            # first live step so late telemetry.init() still gets it;
            # arm_pack dedups by name (a set probe) on every later step
            from .telemetry import alerts as _alerts

            if _alerts.is_active():
                _alerts.get_engine().arm_pack("train", _alerts.train_rule_pack())
            _tel.record_step(rec)
        return out

    # keep the jit surface (lower/trace inspection) reachable
    timed_step.lower = jitted.lower
    if getattr(jitted, "trace", None) is not None:
        timed_step.trace = jitted.trace
    timed_step._jitted = jitted
    return timed_step


def make_eval_step(dmodel: DModule, loss_fn: Callable):
    def step(params, batch):
        out = dmodel.apply({"params": params}, batch["input"], deterministic=True)
        return loss_fn(out, batch)

    return jax.jit(step)
