"""Jitted train-step assembly.

The reference's training loop composes DModule forward + DDP backward +
DistributedOptimizer step as three separately-hooked eager phases (SURVEY
§3.3).  TPU-native, the whole step is ONE jit-compiled program: GSPMD
inserts the DP grad all-reduce, TP boundary collectives and ZeRO
reduce-scatter/all-gather, and XLA's latency-hiding scheduler overlaps them
with compute (the role of the reference's async bucket machinery).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from .dmodule.api import DModule

__all__ = ["make_train_step", "make_eval_step"]


def make_train_step(
    dmodel: DModule,
    tx: optax.GradientTransformation,
    loss_fn: Callable,
    *,
    has_aux: bool = False,
    donate: bool = True,
    rng_streams: tuple = ("dropout",),
):
    """Build ``train_step(params, opt_state, batch, step_key) ->
    (params, opt_state, loss)``.

    ``loss_fn(logits_or_outputs, batch)`` computes the scalar loss from the
    model output.  Dropout etc. draw from ``step_key`` folded per stream —
    deterministic and bitwise-identical under any sharding.
    """

    def step(params, opt_state, batch, step_key=None):
        def compute_loss(p):
            rngs = (
                {name: jax.random.fold_in(step_key, i) for i, name in enumerate(rng_streams)}
                if step_key is not None
                else None
            )
            deterministic = step_key is None
            out = dmodel.apply(
                {"params": p}, batch["input"], deterministic=deterministic, rngs=rngs
            )
            return loss_fn(out, batch)

        if has_aux:
            (loss, aux), grads = jax.value_and_grad(compute_loss, has_aux=True)(params)
        else:
            loss, grads = jax.value_and_grad(compute_loss)(params)
            aux = None
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if has_aux:
            return new_params, new_opt_state, loss, aux
        return new_params, new_opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def make_eval_step(dmodel: DModule, loss_fn: Callable):
    def step(params, batch):
        out = dmodel.apply({"params": params}, batch["input"], deterministic=True)
        return loss_fn(out, batch)

    return jax.jit(step)
