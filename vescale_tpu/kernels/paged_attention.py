"""Paged-attention decode kernel — K/V read straight from the page pool.

The PR-10 serve decode step ran, per layer, as four separate XLA ops over
the WHOLE page pool: scatter the new token's K/V into its page, gather
every slot's pages into a dense (S, Tmax, KV, hd) view, masked fp32
softmax over Tmax, then the value matmul.  The gather alone materializes
``S * Tmax`` K/V rows in HBM per layer per token — the single biggest
serving-throughput lever named by ROADMAP item 1.

This kernel (PagedAttention-style, vLLM lineage) replaces the
gather → softmax → matmul chain with ONE kernel: the per-slot page table
and length vector ride in as scalar-prefetch operands, so the BlockSpec
index map addresses the K/V **page pool directly** — grid step ``(s, p)``
DMAs physical page ``table[s, p]`` into VMEM (the null page 0 for unused
entries), and an online fp32 softmax accumulates across the slot's pages
in VMEM scratch.  Nothing dense is ever materialized: HBM traffic is one
read of the pages the slot actually references plus the (S, H, hd) q/out
rows.  The cache write of the new token's K/V stays the single scatter it
always was — it IS the persistence op, not part of attention.

GQA runs natively: q heads are grouped per kv head inside the kernel
(``H = KV * G``) and scores are computed as a (KV,)-batched matmul, so
repeated K/V heads are never materialized.

Numerics: fp32 scores/softmax/accumulation exactly like the XLA
reference; the accumulation ORDER differs (online per-page vs one full-row
softmax), so parity is ulp-bounded rather than bitwise — the bound is
asserted in tests/test_kernels.py and documented in docs/kernels.md.
Fully-masked rows (inactive slots never have them: length >= 1) divide by
a guarded 1.0 like the flash kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas is TPU-only at runtime; import lazily-safe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["paged_decode"]

_NEG_INF = -1e30


def _decode_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, page, kv_heads, group):
    """Grid (S, Pmax): slot-major, pages fastest (TPU grids run
    sequentially, so the online-softmax state in scratch carries across a
    slot's pages).  ``table_ref``/``len_ref`` are the scalar-prefetch
    operands — the same arrays whose values the k/v index maps read."""
    s = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    # (H, hd) -> (KV, G, hd): q heads of kv group g are rows [g*G, (g+1)*G)
    qg = (q_ref[0].astype(jnp.float32) * scale).reshape(kv_heads, group, -1)
    k = jnp.transpose(k_ref[0].astype(jnp.float32), (1, 0, 2))  # (KV, page, hd)
    v = jnp.transpose(v_ref[0].astype(jnp.float32), (1, 0, 2))
    # (KV, G, page) scores: batched over kv heads, contracted over hd
    sc = jax.lax.dot_general(
        qg, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )
    pos = p * page + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 2)
    sc = jnp.where(pos < len_ref[s], sc, _NEG_INF)
    m_prev = m_scr[...]
    m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
    pexp = jnp.exp(sc - m_new[..., None])
    alpha = jnp.exp(m_prev - m_new)
    l_scr[...] = l_scr[...] * alpha + jnp.sum(pexp, axis=-1)
    acc_scr[...] = acc_scr[...] * alpha[..., None] + jax.lax.dot_general(
        pexp, v, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )
    m_scr[...] = m_new

    @pl.when(p == n_pages - 1)
    def _final():
        l = l_scr[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_scr[...] / l_safe[..., None]  # (KV, G, hd)
        o_ref[0] = out.reshape(kv_heads * group, -1).astype(o_ref.dtype)


def paged_decode(q, k_pool, v_pool, table, lengths, *, scale, interpret):
    """One decode-step attention over the paged KV pool.

    ``q``: (S, H, hd) new-token queries; ``k_pool``/``v_pool``:
    (N, page, KV, hd) ONE layer's physical page pool; ``table``:
    (S, Pmax) int32 physical page ids per slot (0 = the reserved null
    page); ``lengths``: (S,) int32 valid positions per slot (the new token
    included).  Returns fp32 (S, H, hd) attention output — callers reshape
    and cast (the XLA reference's ``.astype(dtype)`` boundary).

    Implementation-only: the caller (serve/engine.py) owns the dispatch
    decision and any shard_map wrapping for a kv-head-sharded pool.
    """
    S, H, hd = q.shape
    N, page, KV, hd2 = k_pool.shape
    assert hd == hd2 and H % KV == 0, (q.shape, k_pool.shape)
    Pmax = table.shape[1]
    G = H // KV
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, Pmax),
        in_specs=[
            pl.BlockSpec((1, H, hd), lambda s, p, t, L: (s, 0, 0)),
            pl.BlockSpec((1, page, KV, hd), lambda s, p, t, L: (t[s, p], 0, 0, 0)),
            pl.BlockSpec((1, page, KV, hd), lambda s, p, t, L: (t[s, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, hd), lambda s, p, t, L: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KV, G), jnp.float32),
            pltpu.VMEM((KV, G), jnp.float32),
            pltpu.VMEM((KV, G, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=float(scale), page=page, kv_heads=KV, group=G
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, hd), jnp.float32),
        interpret=interpret,
    )(table.astype(jnp.int32), lengths.astype(jnp.int32), q, k_pool, v_pool)
