"""Fused vocab-parallel cross entropy — sumexp + gold pick in ONE pass.

``loss.py``'s vocab-parallel path computes, per rank, three separate
passes over the local logits shard: ``sum(exp(lg - gmax))``, the gold
logit pick (``take_along_axis``), and (under label smoothing) ``sum(lg)``.
This kernel walks the vocab dim once per row block and accumulates all
three in VMEM scratch — each logit is read from HBM exactly once — while
keeping the no-full-logits property: everything here is per-shard; the
cross-shard ``pmax``/``psum`` stay with the caller, unchanged.

Shapes: ``lg`` (N, Vs) fp32 local shard rows, ``idx`` (N,) int32 LOCAL
column ids (already clipped in-range by the caller — out-of-range rows are
masked by the caller's ``in_range`` exactly like the XLA path), ``gmax``
(N,) fp32 global row max (stop-gradient, nondiff).  Returns
``(sumexp, picked, sumlg)`` fp32 (N,) each.

Differentiable via custom_vjp (the loss sits under ``value_and_grad`` in
every train step): the backward is its own one-pass kernel computing
``dlg = g_se * exp(lg - gmax) + onehot(idx) * g_pick + g_sl`` — the exact
cotangent jax AD derives for the XLA path's three ops.

Parity: fp32, same elementwise math; the vocab-dim SUM is blocked, so
accumulation order differs from XLA's row reduction — parity is
ulp-bounded (asserted in tests/test_kernels.py; docs/kernels.md)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is TPU-only at runtime; import lazily-safe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["fused_xent_parts", "xent_blocks"]


def _fit_pow2(n: int, cap: int) -> int:
    """Largest power-of-two divisor of ``n``, at most ``cap`` (>= 1)."""
    b = 1
    while b * 2 <= cap and n % (b * 2) == 0:
        b *= 2
    return b


def xent_blocks(n_rows: int, vs: int):
    """(row_block, col_block) for the kernel grid, or None when the shard
    is not worth a kernel launch (callers fall back to the XLA path and
    count it)."""
    if n_rows <= 0 or vs < 8:
        return None
    return _fit_pow2(n_rows, 8), _fit_pow2(vs, 512)


def _xent_fwd_kernel(lg_ref, idx_ref, gmax_ref, se_ref, pk_ref, sl_ref,
                     se_s, pk_s, sl_s, *, block_c):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        se_s[...] = jnp.zeros(se_s.shape, jnp.float32)
        pk_s[...] = jnp.zeros(pk_s.shape, jnp.float32)
        sl_s[...] = jnp.zeros(sl_s.shape, jnp.float32)

    lg = lg_ref[...].astype(jnp.float32)            # (R, C)
    gmax = gmax_ref[...]                            # (R, 1)
    cols = j * block_c + jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
    se_s[...] = se_s[...] + jnp.sum(jnp.exp(lg - gmax), axis=1, keepdims=True)
    hit = cols == idx_ref[...]                      # (R, C) one-hot row pick
    pk_s[...] = pk_s[...] + jnp.sum(jnp.where(hit, lg, 0.0), axis=1, keepdims=True)
    sl_s[...] = sl_s[...] + jnp.sum(lg, axis=1, keepdims=True)

    @pl.when(j == nv - 1)
    def _final():
        se_ref[...] = se_s[...]
        pk_ref[...] = pk_s[...]
        sl_ref[...] = sl_s[...]


def _xent_bwd_kernel(lg_ref, idx_ref, gmax_ref, gse_ref, gpk_ref, gsl_ref, dlg_ref,
                     *, block_c):
    j = pl.program_id(1)
    lg = lg_ref[...].astype(jnp.float32)
    gmax = gmax_ref[...]
    cols = j * block_c + jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
    dlg = gse_ref[...] * jnp.exp(lg - gmax)
    dlg = dlg + jnp.where(cols == idx_ref[...], gpk_ref[...], 0.0)
    dlg = dlg + gsl_ref[...]
    dlg_ref[...] = dlg.astype(dlg_ref.dtype)


def _row_spec(R):
    return pl.BlockSpec((R, 1), lambda i, j: (i, 0))


def _fwd_call(lg, idx, gmax, interpret):
    N, Vs = lg.shape
    R, C = xent_blocks(N, Vs)
    grid = (N // R, Vs // C)
    col2 = lambda i, j: (i, j)
    outs = pl.pallas_call(
        functools.partial(_xent_fwd_kernel, block_c=C),
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, C), col2),
            _row_spec(R),
            _row_spec(R),
        ],
        out_specs=(_row_spec(R), _row_spec(R), _row_spec(R)),
        out_shape=(
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
        ],
        interpret=interpret,
    )(lg, idx[:, None].astype(jnp.int32), gmax[:, None].astype(jnp.float32))
    return tuple(o[:, 0] for o in outs)


def _bwd_call(lg, idx, gmax, gse, gpk, gsl, interpret):
    N, Vs = lg.shape
    R, C = xent_blocks(N, Vs)
    grid = (N // R, Vs // C)
    col2 = lambda i, j: (i, j)
    return pl.pallas_call(
        functools.partial(_xent_bwd_kernel, block_c=C),
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, C), col2),
            _row_spec(R),
            _row_spec(R),
            _row_spec(R),
            _row_spec(R),
            _row_spec(R),
        ],
        out_specs=pl.BlockSpec((R, C), col2),
        out_shape=jax.ShapeDtypeStruct(lg.shape, lg.dtype),
        interpret=interpret,
    )(
        lg,
        idx[:, None].astype(jnp.int32),
        gmax[:, None].astype(jnp.float32),
        gse[:, None].astype(jnp.float32),
        gpk[:, None].astype(jnp.float32),
        gsl[:, None].astype(jnp.float32),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_xent_parts(lg, idx, gmax, interpret):
    """(sumexp, picked, sumlg) over the vocab dim of ``lg`` in one pass.
    ``idx`` int32 local gold columns (clipped), ``gmax`` fp32 row max
    (treated nondiff — the caller stop-gradients it, and the max shift
    cancels in the gradient exactly as in the XLA path)."""
    return _fwd_call(lg, idx, gmax, interpret)


def _fused_fwd(lg, idx, gmax, interpret):
    return _fwd_call(lg, idx, gmax, interpret), (lg, idx, gmax)


def _fused_bwd(interpret, res, cts):
    lg, idx, gmax = res
    gse, gpk, gsl = cts
    dlg = _bwd_call(lg, idx, gmax, gse, gpk, gsl, interpret)
    # int cotangent is float0; gmax is stop-gradient upstream
    return dlg, np.zeros(idx.shape, jax.dtypes.float0), jnp.zeros_like(gmax)


fused_xent_parts.defvjp(_fused_fwd, _fused_bwd)
