"""Fused adamw_lowmem update — one kernel over (g, m, v) per leaf.

``parallel/optimizer.py``'s ``scale_by_adam_lowmem`` runs its moment
update as a chain of ~10 elementwise XLA ops per leaf (two casts in, two
muls + adds for each moment, square, sqrt, two divides, three casts out).
This kernel performs the WHOLE chain in one pass per block — each element
of g/m/v is read once from HBM and each output written once, instead of
XLA's fusion boundaries deciding how many intermediate materializations
the chain costs.

The math is the reference chain verbatim, in the same order, in fp32 —
purely elementwise, so kernel output is BIT-IDENTICAL to the XLA path
(asserted in tests/test_kernels.py, not ulp-bounded).  The bias-correction
scalars c1/c2 are computed once per step by the caller (exactly where the
reference computes them) and ride in as a scalar-prefetch operand.

Sharded leaves: the public entry is wrapped in ``custom_partitioning``
with the STATE leaf's sharding as the rule (g is resharded to match m/v),
which is precisely ZeRO's weight-update sharding — the update runs on
each rank's 1/dp state shard, same as the XLA chain under GSPMD — so
kernel dispatch does not change the program's collective structure.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import def_partition

try:  # pallas is TPU-only at runtime; import lazily-safe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["fused_adamw_update"]

# Flattened leaves are viewed as (rows, _LANES) and each grid step works a
# (_SUB, _LANES) block: lane dim matches the TPU tile (…, 128) so nothing
# is padded inside a tile, and 64K elements per step keeps the sequential
# grid short (a 16M-element weight is 256 steps, not tens of thousands)
# while staying ~0.5 MB of VMEM across the six operands.  Any leaf size
# works — the launch pads the tail block once, outside the kernel.
_LANES = 128
_SUB = 512
_BLOCK = _SUB * _LANES  # elements per grid step


def _adamw_kernel(coef_ref, g_ref, m_ref, v_ref, u_ref, mo_ref, vo_ref, *, b1, b2, eps):
    # the reference chain (optimizer.scale_by_adam_lowmem.one), same order
    g32 = g_ref[...].astype(jnp.float32)
    m32 = b1 * m_ref[...].astype(jnp.float32) + (1.0 - b1) * g32
    v32 = b2 * v_ref[...].astype(jnp.float32) + (1.0 - b2) * jnp.square(g32)
    c1 = coef_ref[0]
    c2 = coef_ref[1]
    u_ref[...] = ((m32 / c1) / (jnp.sqrt(v32 / c2) + eps)).astype(u_ref.dtype)
    mo_ref[...] = m32.astype(mo_ref.dtype)
    vo_ref[...] = v32.astype(vo_ref.dtype)


def _fused_local(g, m, v, coef, *, b1, b2, eps, state_dtype, interpret):
    """The per-shard kernel launch: flatten, pad to the block size, run the
    1-D grid, slice back.  Zero padding is harmless through the chain
    (0 -> u = 0 / (0 + eps) = 0) and sliced off anyway."""
    shape = g.shape
    n = g.size
    nb = max(1, -(-n // _BLOCK))
    pad = nb * _BLOCK - n

    def flat(x):
        x = x.reshape(-1)
        if pad:
            x = jnp.pad(x, (0, pad))
        return x.reshape(nb * _SUB, _LANES)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((_SUB, _LANES), lambda i, c: (i, 0)),
            pl.BlockSpec((_SUB, _LANES), lambda i, c: (i, 0)),
            pl.BlockSpec((_SUB, _LANES), lambda i, c: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((_SUB, _LANES), lambda i, c: (i, 0)),
            pl.BlockSpec((_SUB, _LANES), lambda i, c: (i, 0)),
            pl.BlockSpec((_SUB, _LANES), lambda i, c: (i, 0)),
        ),
    )
    u, mo, vo = pl.pallas_call(
        functools.partial(_adamw_kernel, b1=b1, b2=b2, eps=eps),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((nb * _SUB, _LANES), g.dtype),
            jax.ShapeDtypeStruct((nb * _SUB, _LANES), state_dtype),
            jax.ShapeDtypeStruct((nb * _SUB, _LANES), state_dtype),
        ),
        interpret=interpret,
    )(coef.astype(jnp.float32), flat(g), flat(m), flat(v))

    def unflat(x):
        return x.reshape(-1)[:n].reshape(shape)

    return unflat(u), unflat(mo), unflat(vo)


@functools.lru_cache(maxsize=64)
def _partitioned_fused(ndim, b1, b2, eps, state_dtype_name, interpret):
    """One custom_partitioning rule per (rank, hyperparams): elementwise,
    so every output follows the STATE leaf's sharding (m — the ZeRO
    weight-update shard) and g/v are co-sharded to it.  Registered through
    the shared :func:`kernels.def_partition` shim."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    state_dtype = jnp.dtype(state_dtype_name)

    @custom_partitioning
    def fused(g, m, v, coef):
        return _fused_local(
            g, m, v, coef, b1=b1, b2=b2, eps=eps, state_dtype=state_dtype,
            interpret=interpret,
        )

    def _state_sharding(mesh, arg_shapes):
        spec = getattr(arg_shapes[1].sharding, "spec", None) or P()
        return NamedSharding(mesh, P(*spec))

    def infer(mesh, arg_shapes, result_shape):
        sh = _state_sharding(mesh, arg_shapes)
        return (sh, sh, sh)

    def partition(mesh, arg_shapes, result_shape):
        sh = _state_sharding(mesh, arg_shapes)
        rep = NamedSharding(mesh, P())

        def lower(g, m, v, coef):
            return _fused_local(
                g, m, v, coef, b1=b1, b2=b2, eps=eps, state_dtype=state_dtype,
                interpret=interpret,
            )

        return mesh, lower, (sh, sh, sh), (sh, sh, sh, rep)

    dims = " ".join(f"a{i}" for i in range(ndim)) or "..."
    leaf = dims
    def_partition(
        fused,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule=f"{leaf}, {leaf}, {leaf}, c -> {leaf}, {leaf}, {leaf}",
    )
    return fused


def fused_adamw_update(g, m, v, c1, c2, *, b1, b2, eps, state_dtype, interpret):
    """(updates, m_new, v_new) for one leaf — bit-identical to the XLA
    chain in ``scale_by_adam_lowmem`` (same elementwise ops, same order).
    ``c1``/``c2`` are the caller-computed bias corrections (traced f32
    scalars)."""
    coef = jnp.stack([jnp.asarray(c1, jnp.float32), jnp.asarray(c2, jnp.float32)])
    fn = _partitioned_fused(
        g.ndim, float(b1), float(b2), float(eps), jnp.dtype(state_dtype).name,
        bool(interpret),
    )
    return fn(g, m, v, coef)
