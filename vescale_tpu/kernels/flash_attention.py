"""Flash attention — the fused Pallas TPU kernels (forward + backward).

The kernel half of ``ops/flash_attention.py`` (which owns dispatch, the
custom_vjp and the GSPMD partition rule): forward streams K/V blocks
through the MXU with online-softmax accumulation in fp32 and saves the
per-row logsumexp; backward runs the standard flash decomposition as two
kernels (dq over q-blocks; dk/dv over kv-blocks) recomputing probabilities
from the saved LSE — the T x T score matrix never touches HBM in either
direction, so activation memory is O(T * D).

Lives under ``vescale_tpu.kernels`` so the dispatch contract (and lint
rule VSC206) covers it; the entry points here are implementation-only and
assume the caller already decided kernel-vs-XLA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas is TPU-only at runtime; import lazily-safe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = [
    "_HAS_PALLAS",
    "_NEG_INF",
    "_use_streaming",
    "_flash_fwd_pallas",
    "_flash_bwd_pallas",
]

_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp/where VPU-safe


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, D)
    D = q.shape[-1]

    nk_total = seq_len // block_k
    if causal:
        last = (qi * block_q + block_q - 1) // block_k + 1
        nk = jnp.minimum(nk_total, last)
    else:
        nk = nk_total

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, D), jnp.float32)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # (1, block_q, 1) block: trailing singleton satisfies TPU tiling rules
    lse_ref[0] = (m + jnp.log(l_safe))[:, None]


# The resident kernels keep whole-(T, D) K/V (or Q/dO) blocks in VMEM —
# fastest when they fit (one HBM fetch amortized over the whole inner loop).
# Past this budget (scoped VMEM is ~16 MB; leave headroom for the compute
# blocks) the streaming kernels walk the inner loop as a grid dimension with
# fp32 scratch accumulators instead: VMEM O(block), HBM traffic O(T^2/block)
# on the streamed side — the standard large-T flash trade.
_VMEM_RESIDENT_BUDGET = 10 * 1024 * 1024


def _use_streaming(T: int, D: int, dtype) -> bool:
    # two resident (T, D) arrays, double-buffered by the pipeline
    return 4 * T * D * jnp.dtype(dtype).itemsize > _VMEM_RESIDENT_BUDGET


def _fwd_kernel_stream(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                       *, scale, causal, block_q, block_k, seq_len):
    """Streaming forward: grid (BH, nq, nk) — k/v arrive one block per grid
    step; online-softmax state lives in VMEM scratch across the nk steps."""
    qi = pl.program_id(1)
    j = pl.program_id(2)
    nk = seq_len // block_k

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:, 0] = m_new

    if causal:
        # blocks fully above the diagonal contribute nothing; skip compute
        # (the DMA for the block still happens — data-independent grid)
        pl.when(j * block_k <= qi * block_q + block_q - 1)(compute)
    else:
        compute()

    @pl.when(j == nk - 1)
    def _final():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:, 0] + jnp.log(l_safe))[:, None]


def _flash_fwd_pallas(q3, k3, v3, scale, causal, block_q, block_k, interpret, H, KV,
                      streaming=None):
    """q3: (B*H, T, D); k3/v3: (B*KV, T, D) — GQA never materializes the
    repeated K/V heads; the BlockSpec index map routes each q head to its
    kv group (rows are consecutive per group, llama repeat convention)."""
    BH, T, D = q3.shape
    rep = H // KV
    if streaming is None:
        streaming = _use_streaming(T, D, k3.dtype)
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k, seq_len=T)
    out_shape = (
        jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
    )
    if streaming:
        kv_row_s = lambda b, i, j: ((b // H) * KV + (b % H) // rep, j, 0)
        return pl.pallas_call(
            functools.partial(_fwd_kernel_stream, **kw),
            out_shape=out_shape,
            grid=(BH, T // block_q, T // block_k),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, D), kv_row_s),
                pl.BlockSpec((1, block_k, D), kv_row_s),
            ],
            out_specs=(
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, D), jnp.float32),
            ],
            interpret=interpret,
        )(q3, k3, v3)
    kv_row = lambda b, i: ((b // H) * KV + (b % H) // rep, 0, 0)
    grid = (BH, T // block_q)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, **kw),
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), kv_row),
            pl.BlockSpec((1, T, D), kv_row),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ),
        interpret=interpret,
    )(q3, k3, v3)


# ----------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]    # (block_q,)
    delta = delta_ref[0, :, 0]  # (block_q,)
    D = q.shape[-1]
    nk_total = seq_len // block_k
    if causal:
        last = (qi * block_q + block_q - 1) // block_k + 1
        nk = jnp.minimum(nk_total, last)
    else:
        nk = nk_total
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, causal, block_q, block_k, seq_len, rep):
    """Grid (B*KV, T//block_k, rep): the last (fastest) grid dim walks the
    ``rep`` q heads of this kv group, accumulating into the same dk/dv
    block (TPU grids run sequentially, so output revisiting is the
    accumulation pattern) — GQA head reduction without materializing
    repeated K/V or an (rep, T, D) VMEM slab."""
    ki = pl.program_id(1)
    r = pl.program_id(2)
    k = k_ref[0].astype(jnp.float32)  # (block_k, D)
    v = v_ref[0].astype(jnp.float32)
    D = k.shape[-1]
    nq_total = seq_len // block_q
    if causal:
        first = (ki * block_k) // block_q  # earliest q block on/after diagonal
    else:
        first = 0
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), 0]
        s = scale * jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])  # (block_q, block_k)
        dv_new = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(
        first, nq_total, body, (jnp.zeros((block_k, D), jnp.float32), jnp.zeros((block_k, D), jnp.float32))
    )
    if rep == 1:
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv.astype(dv_ref.dtype)
    else:

        # rep > 1 outputs are fp32 (cast happens outside the kernel): the
        # cross-head accumulation must not round through bf16 each step
        @pl.when(r == 0)
        def _init():
            dk_ref[0] = dk
            dv_ref[0] = dv

        @pl.when(r > 0)
        def _acc():
            dk_ref[0] = dk_ref[0] + dk
            dv_ref[0] = dv_ref[0] + dv


def _dq_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
                      *, scale, causal, block_q, block_k, seq_len):
    """Streaming dq: grid (BH, nq, nk), dq accumulates in fp32 scratch."""
    qi = pl.program_id(1)
    j = pl.program_id(2)
    nk = seq_len // block_k

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(j * block_k <= qi * block_q + block_q - 1)(compute)
    else:
        compute()

    @pl.when(j == nk - 1)
    def _final():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                       dk_scr, dv_scr, *, scale, causal, block_q, block_k, seq_len, rep):
    """Streaming dk/dv: grid (B*KV, nk, rep, nq) — k/v blocks stay resident
    while q/do stream; the GQA head-group reduction accumulates in the same
    fp32 scratch as the q loop (no fp32 output-revisit pass needed)."""
    ki = pl.program_id(1)
    r = pl.program_id(2)
    i = pl.program_id(3)
    nq = seq_len // block_q

    @pl.when((r == 0) & (i == 0))
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(i * block_q + block_q - 1 >= ki * block_k)(compute)
    else:
        compute()

    @pl.when((r == rep - 1) & (i == nq - 1))
    def _final():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q3, k3, v3, o3, do3, lse, scale, causal, block_q, block_k, interpret, H, KV,
                      streaming=None):
    BH, T, D = q3.shape
    rep = H // KV
    if streaming is None:
        streaming = _use_streaming(T, D, k3.dtype)
    if streaming:
        return _flash_bwd_pallas_stream(
            q3, k3, v3, o3, do3, lse, scale, causal, block_q, block_k, interpret, H, KV
        )
    kv_row = lambda b, i: ((b // H) * KV + (b % H) // rep, 0, 0)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1, keepdims=True)  # (BH, T, 1)
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k, seq_len=T)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **kw),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), kv_row),
            pl.BlockSpec((1, T, D), kv_row),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    # dk/dv: kv-centric grid; q rows of group g are the consecutive
    # [g*rep, (g+1)*rep) band, walked by the last grid dim
    q_row = lambda b, i, r: ((b // KV) * H + (b % KV) * rep + r, 0, 0)
    kv_blk = lambda b, i, r: (b, i, 0)
    acc_dtype = k3.dtype if rep == 1 else jnp.float32
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, rep=rep, **kw),
        out_shape=(
            jax.ShapeDtypeStruct(k3.shape, acc_dtype),
            jax.ShapeDtypeStruct(v3.shape, acc_dtype),
        ),
        grid=(k3.shape[0], T // block_k, rep),
        in_specs=[
            pl.BlockSpec((1, T, D), q_row),
            pl.BlockSpec((1, block_k, D), kv_blk),
            pl.BlockSpec((1, block_k, D), kv_blk),
            pl.BlockSpec((1, T, D), q_row),
            pl.BlockSpec((1, T, 1), q_row),
            pl.BlockSpec((1, T, 1), q_row),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), kv_blk),
            pl.BlockSpec((1, block_k, D), kv_blk),
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk.astype(k3.dtype), dv.astype(v3.dtype)


def _flash_bwd_pallas_stream(q3, k3, v3, o3, do3, lse, scale, causal, block_q, block_k,
                             interpret, H, KV):
    """Large-T backward: both kernels stream their inner loop as a grid dim
    (VMEM O(block)); dk/dv accumulate the GQA group reduction in scratch so
    outputs are native dtype directly."""
    BH, T, D = q3.shape
    rep = H // KV
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1, keepdims=True)
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k, seq_len=T)
    kv_row_s = lambda b, i, j: ((b // H) * KV + (b % H) // rep, j, 0)
    q_blk_s = lambda b, i, j: (b, i, 0)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_stream, **kw),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        grid=(BH, T // block_q, T // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), q_blk_s),
            pl.BlockSpec((1, block_k, D), kv_row_s),
            pl.BlockSpec((1, block_k, D), kv_row_s),
            pl.BlockSpec((1, block_q, D), q_blk_s),
            pl.BlockSpec((1, block_q, 1), q_blk_s),
            pl.BlockSpec((1, block_q, 1), q_blk_s),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), q_blk_s),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    # q rows of kv group g are the consecutive [g*rep, (g+1)*rep) band
    q_row_s = lambda b, ki, r, i: ((b // KV) * H + (b % KV) * rep + r, i, 0)
    kv_blk_s = lambda b, ki, r, i: (b, ki, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_stream, rep=rep, **kw),
        out_shape=(
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ),
        grid=(k3.shape[0], T // block_k, rep, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), q_row_s),
            pl.BlockSpec((1, block_k, D), kv_blk_s),
            pl.BlockSpec((1, block_k, D), kv_blk_s),
            pl.BlockSpec((1, block_q, D), q_row_s),
            pl.BlockSpec((1, block_q, 1), q_row_s),
            pl.BlockSpec((1, block_q, 1), q_row_s),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), kv_blk_s),
            pl.BlockSpec((1, block_k, D), kv_blk_s),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv
