"""vescale_tpu.kernels — the Pallas kernel layer behind ONE dispatch contract.

Every hand-written TPU kernel in the framework lives in this package and is
reached through the same three-state knob (``VESCALE_KERNELS``, registered
in ``analysis.envreg``):

  ``off``        (default) the kernels are never consulted — every caller
                 takes exactly the XLA path it took before this package
                 existed, byte-identical (asserted by tests/test_kernels.py).
  ``interpret``  the Pallas kernels run through the pallas INTERPRETER on
                 any backend — slow, but it executes the real kernel code
                 path, so CPU tier-1 exercises the same program a TPU would
                 compile and parity against the XLA reference is checkable
                 bit-for-bit (or to the documented ulp bound where fp32
                 accumulation order differs — docs/kernels.md).
  ``on``         compiled Pallas kernels on TPU; off-TPU this degrades to
                 the XLA path (counted as a fallback) rather than crawling
                 through the interpreter.

Kernels in this package:

  * ``flash_attention``  — online-softmax fused attention (forward +
    backward); dispatched by ``ops/flash_attention.py``.
  * ``paged_decode``     — PagedAttention-style serve decode: K/V read
    straight out of the ``PagedKVCache`` page pool through the per-slot
    page table (scalar-prefetched BlockSpec index maps), online fp32
    softmax masked by the slot length — one kernel instead of the
    gather → masked-softmax → matmul chain; dispatched by
    ``serve/engine.py``.
  * ``fused_adamw``      — the adamw_lowmem moment/update elementwise
    chain as one kernel over (g, m, v); dispatched by
    ``parallel/optimizer.py``.
  * ``fused_xent``       — vocab-parallel cross entropy's per-shard
    sumexp + gold-logit pick + Σlogits in ONE pass over the vocab dim
    (full logits still never materialized); dispatched by ``loss.py``.

Contract points:

  * Dispatch decisions are HOST-side and live-read: each call site asks
    :func:`resolve` (or :func:`mode` + the counters) at trace/build time.
    A jitted program therefore latches the mode at compile time — flip the
    knob, rebuild/retrace, and the other path compiles.  The serve engine
    documents the same latch (mode read at ``ServeEngine`` build).
  * Telemetry: every dispatch decision increments
    ``kernel_dispatch_<name>_total`` (kernel path taken) or
    ``kernel_fallback_<name>_total`` (kernel requested but the XLA path
    ran: off-TPU ``on``, pallas unavailable, unsupported shape), plus the
    ``kernel_dispatch_total`` / ``kernel_fallback_total`` aggregates.
    They ride the telemetry registry gate — a run that never calls
    ``telemetry.init()`` pays one dormant-branch check, nothing else —
    and render as the dashboard's ``kernels:`` block.
  * ``vescale-lint`` VSC206 bans direct ``pallas_call`` outside this
    package, so every kernel stays behind this contract.
  * :func:`def_partition` is the jax-version compat shim for
    ``custom_partitioning.def_partition`` shared by every custom-
    partitioned op (kernel or XLA implementation — one partition rule per
    op, not one per implementation).
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "MODES",
    "mode",
    "resolve",
    "record_dispatch",
    "record_fallback",
    "def_partition",
    "has_pallas",
    "on_tpu",
    "ulps_at_scale",
]

MODES = ("off", "interpret", "on")


def mode() -> str:
    """The active ``VESCALE_KERNELS`` mode (live env read via envreg)."""
    from ..analysis import envreg

    m = (envreg.get_str("VESCALE_KERNELS") or "off").strip().lower()
    if m not in MODES:
        raise ValueError(
            f"VESCALE_KERNELS={m!r}: expected one of {'|'.join(MODES)} "
            "(see docs/kernels.md)"
        )
    return m


def has_pallas() -> bool:
    try:  # pallas imports lazily-safe (TPU-only at compile time)
        from jax.experimental import pallas as _pl  # noqa: F401

        return True
    except Exception:  # pragma: no cover
        return False


def on_tpu() -> bool:
    import jax

    return jax.devices()[0].platform == "tpu"


def resolve(name: str) -> Optional[bool]:
    """One-stop dispatch decision for kernel ``name``.

    Returns ``None`` when the caller must take its XLA path (mode off, no
    pallas, or ``on`` off-TPU), else the ``interpret=`` flag to pass to the
    kernel (True under ``interpret`` mode, False for compiled-on-TPU).
    Counts the decision into the kernel telemetry (no-op while telemetry
    is dormant).  Call sites with their own late fallbacks (shape checks)
    should use :func:`mode` + :func:`record_fallback` instead of counting
    a dispatch they then abandon.
    """
    m = mode()
    if m == "off":
        return None
    if not has_pallas():
        record_fallback(name)
        return None
    if m == "interpret":
        record_dispatch(name)
        return True
    if not on_tpu():  # "on" wants compiled kernels; no TPU -> XLA path
        record_fallback(name)
        return None
    record_dispatch(name)
    return False


def record_dispatch(name: str) -> None:
    """Count one kernel-path dispatch decision (per call site evaluation:
    once per eager call, once per trace for jitted programs)."""
    from ..telemetry import api as _telemetry

    _telemetry.count("kernel_dispatch_total")
    _telemetry.count(f"kernel_dispatch_{name}_total")


def record_fallback(name: str) -> None:
    """Count one requested-but-declined dispatch (the XLA path ran)."""
    from ..telemetry import api as _telemetry

    _telemetry.count("kernel_fallback_total")
    _telemetry.count(f"kernel_fallback_{name}_total")


def ulps_at_scale(a, b) -> float:
    """THE parity metric of the kernel layer (docs/kernels.md): max
    ``|a - b|`` over the fp32 spacing at the reference ``b``'s max
    magnitude — "off by N representable steps at the tensor's scale", so
    near-zero elements don't inflate the number.  NaN and signed-Inf
    patterns must agree exactly: a kernel that overflows to Inf (or
    drops/creates a NaN) where the reference doesn't returns ``inf``, a
    parity failure, never an excluded element.  One definition, imported
    by bench.py, scripts/kernels_smoke.py and tests/test_kernels.py, so
    the asserted bound cannot drift between them."""
    import numpy as np

    a64 = np.asarray(a, np.float64).ravel()
    b64 = np.asarray(b, np.float64).ravel()
    if (
        not (np.isnan(a64) == np.isnan(b64)).all()
        or not (np.isposinf(a64) == np.isposinf(b64)).all()
        or not (np.isneginf(a64) == np.isneginf(b64)).all()
    ):
        return float("inf")
    fin = np.isfinite(a64) & np.isfinite(b64)
    if not fin.any():
        return 0.0
    step = float(np.spacing(np.float32(np.max(np.abs(b64[fin])) or 1.0)))
    return float(np.max(np.abs(a64[fin] - b64[fin])) / step)


def def_partition(cp, **kwargs) -> None:
    """``custom_partitioning.def_partition`` across jax versions: newer jax
    grew ``sharding_rule`` (shardy) and ``need_replication_factors``; jax
    0.4.x has neither.  Keyword args the installed signature doesn't accept
    are dropped — the explicit ``partition``/``infer_sharding_from_operands``
    callbacks (always passed) carry the same contract for GSPMD, so older
    versions lose nothing but the shardy-path rule.  The same shim idea as
    ``collectives.shard_map`` (check_vma/check_rep).  Shared by every
    custom-partitioned op so the kernel and XLA implementations of one op
    register ONE rule through one code path."""
    import inspect as _inspect

    params = frozenset(_inspect.signature(type(cp).def_partition).parameters)
    cp.def_partition(**{k: v for k, v in kwargs.items() if k in params})
