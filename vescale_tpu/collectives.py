"""Mesh collectives + cost model.

Reference: legacy/vescale/dtensor/_collective_utils.py:50-357 (mesh_scatter /
all_to_all / broadcast / reduce_scatter / all_gather / all_reduce over NCCL
process groups) and the bandwidth-factor cost model (:406-475) used by
sharding-strategy selection.

TPU-native: each collective is an XLA op over a named mesh axis, executed via
``shard_map`` so it works both eagerly and under jit, riding ICI.  There are
no process groups and no async handles — overlap comes from XLA's
latency-hiding scheduler (SURVEY §5 "Distributed communication backend").

Functions take and return *global* jax.Arrays whose leading mesh-axis layout
matches the reference's per-rank calling convention: the input's dim
``stack_dim`` (default 0) of size ``mesh.size(dim)`` carries "each rank's
operand" and collectives combine along it.
"""

from __future__ import annotations

import functools
import itertools
from typing import Optional, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DeviceMesh

try:  # jax>=0.4.35
    from jax import shard_map as _shard_map_mod  # type: ignore

    _raw_shard_map = (
        _shard_map_mod.shard_map if hasattr(_shard_map_mod, "shard_map") else _shard_map_mod
    )
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _raw_shard_map  # type: ignore

# kwarg compat across the jax 0.4 -> 0.5+ rename: ``check_rep`` became
# ``check_vma`` and ``axis_names`` was added.  Callers here use the NEW
# spelling; translate (or drop) for older installed versions so the
# per-shard transition kernels build everywhere.
import inspect as _inspect

_SM_PARAMS = frozenset(_inspect.signature(_raw_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, axis_names=None, **kw):
    if check_vma is not None:
        if "check_vma" in _SM_PARAMS:
            kw["check_vma"] = check_vma
        elif "check_rep" in _SM_PARAMS:
            kw["check_rep"] = check_vma
    if axis_names is not None and "axis_names" in _SM_PARAMS:
        # pre-rename jax (< 0.5) drops the partial-manual request: its
        # ``auto=`` spelling exists but lowers partition-id collectives the
        # SPMD partitioner rejects, so every axis goes manual inside the
        # body there.  dmodule._constrain degrades its layout hints to
        # no-ops in that regime (see _legacy_manual_axes) — same values,
        # GSPMD just places the buffers without the explicit pins.
        kw["axis_names"] = axis_names
    return _raw_shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

__all__ = [
    "mesh_all_reduce",
    "mesh_all_gather",
    "mesh_reduce_scatter",
    "mesh_all_to_all",
    "mesh_broadcast",
    "mesh_scatter",
    "mesh_ppermute",
    "all_reduce_q",
    "reduce_scatter_q",
    "next_sr_key",
    "q_psum",
    "q_all_gather",
    "q_psum_scatter",
    "q_all_to_all",
    "allgather_cost",
    "analytic_cost_us",
    "allreduce_cost",
    "reduce_scatter_cost",
    "all_to_all_cost",
    "redistribute_cost",
]

_REDUCE = {
    "sum": jax.lax.psum,
    "avg": lambda x, axis_name: jax.lax.pmean(x, axis_name),
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


def _axis(mesh: DeviceMesh, mesh_dim) -> str:
    return mesh.dim_name(mesh_dim)


def _smap(mesh: DeviceMesh, fn, in_spec, out_spec):
    return shard_map(fn, mesh=mesh.jax_mesh, in_specs=in_spec, out_specs=out_spec, check_vma=False)


def mesh_all_reduce(tensor, mesh: DeviceMesh, reduce_op: str = "sum", mesh_dim=0, stacked: bool = True):
    """If ``stacked``: input dim0 (= mesh dim size) holds per-rank operands,
    output is the reduced value (dim0 removed).  Mirrors
    _collective_utils.py:344."""
    ax = _axis(mesh, mesh_dim)
    op = _REDUCE[reduce_op]
    if stacked:
        f = _smap(mesh, lambda x: op(jnp.squeeze(x, 0), ax), P(ax), P())
        return f(tensor)
    f = _smap(mesh, lambda x: op(x, ax), P(), P())
    return f(tensor)


def mesh_all_gather(tensor, mesh: DeviceMesh, mesh_dim=0, gather_dim: int = 0, stacked: bool = True):
    """All-gather per-rank operands along ``gather_dim``
    (_collective_utils.py:315).  With ``stacked`` the input dim0 carries the
    per-rank shards."""
    ax = _axis(mesh, mesh_dim)
    if stacked:

        def body(x):  # x: (1, *local)
            return jax.lax.all_gather(jnp.squeeze(x, 0), ax, axis=gather_dim, tiled=True)

        return _smap(mesh, body, P(ax), P())(tensor)

    def body(x):
        return jax.lax.all_gather(x, ax, axis=gather_dim, tiled=True)

    return _smap(mesh, body, P(), P())(tensor)


def mesh_reduce_scatter(tensor, mesh: DeviceMesh, reduce_op: str = "sum", scatter_dim: int = 0, mesh_dim=0):
    """Each rank contributes a full tensor (stacked on dim0); output stacks
    each rank's reduced scatter chunk on dim0 (_collective_utils.py:288)."""
    ax = _axis(mesh, mesh_dim)

    def body(x):  # (1, *full)
        x = jnp.squeeze(x, 0)
        if reduce_op == "avg":
            out = jax.lax.psum_scatter(x, ax, scatter_dimension=scatter_dim, tiled=True) / mesh.size(mesh_dim)
        elif reduce_op == "sum":
            out = jax.lax.psum_scatter(x, ax, scatter_dimension=scatter_dim, tiled=True)
        else:
            full = _REDUCE[reduce_op](x, ax)
            n = mesh.size(mesh_dim)
            idx = jax.lax.axis_index(ax)
            chunk = full.shape[scatter_dim] // n
            out = jax.lax.dynamic_slice_in_dim(full, idx * chunk, chunk, axis=scatter_dim)
        return out[None]

    return _smap(mesh, body, P(ax), P(ax))(tensor)


def mesh_all_to_all(tensor, mesh: DeviceMesh, mesh_dim=0, split_dim: int = 0, concat_dim: int = 0):
    """Stacked all-to-all (_collective_utils.py:119): input dim0 = per-rank
    operands; each rank splits its operand along ``split_dim`` and exchanges
    chunk j with rank j, concatenating received chunks along ``concat_dim``.
    Dims are in the *operand* (post-squeeze) coordinate system."""
    ax = _axis(mesh, mesh_dim)

    def body(x):
        x = jnp.squeeze(x, 0)
        out = jax.lax.all_to_all(x, ax, split_axis=split_dim, concat_axis=concat_dim, tiled=True)
        return out[None]

    return _smap(mesh, body, P(ax), P(ax))(tensor)


def mesh_broadcast(tensor, mesh: DeviceMesh, mesh_dim=0, src_rank: int = 0):
    """Broadcast rank ``src_rank``'s operand (from the stacked dim0) to all
    (_collective_utils.py:237): output has no stack dim."""
    ax = _axis(mesh, mesh_dim)

    def body(x):
        x = jnp.squeeze(x, 0)
        masked = jnp.where(jax.lax.axis_index(ax) == src_rank, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, ax)

    return _smap(mesh, body, P(ax), P())(tensor)


def mesh_scatter(tensor, mesh: DeviceMesh, mesh_dim=0, scatter_dim: int = 0, src_rank: int = 0):
    """Scatter chunks of the full tensor along ``scatter_dim`` from
    ``src_rank`` (_collective_utils.py:50).  Output stacks each rank's chunk
    on dim0.  On TPU this is a resharding (slice) — data is already global."""
    n = mesh.size(mesh_dim)
    chunks = jnp.stack(jnp.array_split(tensor, n, axis=scatter_dim), axis=0)
    ax = _axis(mesh, mesh_dim)
    return jax.device_put(chunks, NamedSharding(mesh.jax_mesh, P(ax)))


def mesh_ppermute(tensor, mesh: DeviceMesh, mesh_dim=0, shift: int = 1):
    """Ring permute along a mesh dim (the PP p2p primitive; reference uses
    dist.send/recv — pipe/p2p_communication.py)."""
    ax = _axis(mesh, mesh_dim)
    n = mesh.size(mesh_dim)
    perm = [(i, (i + shift) % n) for i in range(n)]

    def body(x):
        x = jnp.squeeze(x, 0)
        return jax.lax.ppermute(x, ax, perm)[None]

    return _smap(mesh, body, P(ax), P(ax))(tensor)


# ------------------------------------------------- quantized collectives
# Block-scaled int8 gradient collectives (ROADMAP item 2; EQuARX,
# arXiv:2506.17615): quantize each rank's contribution ONCE (per-block fp32
# scales, quant/blockscale.py), move a single packed int8 buffer on the
# wire, and accumulate the dequantized contributions in a wide master dtype
# in FIXED rank order — so the reduction can never overflow int8 and the
# result is deterministic + bitwise replayable by the emulator's quantized
# mode (emulator/quantized.py).  The ``q_*`` helpers run INSIDE a shard_map
# body (an axis name in scope); ``all_reduce_q``/``reduce_scatter_q`` are
# the eager stacked-convention wrappers mirroring ``mesh_all_reduce`` /
# ``mesh_reduce_scatter``.
#
# Wire-dtype convention (debug/comm_mode.py keys on it): REDUCTION payloads
# travel as signed int8 (HLO ``s8``) and pure data-MOVEMENT payloads as
# unsigned int8 (``u8``), so compiled-HLO comm accounting can attribute an
# s8 all-gather to a logical quantized all-reduce and a u8 collective to
# its own logical op.

def _rank_key(key, axis_name, rounding: str):
    """Per-rank stochastic-rounding key: fold the mesh position into the
    seed so ranks draw independent (but replayable) noise."""
    if rounding != "stochastic":
        return None
    return jax.random.fold_in(key, jax.lax.axis_index(axis_name))


_SR_CALLS = itertools.count()


def next_sr_key():
    """A fresh stochastic-rounding key for ONE eager quantized reduction:
    ``fold_in(key(VESCALE_GRAD_COMPRESS_SEED), call_index)``.  Successive
    calls (steps, tree leaves) draw independent noise — reusing one key
    across steps would correlate rounding errors into systematic drift,
    the bias SR exists to remove — while the sequence stays a pure
    function of (seed, call order), so a run is replayable end to end.
    Jit-embedded callers can't use a host counter: they thread a key (or
    ``step``) explicitly — see ``dp_grad_reduce``."""
    from .analysis import envreg

    seed = envreg.get_int("VESCALE_GRAD_COMPRESS_SEED") or 0
    return jax.random.fold_in(jax.random.key(seed), next(_SR_CALLS))


def _compress_settings(block, rounding):
    """Resolve the static compression knobs: explicit args win, else the
    registered VESCALE_GRAD_COMPRESS_* env defaults.  The ONE place the
    block-size and rounding-mode precedence lives (the eager wrappers and
    the DDP/ZeRO reduction path both call it)."""
    from .analysis import envreg
    from .quant import blockscale

    if block is None:
        block = envreg.get_int("VESCALE_GRAD_COMPRESS_BLOCK") or blockscale.DEFAULT_BLOCK
    if rounding is None:
        rounding = (
            "stochastic" if envreg.get_bool("VESCALE_GRAD_COMPRESS_SR") else "nearest"
        )
    return int(block), rounding


def _compress_defaults(block, rounding, key):
    """``_compress_settings`` plus the key draw: an SR call without an
    explicit key gets a FRESH counter-derived one (``next_sr_key``) — note
    this is resolved at TRACE time under jit, where the caller should
    thread a per-step key instead."""
    block, rounding = _compress_settings(block, rounding)
    if rounding == "stochastic" and key is None:
        key = next_sr_key()
    return block, rounding, key


def q_psum(x, axis_name, n: int, *, block, rounding="nearest", key=None,
           acc_dtype=jnp.float32, reduce_op: str = "sum"):
    """Quantized all-reduce over ``axis_name`` (shard_map body helper):
    quantize → all-gather one packed s8 buffer → dequantize-accumulate all
    ``n`` contributions in ``acc_dtype`` in rank order."""
    from .quant import blockscale

    if reduce_op not in ("sum", "avg"):
        raise ValueError(f"quantized reduction supports sum/avg, got {reduce_op!r}")
    qb = blockscale.quantize_int8_blocks(x, block, rounding, _rank_key(key, axis_name, rounding))
    payload = blockscale.pack_int8_payload(qb)
    allp = jax.lax.all_gather(payload, axis_name, axis=0, tiled=False)  # (n, P)
    nb = qb.q.shape[0]
    acc = None
    for r in range(n):  # fixed rank order: deterministic, emulator-replayable
        qr = blockscale.unpack_int8_payload(allp[r], nb, block)
        # the dequantize multiply is EXACT (power-of-two scales,
        # blockscale.py), so backend FMA contraction of this mul into the
        # accumulate add cannot change a bit — the emulator's
        # mul-then-add replay stays bit-for-bit without fighting fusion
        d = qr.q.astype(acc_dtype) * qr.scales.astype(acc_dtype)[:, None]
        acc = d if acc is None else acc + d
    if reduce_op == "avg":
        acc = acc / n
    return acc.reshape(-1)[: x.size].reshape(x.shape).astype(x.dtype)


def _as_move_payload(payload):
    # movement convention: u8 on the wire (see module comment)
    return jax.lax.bitcast_convert_type(payload, jnp.uint8)


def _from_move_payload(payload_u8):
    return jax.lax.bitcast_convert_type(payload_u8, jnp.int8)


def q_all_gather(x, axis_name, n: int, *, axis: int, extent: int, block,
                 rounding="nearest", key=None, acc_dtype=jnp.float32):
    """Quantized all-gather along tensor ``axis`` (shard_map body helper):
    each rank's chunk moves as a packed u8 buffer; chunks are dequantized
    and concatenated in rank order, trimmed to the logical ``extent``.
    Lossy — every rank's data (including the caller's own chunk) round
    trips through int8, so the result is REPLICATED consistently."""
    from .quant import blockscale

    qb = blockscale.quantize_int8_blocks(x, block, rounding, _rank_key(key, axis_name, rounding))
    payload = _as_move_payload(blockscale.pack_int8_payload(qb))
    allp = jax.lax.all_gather(payload, axis_name, axis=0, tiled=False)
    nb = qb.q.shape[0]
    parts = []
    for r in range(n):
        qr = blockscale.unpack_int8_payload(_from_move_payload(allp[r]), nb, block)
        parts.append(blockscale.dequantize_int8_blocks(qr, x.shape, x.dtype, acc_dtype))
    out = jnp.concatenate(parts, axis=axis)
    if out.shape[axis] != extent:
        out = jax.lax.slice_in_dim(out, 0, extent, axis=axis)
    return out


def q_psum_scatter(x, axis_name, n: int, *, scatter_dim: int, block,
                   rounding="nearest", key=None, acc_dtype=jnp.float32,
                   reduce_op: str = "sum"):
    """Quantized reduce-scatter (shard_map body helper): the operand is
    split into ``n`` chunks along ``scatter_dim`` (must divide evenly —
    callers pad first), each chunk quantized separately so its blocks and
    scales travel together through one packed s8 all-to-all; each rank
    dequantize-accumulates its received chunks in rank order."""
    from .quant import blockscale

    if reduce_op not in ("sum", "avg"):
        raise ValueError(f"quantized reduction supports sum/avg, got {reduce_op!r}")
    if x.shape[scatter_dim] % n:
        raise ValueError(
            f"q_psum_scatter: dim {scatter_dim} extent {x.shape[scatter_dim]} "
            f"not divisible by {n} (pad first)"
        )
    chunks = jnp.split(x, n, axis=scatter_dim)
    key0 = _rank_key(key, axis_name, rounding)
    payloads = []
    nb = None
    for c, chunk in enumerate(chunks):
        kc = None if key0 is None else jax.random.fold_in(key0, c)
        qb = blockscale.quantize_int8_blocks(chunk, block, rounding, kc)
        nb = qb.q.shape[0]
        payloads.append(blockscale.pack_int8_payload(qb))
    stackp = jnp.stack(payloads)  # (n, P) s8
    recv = jax.lax.all_to_all(stackp, axis_name, split_axis=0, concat_axis=0, tiled=True)
    acc = None
    for r in range(n):
        qr = blockscale.unpack_int8_payload(recv[r], nb, block)
        # exact dequantize multiply: FMA-contraction-proof (see q_psum)
        d = qr.q.astype(acc_dtype) * qr.scales.astype(acc_dtype)[:, None]
        acc = d if acc is None else acc + d
    if reduce_op == "avg":
        acc = acc / n
    cshape = chunks[0].shape
    csize = 1
    for s in cshape:
        csize *= int(s)
    return acc.reshape(-1)[:csize].reshape(cshape).astype(x.dtype)


def q_all_to_all(x, axis_name, n: int, *, split_axis: int, concat_axis: int,
                 block, rounding="nearest", key=None, acc_dtype=jnp.float32):
    """Quantized all-to-all (shard_map body helper): split along
    ``split_axis`` (must divide evenly), move packed u8 chunk payloads,
    reassemble the received chunks along ``concat_axis`` in rank order.
    Pure movement — lossy only through one quantize round trip."""
    from .quant import blockscale

    if x.shape[split_axis] % n:
        raise ValueError(
            f"q_all_to_all: dim {split_axis} extent {x.shape[split_axis]} "
            f"not divisible by {n} (pad first)"
        )
    chunks = jnp.split(x, n, axis=split_axis)
    key0 = _rank_key(key, axis_name, rounding)
    payloads = []
    nb = None
    for c, chunk in enumerate(chunks):
        kc = None if key0 is None else jax.random.fold_in(key0, c)
        qb = blockscale.quantize_int8_blocks(chunk, block, rounding, kc)
        nb = qb.q.shape[0]
        payloads.append(_as_move_payload(blockscale.pack_int8_payload(qb)))
    stackp = jnp.stack(payloads)  # (n, P) u8
    recv = jax.lax.all_to_all(stackp, axis_name, split_axis=0, concat_axis=0, tiled=True)
    parts = []
    for r in range(n):
        qr = blockscale.unpack_int8_payload(_from_move_payload(recv[r]), nb, block)
        parts.append(
            blockscale.dequantize_int8_blocks(qr, chunks[0].shape, x.dtype, acc_dtype)
        )
    return jnp.concatenate(parts, axis=concat_axis)


_WARNED_COUNTERPRODUCTIVE = set()


def _compress_wire_bytes(n_elements: int, itemsize: int, block: int, op: str, n: int):
    """WIRE-accurate per-device byte accounting for one quantized
    collective vs its uncompressed form: the quantized all-reduce is
    gather-based (moves (n-1) packed contributions vs the ring's
    2(n-1)/n raw), so at large mesh dims it moves MORE — the telemetry
    must say so rather than report payload-packing 'savings'."""
    from .quant import blockscale

    raw = n_elements * itemsize
    packed = blockscale.packed_nbytes(n_elements, block)
    f = (n - 1) / max(1, n)
    if op == "all_reduce":
        return 2.0 * f * raw, float((n - 1) * packed)
    # reduce_scatter: all-to-all of packed chunks vs psum_scatter's ring
    return f * raw, f * packed


def _compress_telemetry(n_elements: int, itemsize: int, block: int, op: str, n: int):
    """Byte-savings accounting per quantized collective call (eager
    wrappers + DDP wiring), using the wire formulas above.  A
    counterproductive configuration (quantized bytes >= raw bytes on the
    wire — e.g. int8 all-reduce on a large dp dim) warns once per
    (op, n) instead of crediting phantom savings."""
    if n <= 1:
        # size-1 mesh dim: no bytes move either way — count the call but
        # record no savings/ratio and never warn about a no-op
        from . import telemetry as _tel

        if _tel.is_active():
            _tel.count("grad_compress_collectives_total")
            _tel.count(f"grad_compress_{op}_total")
        return
    raw_wire, q_wire = _compress_wire_bytes(n_elements, itemsize, block, op, n)
    if q_wire >= raw_wire and (op, n) not in _WARNED_COUNTERPRODUCTIVE:
        _WARNED_COUNTERPRODUCTIVE.add((op, n))
        import warnings

        # a config-review notice latched per (op, n) — the fix is editing
        # VESCALE_GRAD_COMPRESS, not paging anyone; stays a warning
        warnings.warn(  # vescale-lint: disable=VSC207
            f"grad_compress='int8' {op} over a mesh dim of {n} moves "
            f"~{int(q_wire)} bytes on the wire vs ~{int(raw_wire)} uncompressed "
            "(the gather-based quantized all-reduce is O(n) in wire bytes) — "
            "compression is counterproductive here; prefer the ZeRO "
            "reduce-scatter path or disable VESCALE_GRAD_COMPRESS",
            stacklevel=3,
        )
    from . import telemetry as _tel

    if not _tel.is_active():
        return
    _tel.count("grad_compress_collectives_total")
    _tel.count("grad_compress_bytes_saved_total", max(0.0, raw_wire - q_wire))
    _tel.set_gauge("grad_compress_ratio", raw_wire / q_wire if q_wire else 0.0)
    _tel.count(f"grad_compress_{op}_total")


def all_reduce_q(tensor, mesh: DeviceMesh, reduce_op: str = "sum", mesh_dim=0,
                 stacked: bool = True, *, block=None, rounding=None, key=None,
                 acc_dtype=jnp.float32):
    """Block-scaled int8 all-reduce — the quantized ``mesh_all_reduce``.
    Same stacked calling convention; knobs default from the registered
    ``VESCALE_GRAD_COMPRESS_*`` env vars."""
    block, rounding, key = _compress_defaults(block, rounding, key)
    ax = _axis(mesh, mesh_dim)
    n = mesh.size(mesh_dim)
    kw = dict(block=block, rounding=rounding, key=key, acc_dtype=acc_dtype,
              reduce_op=reduce_op)
    if stacked:
        f = _smap(mesh, lambda x: q_psum(jnp.squeeze(x, 0), ax, n, **kw), P(ax), P())
        elems = int(np.prod(tensor.shape[1:]))
    else:
        f = _smap(mesh, lambda x: q_psum(x, ax, n, **kw), P(), P())
        elems = int(np.prod(tensor.shape))
    out = f(tensor)
    _compress_telemetry(elems, jnp.dtype(tensor.dtype).itemsize, block, "all_reduce", n)
    return out


def reduce_scatter_q(tensor, mesh: DeviceMesh, reduce_op: str = "sum",
                     scatter_dim: int = 0, mesh_dim=0, *, block=None,
                     rounding=None, key=None, acc_dtype=jnp.float32):
    """Block-scaled int8 reduce-scatter — the quantized
    ``mesh_reduce_scatter`` (same stacked convention: input dim0 carries
    per-rank full operands, output dim0 the per-rank reduced chunks)."""
    block, rounding, key = _compress_defaults(block, rounding, key)
    ax = _axis(mesh, mesh_dim)
    n = mesh.size(mesh_dim)

    def body(x):  # (1, *full)
        x = jnp.squeeze(x, 0)
        out = q_psum_scatter(
            x, ax, n, scatter_dim=scatter_dim, block=block, rounding=rounding,
            key=key, acc_dtype=acc_dtype, reduce_op=reduce_op,
        )
        return out[None]

    out = _smap(mesh, body, P(ax), P(ax))(tensor)
    elems = int(np.prod(tensor.shape[1:]))
    _compress_telemetry(elems, jnp.dtype(tensor.dtype).itemsize, block, "reduce_scatter", n)
    return out


# ------------------------------------------------------------- cost model
# Bandwidth-factor model mirroring _collective_utils.py:406-475: cost in
# microseconds for `bytes_gb` gigabytes over a mesh dim of size n.  The
# factors are tuned for TPU ICI (~100 GB/s per link v5p) instead of NCCL.
#
# Calibrated mode (telemetry/calibrate.py): when VESCALE_COST_CALIBRATION
# arms a measured table, each cost function answers from the table's
# (op, mesh-dim size, byte bucket) wall-times — interpolated between
# buckets — and falls back to the analytic formula below (with a one-time
# warning per missing op/axis pair) otherwise.  Without a table, or with an
# EMPTY one, the numbers are bit-identical to the analytic model.
_ICI_GBPS = 100.0
_LAUNCH_US = 1.0  # per-op overhead (vs reference's kernel-launch constant)


def _ring_cost(bytes_gb: float, n: int, steps_factor: float) -> float:
    if n <= 1:
        return 0.0
    return _LAUNCH_US + (bytes_gb * steps_factor * (n - 1) / n) / _ICI_GBPS * 1e6


def _measured_us(op: str, num_devices: int, bytes_gb: float):
    from .telemetry import calibrate as _cal

    return _cal.collective_cost_us(op, num_devices, bytes_gb * 1e9)


def analytic_cost_us(op: str, bytes_gb: float, num_devices: int) -> float:
    """The pure bandwidth-factor cost (never consults the calibration
    table) — the planner's in-calibrated-mode fallback for ops whose
    bucket is missing, so one Dijkstra never mixes denominations."""
    factors = {"all_gather": 1.0, "reduce_scatter": 1.0, "all_to_all": 1.0,
               "all_reduce": 2.0, "ppermute": 1.0}
    return _ring_cost(bytes_gb, num_devices, factors[op])


def allgather_cost(bytes_gb: float, num_devices: int) -> float:
    us = _measured_us("all_gather", num_devices, bytes_gb)
    return us if us is not None else _ring_cost(bytes_gb, num_devices, 1.0)


def reduce_scatter_cost(bytes_gb: float, num_devices: int) -> float:
    us = _measured_us("reduce_scatter", num_devices, bytes_gb)
    return us if us is not None else _ring_cost(bytes_gb, num_devices, 1.0)


def allreduce_cost(bytes_gb: float, num_devices: int) -> float:
    us = _measured_us("all_reduce", num_devices, bytes_gb)
    return us if us is not None else _ring_cost(bytes_gb, num_devices, 2.0)


def all_to_all_cost(bytes_gb: float, num_devices: int) -> float:
    us = _measured_us("all_to_all", num_devices, bytes_gb)
    return us if us is not None else _ring_cost(bytes_gb, num_devices, 1.0)


def redistribute_cost(src_spec, dst_spec) -> float:
    """Estimated cost of ``redistribute(src -> dst)`` (reference
    redistribute_cost, _collective_utils.py:453) — used by auto-plan."""
    import math

    if src_spec.mesh != dst_spec.mesh:
        return float("inf")
    nbytes = float(np.prod(src_spec.shape)) * jnp.dtype(src_spec.dtype).itemsize
    gb = nbytes / 1e9
    cost = 0.0
    for i, (s, d) in enumerate(zip(src_spec.placements, dst_spec.placements)):
        n = src_spec.mesh.shape[i]
        if s == d:
            continue
        if s.is_partial() and d.is_replicate():
            cost += allreduce_cost(gb, n)
        elif s.is_partial() and d.is_shard():
            cost += reduce_scatter_cost(gb, n)
        elif (s.is_shard() or s.is_ragged_shard()) and d.is_replicate():
            cost += allgather_cost(gb / n, n)
        elif s.is_shard() and d.is_shard():
            cost += all_to_all_cost(gb / n, n)
        elif s.is_replicate() and (d.is_shard() or d.is_ragged_shard()):
            cost += 0.0  # local slice
        else:
            cost += allreduce_cost(gb, n)
    return cost
