"""Mesh collectives + cost model.

Reference: legacy/vescale/dtensor/_collective_utils.py:50-357 (mesh_scatter /
all_to_all / broadcast / reduce_scatter / all_gather / all_reduce over NCCL
process groups) and the bandwidth-factor cost model (:406-475) used by
sharding-strategy selection.

TPU-native: each collective is an XLA op over a named mesh axis, executed via
``shard_map`` so it works both eagerly and under jit, riding ICI.  There are
no process groups and no async handles — overlap comes from XLA's
latency-hiding scheduler (SURVEY §5 "Distributed communication backend").

Functions take and return *global* jax.Arrays whose leading mesh-axis layout
matches the reference's per-rank calling convention: the input's dim
``stack_dim`` (default 0) of size ``mesh.size(dim)`` carries "each rank's
operand" and collectives combine along it.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DeviceMesh

try:  # jax>=0.4.35
    from jax import shard_map as _shard_map_mod  # type: ignore

    _raw_shard_map = (
        _shard_map_mod.shard_map if hasattr(_shard_map_mod, "shard_map") else _shard_map_mod
    )
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _raw_shard_map  # type: ignore

# kwarg compat across the jax 0.4 -> 0.5+ rename: ``check_rep`` became
# ``check_vma`` and ``axis_names`` was added.  Callers here use the NEW
# spelling; translate (or drop) for older installed versions so the
# per-shard transition kernels build everywhere.
import inspect as _inspect

_SM_PARAMS = frozenset(_inspect.signature(_raw_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, axis_names=None, **kw):
    if check_vma is not None:
        if "check_vma" in _SM_PARAMS:
            kw["check_vma"] = check_vma
        elif "check_rep" in _SM_PARAMS:
            kw["check_rep"] = check_vma
    if axis_names is not None and "axis_names" in _SM_PARAMS:
        kw["axis_names"] = axis_names
    return _raw_shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

__all__ = [
    "mesh_all_reduce",
    "mesh_all_gather",
    "mesh_reduce_scatter",
    "mesh_all_to_all",
    "mesh_broadcast",
    "mesh_scatter",
    "mesh_ppermute",
    "allgather_cost",
    "allreduce_cost",
    "reduce_scatter_cost",
    "all_to_all_cost",
    "redistribute_cost",
]

_REDUCE = {
    "sum": jax.lax.psum,
    "avg": lambda x, axis_name: jax.lax.pmean(x, axis_name),
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


def _axis(mesh: DeviceMesh, mesh_dim) -> str:
    return mesh.dim_name(mesh_dim)


def _smap(mesh: DeviceMesh, fn, in_spec, out_spec):
    return shard_map(fn, mesh=mesh.jax_mesh, in_specs=in_spec, out_specs=out_spec, check_vma=False)


def mesh_all_reduce(tensor, mesh: DeviceMesh, reduce_op: str = "sum", mesh_dim=0, stacked: bool = True):
    """If ``stacked``: input dim0 (= mesh dim size) holds per-rank operands,
    output is the reduced value (dim0 removed).  Mirrors
    _collective_utils.py:344."""
    ax = _axis(mesh, mesh_dim)
    op = _REDUCE[reduce_op]
    if stacked:
        f = _smap(mesh, lambda x: op(jnp.squeeze(x, 0), ax), P(ax), P())
        return f(tensor)
    f = _smap(mesh, lambda x: op(x, ax), P(), P())
    return f(tensor)


def mesh_all_gather(tensor, mesh: DeviceMesh, mesh_dim=0, gather_dim: int = 0, stacked: bool = True):
    """All-gather per-rank operands along ``gather_dim``
    (_collective_utils.py:315).  With ``stacked`` the input dim0 carries the
    per-rank shards."""
    ax = _axis(mesh, mesh_dim)
    if stacked:

        def body(x):  # x: (1, *local)
            return jax.lax.all_gather(jnp.squeeze(x, 0), ax, axis=gather_dim, tiled=True)

        return _smap(mesh, body, P(ax), P())(tensor)

    def body(x):
        return jax.lax.all_gather(x, ax, axis=gather_dim, tiled=True)

    return _smap(mesh, body, P(), P())(tensor)


def mesh_reduce_scatter(tensor, mesh: DeviceMesh, reduce_op: str = "sum", scatter_dim: int = 0, mesh_dim=0):
    """Each rank contributes a full tensor (stacked on dim0); output stacks
    each rank's reduced scatter chunk on dim0 (_collective_utils.py:288)."""
    ax = _axis(mesh, mesh_dim)

    def body(x):  # (1, *full)
        x = jnp.squeeze(x, 0)
        if reduce_op == "avg":
            out = jax.lax.psum_scatter(x, ax, scatter_dimension=scatter_dim, tiled=True) / mesh.size(mesh_dim)
        elif reduce_op == "sum":
            out = jax.lax.psum_scatter(x, ax, scatter_dimension=scatter_dim, tiled=True)
        else:
            full = _REDUCE[reduce_op](x, ax)
            n = mesh.size(mesh_dim)
            idx = jax.lax.axis_index(ax)
            chunk = full.shape[scatter_dim] // n
            out = jax.lax.dynamic_slice_in_dim(full, idx * chunk, chunk, axis=scatter_dim)
        return out[None]

    return _smap(mesh, body, P(ax), P(ax))(tensor)


def mesh_all_to_all(tensor, mesh: DeviceMesh, mesh_dim=0, split_dim: int = 0, concat_dim: int = 0):
    """Stacked all-to-all (_collective_utils.py:119): input dim0 = per-rank
    operands; each rank splits its operand along ``split_dim`` and exchanges
    chunk j with rank j, concatenating received chunks along ``concat_dim``.
    Dims are in the *operand* (post-squeeze) coordinate system."""
    ax = _axis(mesh, mesh_dim)

    def body(x):
        x = jnp.squeeze(x, 0)
        out = jax.lax.all_to_all(x, ax, split_axis=split_dim, concat_axis=concat_dim, tiled=True)
        return out[None]

    return _smap(mesh, body, P(ax), P(ax))(tensor)


def mesh_broadcast(tensor, mesh: DeviceMesh, mesh_dim=0, src_rank: int = 0):
    """Broadcast rank ``src_rank``'s operand (from the stacked dim0) to all
    (_collective_utils.py:237): output has no stack dim."""
    ax = _axis(mesh, mesh_dim)

    def body(x):
        x = jnp.squeeze(x, 0)
        masked = jnp.where(jax.lax.axis_index(ax) == src_rank, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, ax)

    return _smap(mesh, body, P(ax), P())(tensor)


def mesh_scatter(tensor, mesh: DeviceMesh, mesh_dim=0, scatter_dim: int = 0, src_rank: int = 0):
    """Scatter chunks of the full tensor along ``scatter_dim`` from
    ``src_rank`` (_collective_utils.py:50).  Output stacks each rank's chunk
    on dim0.  On TPU this is a resharding (slice) — data is already global."""
    n = mesh.size(mesh_dim)
    chunks = jnp.stack(jnp.array_split(tensor, n, axis=scatter_dim), axis=0)
    ax = _axis(mesh, mesh_dim)
    return jax.device_put(chunks, NamedSharding(mesh.jax_mesh, P(ax)))


def mesh_ppermute(tensor, mesh: DeviceMesh, mesh_dim=0, shift: int = 1):
    """Ring permute along a mesh dim (the PP p2p primitive; reference uses
    dist.send/recv — pipe/p2p_communication.py)."""
    ax = _axis(mesh, mesh_dim)
    n = mesh.size(mesh_dim)
    perm = [(i, (i + shift) % n) for i in range(n)]

    def body(x):
        x = jnp.squeeze(x, 0)
        return jax.lax.ppermute(x, ax, perm)[None]

    return _smap(mesh, body, P(ax), P(ax))(tensor)


# ------------------------------------------------------------- cost model
# Bandwidth-factor model mirroring _collective_utils.py:406-475: cost in
# microseconds for `bytes_gb` gigabytes over a mesh dim of size n.  The
# factors are tuned for TPU ICI (~100 GB/s per link v5p) instead of NCCL.
_ICI_GBPS = 100.0
_LAUNCH_US = 1.0  # per-op overhead (vs reference's kernel-launch constant)


def _ring_cost(bytes_gb: float, n: int, steps_factor: float) -> float:
    if n <= 1:
        return 0.0
    return _LAUNCH_US + (bytes_gb * steps_factor * (n - 1) / n) / _ICI_GBPS * 1e6


def allgather_cost(bytes_gb: float, num_devices: int) -> float:
    return _ring_cost(bytes_gb, num_devices, 1.0)


def reduce_scatter_cost(bytes_gb: float, num_devices: int) -> float:
    return _ring_cost(bytes_gb, num_devices, 1.0)


def allreduce_cost(bytes_gb: float, num_devices: int) -> float:
    return _ring_cost(bytes_gb, num_devices, 2.0)


def all_to_all_cost(bytes_gb: float, num_devices: int) -> float:
    return _ring_cost(bytes_gb, num_devices, 1.0)


def redistribute_cost(src_spec, dst_spec) -> float:
    """Estimated cost of ``redistribute(src -> dst)`` (reference
    redistribute_cost, _collective_utils.py:453) — used by auto-plan."""
    import math

    if src_spec.mesh != dst_spec.mesh:
        return float("inf")
    nbytes = float(np.prod(src_spec.shape)) * jnp.dtype(src_spec.dtype).itemsize
    gb = nbytes / 1e9
    cost = 0.0
    for i, (s, d) in enumerate(zip(src_spec.placements, dst_spec.placements)):
        n = src_spec.mesh.shape[i]
        if s == d:
            continue
        if s.is_partial() and d.is_replicate():
            cost += allreduce_cost(gb, n)
        elif s.is_partial() and d.is_shard():
            cost += reduce_scatter_cost(gb, n)
        elif (s.is_shard() or s.is_ragged_shard()) and d.is_replicate():
            cost += allgather_cost(gb / n, n)
        elif s.is_shard() and d.is_shard():
            cost += all_to_all_cost(gb / n, n)
        elif s.is_replicate() and (d.is_shard() or d.is_ragged_shard()):
            cost += 0.0  # local slice
        else:
            cost += allreduce_cost(gb, n)
    return cost
