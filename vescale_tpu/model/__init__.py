from . import patch
