"""patch_method (reference vescale/utils/monkey_patch.py) — swap a method on
a class/instance, returning an undo handle.  Used by the reference to patch
HF modules post-parallelize; kept for migration parity."""

from __future__ import annotations

from typing import Any, Callable

__all__ = ["patch_method"]


def patch_method(target: Any, name: str, new_fn: Callable) -> Callable[[], None]:
    old = getattr(target, name)
    setattr(target, name, new_fn)

    def undo() -> None:
        setattr(target, name, old)

    return undo
