"""VocabParallelEmbedding (reference legacy/vescale/model/patch/
vp_embedding.py:38): embedding table sharded on the VOCAB dim; each rank
looks up its slice and the partial results all-reduce.

TPU-native: the masked local lookup + psum is exactly what GSPMD derives
from a Shard(0) table, so the module just declares the layout; the explicit
shard_map path is provided for eager parity tests.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from ...mesh import DeviceMesh

__all__ = ["VocabParallelEmbedding"]


class VocabParallelEmbedding(nn.Module):
    num_embeddings: int
    features: int
    mesh: Optional[DeviceMesh] = None
    vocab_dim_name: str = "tp"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, idx):
        emb = self.param(
            "embedding",
            nn.initializers.normal(stddev=0.02),
            (self.num_embeddings, self.features),
            self.dtype,
        )
        if self.mesh is not None:
            emb = jax.lax.with_sharding_constraint(
                emb, NamedSharding(self.mesh.jax_mesh, P(self.vocab_dim_name, None))
            )
        # one-hot-free gather; XLA partitions it over the sharded vocab dim
        # (masked local lookup + all-reduce, vp_embedding.py forward)
        return jnp.take(emb, idx, axis=0)
