"""Row/Column-parallel linear modules (reference legacy/vescale/model/patch/
linear.py:32,56 — the RowParallelLinear forward rewrite that defers the
partial-sum all-reduce).

TPU-native: the modules annotate kernel layouts; XLA places the all-reduce
(row) / activation split (column) and fuses it with neighbors — the
reference's hand-deferred resharding is the default compiler behavior.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from ...mesh import DeviceMesh

__all__ = ["RowParallelLinear", "ColumnParallelLinear"]


class ColumnParallelLinear(nn.Module):
    features: int
    mesh: Optional[DeviceMesh] = None
    tp_dim_name: str = "tp"
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        k = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features), self.dtype
        )
        if self.mesh is not None:
            k = jax.lax.with_sharding_constraint(
                k, NamedSharding(self.mesh.jax_mesh, P(None, self.tp_dim_name))
            )
        y = x @ k
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros, (self.features,), self.dtype)
            if self.mesh is not None:
                b = jax.lax.with_sharding_constraint(
                    b, NamedSharding(self.mesh.jax_mesh, P(self.tp_dim_name))
                )
            y = y + b
        return y


class RowParallelLinear(nn.Module):
    features: int
    mesh: Optional[DeviceMesh] = None
    tp_dim_name: str = "tp"
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        k = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features), self.dtype
        )
        if self.mesh is not None:
            k = jax.lax.with_sharding_constraint(
                k, NamedSharding(self.mesh.jax_mesh, P(self.tp_dim_name, None))
            )
        y = x @ k  # contraction over the sharded dim -> XLA all-reduces
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros, (self.features,), self.dtype)
            y = y + b  # bias added once, after the reduce (linear.py:56)
        return y
