"""_VocabParallelCrossEntropy (reference legacy/vescale/model/patch/
vp_cross_entropy.py:43,149) — module-form wrapper over the sharded loss."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn

from ...loss import vocab_parallel_cross_entropy
from ...mesh import DeviceMesh

__all__ = ["VocabParallelCrossEntropy"]


class VocabParallelCrossEntropy(nn.Module):
    mesh: Optional[DeviceMesh] = None
    vocab_dim_name: Optional[str] = "tp"
    label_smoothing: float = 0.0

    @nn.compact
    def __call__(self, logits, targets):
        return vocab_parallel_cross_entropy(
            logits,
            targets,
            mesh=self.mesh,
            vocab_dim_name=self.vocab_dim_name if self.mesh is not None else None,
            label_smoothing=self.label_smoothing,
        )
