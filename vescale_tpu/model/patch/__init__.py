from .vp_embedding import VocabParallelEmbedding
from .vp_cross_entropy import VocabParallelCrossEntropy
from .linear import RowParallelLinear, ColumnParallelLinear
from .monkey_patch import patch_method
