"""vescale_tpu.resilience — fault tolerance for production training runs.

Four pieces, one layer (docs/resilience.md):

  1. **faultsim** (faultsim.py): deterministic, env/config-gated fault
     injection — storage read/write ``OSError``s, native-loader failures,
     non-finite loss bursts, simulated preemption, RESOURCE_EXHAUSTED —
     seeded schedules keyed on step/call-count; zero overhead disarmed
     (no-op function references, the ``telemetry.init()`` pattern).
  2. **retry** (retry.py): ``RetryPolicy`` — bounded attempts, exponential
     backoff + deterministic jitter, optional per-attempt timeout — wired
     into checkpoint storage and ``TokenDataLoader.next`` via
     ``VESCALE_CKPT_RETRIES`` / ``VESCALE_IO_BACKOFF_*`` env knobs.
  3. **preempt** (preempt.py): SIGTERM/SIGINT -> stop flag checked at step
     boundaries; one emergency synchronous save, clean exit, sample-exact
     resume (with ``TokenDataLoader.state()``/``load_state()``).
  4. **loop** (loop.py): ``run_resilient(...)`` — auto-resume from the
     newest committed checkpoint, corrupt-checkpoint quarantine, anomaly
     guard (NaN/skip/z-spike -> rollback, replay-then-skip), bounded
     in-process restarts with backoff.
  5. **watchdog** (watchdog.py): per-host heartbeat with a step-progress
     deadline — a hang (wedged collective, dead peer) becomes an
     all-thread stack dump + flight record + optional abort instead of a
     silent infinite stall; ``distributed.barrier``/``all_processes_ok``
     grow ``timeout_s`` (``VESCALE_BARRIER_TIMEOUT``) raising
     ``BarrierTimeout`` at explicit sync points.
  6. **consistency** (consistency.py): cross-rank desync detection —
     cheap all-gathered fingerprints (step/RNG/loader position/replicated
     param sample/tree structure) raising ``DesyncError`` before a
     divergent rank can poison the next save; ``run_resilient`` runs the
     coordinated multi-host protocol on top (agreed preemption, two-phase
     next-boundary commits, common rollback targets).

All recovery events surface as ``resilience_*`` / ``consistency_*``
counters in the telemetry registry (rendered as the ``resilience:``
dashboard block) and as event lines in ``steps.jsonl``.
"""

from . import consistency, faultsim
from .consistency import ConsistencyChecker, DesyncError
from .faultsim import Fault, FaultInjector, arm_from_env, parse_schedule
from .loop import AnomalyPolicy, RunResult, run_resilient
from .preempt import PreemptionHandler
from .retry import RetryPolicy, ckpt_policy, loader_policy, reset_default_policies
from .watchdog import Watchdog

__all__ = [
    "faultsim",
    "consistency",
    "Fault",
    "FaultInjector",
    "parse_schedule",
    "arm_from_env",
    "RetryPolicy",
    "ckpt_policy",
    "loader_policy",
    "reset_default_policies",
    "PreemptionHandler",
    "AnomalyPolicy",
    "RunResult",
    "run_resilient",
    "Watchdog",
    "ConsistencyChecker",
    "DesyncError",
]
