"""Preemption handling — turn SIGTERM into one clean emergency checkpoint.

Cloud TPU/GPU schedulers preempt with a SIGTERM and a grace window (30 s
to a few minutes).  A run that ignores it loses everything since the last
periodic save; a run that handles it saves once, synchronously, and exits
clean — on restart ``run_resilient`` resumes sample-exact from that very
step.

The handler is deliberately tiny and async-signal-safe: the signal
callback only sets a flag (and remembers which signal).  All real work —
draining in-flight async saves, the emergency ``CheckpointManager.save``
— happens at the next step boundary on the training thread
(``run_resilient`` checks ``requested()`` before each step).  ``request()``
is the programmatic twin used by faultsim's ``preempt`` kind, so the whole
path is testable without delivering a real signal (though it handles real
ones too).
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

__all__ = ["PreemptionHandler"]


class PreemptionHandler:
    """SIGTERM/SIGINT -> stop flag; checked at step boundaries.

        handler = PreemptionHandler().install()
        ...
        if handler.requested():
            <drain + emergency save + clean exit>
        handler.uninstall()

    ``install`` chains: the previous handler is saved and restored by
    ``uninstall``.  Installing from a non-main thread is a no-op for the
    signal wiring (CPython restricts ``signal.signal`` to the main
    thread) — ``request()`` still works, so worker-thread test harnesses
    degrade gracefully."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self._signum: Optional[int] = None
        self._prev = {}
        self._installed = False

    # ------------------------------------------------------------ wiring
    def _on_signal(self, signum, frame):
        # flag-set only: the handler runs between main-thread bytecodes, so
        # taking any lock here (telemetry registry included) could deadlock
        # against the very code it interrupted — counting happens at the
        # step boundary that observes the flag
        self._signum = signum
        self._flag.set()

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):  # non-main thread / exotic prev
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------------- state
    def request(self, signum: Optional[int] = None) -> None:
        """Programmatic preemption (faultsim / tests / orchestrators)."""
        self._signum = signum
        self._flag.set()

    def requested(self) -> bool:
        return self._flag.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def clear(self) -> None:
        """Re-arm after a handled preemption (a resumed in-process run)."""
        self._signum = None
        self._flag.clear()
