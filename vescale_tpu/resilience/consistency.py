"""Cross-rank desync detection — cheap fingerprints, loud mismatches.

SPMD training is correct only while every process runs the SAME program
over the same host-side state: step counter, RNG stream, data position,
replicated parameters, mesh/plan shape.  A rank that silently drifts (bit
flip in host memory, a rank-local retry that consumed an extra batch, a
filesystem that fed one rank a stale file) does not crash — it keeps
issuing collectives that still pair up, and the first hard evidence is a
corrupted checkpoint that LOOKS committed (arXiv:2004.13336's sharded
state makes one divergent rank's shard poison the whole save).

This module makes drift a detectable, attributable error: every rank
computes a small int64 fingerprint vector of its host-side state, the
vectors are all-gathered (``distributed.allgather_ints``), and any
field-wise mismatch raises ``DesyncError`` naming the field and every
rank's value — BEFORE the next save can commit divergent state.

Fingerprint fields (one int64 each, ``FIELDS`` order):

  magic       schema version constant — catches mixed-code-version runs
  step        next training step counter
  data_cursor next batch index
  rng_seed    the run's RNG seed (-1: unseeded)
  loader      hash of the loader's rank-INVARIANT position state
              (``batches_served``/``batch``/``seq_len``/``seed``/
              ``dp_world`` — ``dp_rank`` legitimately differs per rank)
  structure   hash of the params/opt-state tree STRUCTURE: treedef, leaf
              shapes, dtypes, shardings/specs — catches mesh/plan drift
  params      hash of a strided value sample of process-REPLICATED leaves
              (every rank holds an identical copy by construction, so any
              difference is real divergence; rank-sharded leaves hold
              legitimately different bytes and contribute to ``structure``
              only)
  extra       caller-provided discriminator (0 default)

Single-process: checks short-circuit to success (no collective), so the
same code path runs everywhere and tier-1 covers the fingerprint logic.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "FIELDS",
    "MAGIC",
    "DesyncError",
    "tree_structure_fingerprint",
    "replicated_sample_fingerprint",
    "fingerprint",
    "compare_rows",
    "check",
    "ConsistencyChecker",
]

# field order of the fingerprint vector; MAGIC bumps on schema change so
# ranks running different code versions mismatch on field 0, loudly
FIELDS = ("magic", "step", "data_cursor", "rng_seed", "loader", "structure", "params", "extra")
MAGIC = 0x7E5CA1E_01  # "vescale" + schema version


def _h64(parts: Sequence[Any]) -> int:
    """Stable 63-bit hash of a sequence of stringable parts (blake2b —
    process-salt-free, unlike ``hash``)."""
    h = hashlib.blake2b(digest_size=8)
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return int.from_bytes(h.digest(), "little") & 0x7FFFFFFFFFFFFFFF


def _mask(v: int) -> int:
    return int(v) & 0x7FFFFFFFFFFFFFFF


def _replicated_host_value(leaf) -> Optional[np.ndarray]:
    """The leaf's full value as a host array IFF every process provably
    holds an identical copy; None otherwise.  Never gathers — a fingerprint
    must stay cheap and collective-free."""
    import jax

    from ..darray import DArray

    if isinstance(leaf, (bool, int, float)):
        return np.asarray(leaf)
    if isinstance(leaf, np.ndarray):
        return leaf
    if isinstance(leaf, DArray):
        from ..placements import Replicate

        if all(isinstance(p, Replicate) for p in leaf.placements):
            try:
                return np.asarray(leaf.to_local())
            except Exception:
                return None
        return None
    if isinstance(leaf, jax.Array):
        try:
            if not leaf.sharding.is_fully_replicated:
                return None
            shards = leaf.addressable_shards
            if not shards:
                return None
            return np.asarray(shards[0].data)
        except Exception:
            return None
    return None


def _leaf_structure(leaf) -> tuple:
    import jax

    from ..darray import DArray

    if isinstance(leaf, DArray):
        return ("darray", tuple(leaf.shape), str(leaf.dtype), str(leaf.spec))
    if isinstance(leaf, jax.Array):
        try:
            sh = str(leaf.sharding)
        except Exception:
            sh = "?"
        return ("jax", tuple(leaf.shape), str(leaf.dtype), sh)
    if isinstance(leaf, np.ndarray):
        return ("np", tuple(leaf.shape), str(leaf.dtype))
    return ("py", type(leaf).__name__)


def tree_structure_fingerprint(*trees) -> int:
    """Hash of the trees' STRUCTURE: treedefs + per-leaf shape/dtype/
    sharding.  Catches a rank building a different mesh, plan, or state
    schema — the desyncs that corrupt checkpoints without ever producing
    a NaN."""
    import jax

    parts: List[Any] = []
    for tree in trees:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        parts.append(str(treedef))
        parts.extend(_leaf_structure(l) for l in leaves)
    return _h64(parts)


def replicated_sample_fingerprint(*trees, sample_stride: int = 4097) -> int:
    """Hash of a strided value sample of every process-replicated leaf.
    ``sample_stride`` keeps the host transfer tiny (a few elements per
    leaf); prime-ish so it does not alias layout periods.  Non-finite
    values hash by position (NaN != NaN would make every fingerprint
    unique)."""
    import jax

    parts: List[Any] = []
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            host = _replicated_host_value(leaf)
            if host is None:
                continue
            flat = np.asarray(host).reshape(-1)
            sample = flat[:: max(1, sample_stride)]
            with np.errstate(all="ignore"):
                # nan_to_num: NaN != NaN would make every fingerprint
                # unique; a NaN burst still changes the hash (to the
                # canonical 0 at that position) so divergence shows
                finite = np.nan_to_num(sample.astype(np.float64, copy=False))
            parts.append(finite.tobytes())
            parts.append(sample.shape)
    return _h64(parts)


def _loader_fingerprint(loader_state: Optional[Dict[str, int]]) -> int:
    if not loader_state:
        return 0
    # dp_rank differs per rank BY DESIGN (each rank reads its own stream
    # slice); everything else must agree
    inv = {k: int(v) for k, v in sorted(loader_state.items()) if k != "dp_rank"}
    return _h64(sorted(inv.items()))


def fingerprint(
    *,
    step: int,
    data_cursor: int = 0,
    rng_seed: Optional[int] = None,
    loader_state: Optional[Dict[str, int]] = None,
    params: Any = None,
    opt_state: Any = None,
    sample_stride: int = 4097,
    extra: int = 0,
) -> np.ndarray:
    """This rank's fingerprint vector (int64, ``FIELDS`` order)."""
    trees = [t for t in (params, opt_state) if t is not None]
    return np.asarray(
        [
            MAGIC,
            int(step),
            int(data_cursor),
            _mask(rng_seed) if rng_seed is not None else -1,
            _loader_fingerprint(loader_state),
            tree_structure_fingerprint(*trees) if trees else 0,
            replicated_sample_fingerprint(*trees, sample_stride=sample_stride)
            if trees
            else 0,
            _mask(extra),
        ],
        np.int64,
    )


class DesyncError(RuntimeError):
    """Ranks disagree on state that must be identical.  Carries the full
    all-gathered matrix so the error message (and forensics) name WHICH
    field diverged and every rank's value — the difference between "the
    job died" and "rank 3 is one batch ahead"."""

    def __init__(self, mismatched: Dict[str, List[int]], rows: np.ndarray):
        self.mismatched = mismatched
        self.rows = rows
        detail = "; ".join(
            f"{field}: " + ", ".join(f"rank{r}={v}" for r, v in enumerate(vals))
            for field, vals in mismatched.items()
        )
        super().__init__(
            f"cross-rank desync detected on {sorted(mismatched)} — {detail}"
        )


def compare_rows(rows: np.ndarray, fields: Sequence[str] = FIELDS) -> Dict[str, List[int]]:
    """Field-wise mismatch map of an all-gathered fingerprint matrix
    (rank-major rows); empty when every rank agrees."""
    rows = np.asarray(rows)
    out: Dict[str, List[int]] = {}
    for i, name in enumerate(fields[: rows.shape[1]]):
        col = rows[:, i]
        if not np.all(col == col[0]):
            out[name] = [int(v) for v in col]
    return out


def check(
    fp: np.ndarray,
    tag: str = "resilience_consistency",
    timeout_s: Optional[float] = None,
) -> np.ndarray:
    """All-gather this rank's fingerprint and verify every rank matches.
    Raises ``DesyncError`` on mismatch (symmetric: every rank sees the
    same gathered matrix, so every rank raises).  Single-process: the
    fingerprint is trivially consistent.  Returns the gathered matrix."""
    from .. import telemetry as _tel
    from ..distributed import allgather_ints

    rows = allgather_ints(fp, tag=tag, timeout_s=timeout_s)
    _tel.count("consistency_checks_total")
    mismatched = compare_rows(rows)
    if mismatched:
        _tel.count("consistency_mismatches_total")
        _tel.record_event(
            "resilience_desync",
            fields=sorted(mismatched),
            rows={f: v for f, v in mismatched.items()},
        )
        raise DesyncError(mismatched, rows)
    return rows


class ConsistencyChecker:
    """Cadenced fingerprint checks for a training loop.

        checker = ConsistencyChecker(every=32)
        ...
        checker.maybe_check(step, params=params, opt_state=opt,
                            data_cursor=cursor, rng_seed=seed,
                            loader_state=loader.state())

    ``every`` trades detection latency against the (tiny) allgather cost;
    ``run_resilient`` aligns its own control-plane exchange with this
    cadence so desync is caught before the next checkpoint save commits."""

    def __init__(
        self,
        every: int = 32,
        sample_stride: int = 4097,
        timeout_s: Optional[float] = None,
    ):
        if every <= 0:
            raise ValueError("ConsistencyChecker every must be positive")
        self.every = int(every)
        self.sample_stride = int(sample_stride)
        self.timeout_s = timeout_s
        self.checks = 0

    def due(self, step: int) -> bool:
        return step % self.every == 0

    def fingerprint(self, step: int, **state) -> np.ndarray:
        return fingerprint(step=step, sample_stride=self.sample_stride, **state)

    def maybe_check(self, step: int, **state) -> Optional[np.ndarray]:
        if not self.due(step):
            return None
        self.checks += 1
        return check(self.fingerprint(step, **state), timeout_s=self.timeout_s)
