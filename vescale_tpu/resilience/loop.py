"""run_resilient — the auto-recovering train loop.

The layer that composes the repo's fault-tolerance ingredients into a run
that actually survives the real world (PAPER.md §L4's reason to exist —
the MegaScale-style recovery loop the checkpoint layer was built to make
cheap): commit-protocol checkpoints (checkpoint/manager.py), sample-exact
loader resume (data/loader.py ``state``/``load_state``), retry/backoff
I/O (resilience/retry.py), preemption handling (resilience/preempt.py),
the optimizer's skip-on-nonfinite signal, and the OOM flight recorder
(telemetry/memtrack.py).  Failure playbook:

  crash / restart        auto-resume from the newest COMMITTED checkpoint:
                         params, optimizer state, RNG stream, loader
                         position, step counter — all one unit.
  corrupt latest ckpt    quarantined (``step_N.corrupt``) and the
                         next-older committed step is tried — a bad disk
                         block costs one checkpoint interval, not the run.
  SIGTERM / SIGINT       stop flag checked at the step boundary: drain
                         in-flight async saves, one emergency SYNCHRONOUS
                         save, clean return (status="preempted").
  NaN / loss-spike burst after ``threshold`` consecutive anomalous steps
                         (non-finite loss, optimizer skip, or z-score
                         spike) roll back to the last good checkpoint and
                         REPLAY (transient faults vanish); if the same
                         window goes bad twice, skip its data (bad batch).
  step exception         (RESOURCE_EXHAUSTED, loader hard-failure, ...)
                         flight-record, back off, restore, retry — up to
                         ``max_restarts`` in-process restarts.

Every recovery event surfaces as a ``resilience_*`` counter in the
telemetry registry (exporters render them as the ``resilience:`` dashboard
block) and as an event line in ``steps.jsonl``.

Determinism contract: with a seeded loader (or a pure ``batch_fn``) and a
deterministic step, a run that suffers any schedule of transient faults
finishes BIT-IDENTICAL to an uninterrupted run — replay recomputes the
same program on the same data from checkpoint-roundtripped state
(scripts/resilience_smoke.py asserts this end to end).
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from . import faultsim as _fs
from .preempt import PreemptionHandler

__all__ = ["AnomalyPolicy", "RunResult", "run_resilient"]


@dataclass
class AnomalyPolicy:
    """When does a sequence of suspicious steps become a rollback?

    A step is ANOMALOUS when its loss is non-finite, the optimizer's
    dynamic-loss-scale machinery skipped it (``skip_count`` > 0 in the
    opt state), or its loss z-scores beyond ``zscore`` against the rolling
    window of the last ``window`` clean losses (only once ``min_history``
    of them exist — early training is spiky by nature).  ``threshold``
    consecutive anomalous steps trigger the rollback."""

    threshold: int = 3
    zscore: float = 0.0  # 0 disables spike detection (NaN/skip still armed)
    window: int = 64
    min_history: int = 16
    max_rollbacks: int = 8


@dataclass
class RunResult:
    params: Any
    opt_state: Any
    step: int  # last COMPLETED step (-1: none)
    status: str  # "completed" | "preempted"
    restarts: int = 0
    rollbacks: int = 0
    quarantined: int = 0
    anomaly_steps: int = 0
    emergency_save_step: Optional[int] = None
    losses: Dict[int, float] = field(default_factory=dict)  # last-run window


def _skip_count(opt_state) -> int:
    """The optimizer's consecutive-skipped-step counter, when it has one
    (DistributedOptimizer with loss_scale='dynamic'); 0 otherwise."""
    if isinstance(opt_state, dict):
        ls = opt_state.get("loss_scale")
        if isinstance(ls, dict) and "skip_count" in ls:
            try:
                return int(ls["skip_count"])
            except (TypeError, ValueError):
                return 0
    return 0


def run_resilient(
    *,
    step_fn: Callable,
    params: Any,
    opt_state: Any,
    manager,
    total_steps: int,
    loader=None,
    batch_fn: Optional[Callable[[int], Any]] = None,
    save_every: int = 100,
    async_save: bool = True,
    rng_seed: Optional[int] = None,
    anomaly: Optional[AnomalyPolicy] = None,
    max_restarts: int = 3,
    restart_backoff: float = 0.5,
    preemption: Optional[PreemptionHandler] = None,
    install_signal_handlers: bool = True,
    on_step: Optional[Callable[[int, float], None]] = None,
) -> RunResult:
    """Run ``total_steps`` training steps with automatic recovery.

    ``step_fn(params, opt_state, batch[, step_key]) -> (params, opt_state,
    loss, ...)`` — a ``make_train_step`` product or anything
    signature-compatible.  Data comes from ``loader`` (a ``TokenDataLoader``
    or anything with ``next()``/``state()``/``load_state()``) or from a pure
    ``batch_fn(batch_index)``; exactly one must be given.  Batch index i
    feeds step i until an escalated anomaly rollback skips a bad window
    (the loop then rides the data cursor forward of the step counter —
    both are checkpointed, so resume stays sample-exact either way).

    ``rng_seed`` (optional) derives ``step_key = fold_in(PRNGKey(seed),
    step)`` per step — replay-stable and checkpointed.

    Resumes automatically from ``manager``'s newest committed checkpoint;
    a checkpoint that commits but fails to restore is quarantined
    (``step_N.corrupt``) and the next-older one is tried.  A run that
    never saved CANNOT be restarted in-process after a step exception
    (the pre-step state is gone once the step ran) — save early.

    NOTE: the anomaly guard reads the loss on the host every step (the
    same sync ``telemetry.record_step`` opts into); ``VESCALE_BENCH=
    resilience`` measures the armed-but-quiescent overhead."""
    if (loader is None) == (batch_fn is None):
        raise ValueError("exactly one of loader / batch_fn is required")
    if total_steps <= 0:
        raise ValueError("total_steps must be positive")
    import jax

    from .. import telemetry as _tel
    from ..telemetry import memtrack as _memtrack

    if not _fs.is_armed():
        _fs.arm_from_env()  # VESCALE_FAULTSIM schedules for scripted runs
    pol = anomaly or AnomalyPolicy()
    handler = preemption or PreemptionHandler()
    own_handler = preemption is None
    if own_handler and install_signal_handlers:
        handler.install()

    base_key = jax.random.PRNGKey(rng_seed) if rng_seed is not None else None

    # ---------------------------------------------------------------- state
    result = RunResult(params=params, opt_state=opt_state, step=-1, status="completed")
    step = 0  # next step to run
    data_cursor = 0  # next batch index (>= step after an escalated skip)
    loss_window: deque = deque(maxlen=max(2, pol.window))
    bad_streak = 0
    restart_attempts = 0
    last_rollback_target: Optional[int] = None
    escalate_skip = False

    def _extra_state(completed_step: int) -> Dict[str, Any]:
        # `completed_step` is the step whose output result.params holds;
        # data_cursor / loader position already point at the NEXT batch
        return {
            "step": int(completed_step),
            "rng_seed": int(rng_seed) if rng_seed is not None else -1,
            "data_cursor": int(data_cursor),
            "loader": loader.state() if loader is not None else {},
        }

    def _ckpt_state(completed_step: int) -> Dict[str, Any]:
        return {
            "model": result.params,
            "optimizer": result.opt_state,
            "extra": _extra_state(completed_step),
        }

    def _event(kind: str, **fields) -> None:
        _tel.record_event(f"resilience_{kind}", **fields)

    def _restore_latest() -> Optional[int]:
        """Restore the newest committed checkpoint, quarantining any that
        commit but will not load.  Returns the restored step or None.
        Mutates result.params/opt_state, step, data_cursor, loader."""
        nonlocal step, data_cursor
        while True:
            target = manager.latest_step()
            if target is None:
                return None
            template = _ckpt_state(0)
            try:
                restored = manager.restore(template, step=target)
            except KeyError as e:
                # missing array key = STRUCTURAL mismatch (e.g. a manual-loop
                # checkpoint without the 'extra' tree, or a renamed state
                # field) — deterministic across every checkpoint, so
                # quarantining would sideline all the good saves and
                # silently restart from scratch.  Refuse instead.
                raise RuntimeError(
                    f"checkpoint step {target} does not match run_resilient's "
                    f"state schema ({e}); refusing to quarantine a "
                    "structurally incompatible (not corrupt) checkpoint — "
                    "restore it manually or resume with matching state"
                ) from e
            except Exception as e:  # corrupt-but-committed: quarantine, go older
                result.quarantined += 1
                dst = manager.quarantine(target)
                if dst is None:
                    # rename failed (read-only root?): without it the same
                    # step stays newest-committed and this loop would spin
                    raise RuntimeError(
                        f"checkpoint step {target} is unloadable ({e!r}) and "
                        "could not be quarantined; aborting restore"
                    ) from e
                _event("quarantine", ckpt_step=target, path=dst, error=repr(e))
                import warnings

                warnings.warn(
                    f"checkpoint step {target} is committed but unloadable "
                    f"({e!r}); quarantined to {dst} — trying the next-older "
                    "committed step",
                    stacklevel=2,
                )
                continue
            result.params = restored["model"]
            result.opt_state = restored["optimizer"]
            extra = restored["extra"]
            result.step = int(extra["step"])
            step = int(extra["step"]) + 1
            data_cursor = int(extra["data_cursor"])  # already next-batch index
            if loader is not None:
                loader.load_state(jax.tree_util.tree_map(int, extra["loader"]))
            saved_seed = int(extra["rng_seed"])
            if rng_seed is not None and saved_seed not in (-1, int(rng_seed)):
                raise ValueError(
                    f"checkpoint was written with rng_seed={saved_seed}, this "
                    f"run uses {rng_seed} — resuming would fork the RNG stream"
                )
            return target

    def _next_batch():
        nonlocal data_cursor
        batch = loader.next() if loader is not None else batch_fn(data_cursor)
        data_cursor += 1
        return batch

    def _save(at_step: int, sync: bool = False) -> None:
        manager.save(
            at_step,
            _ckpt_state(at_step),
            async_checkpoint=async_save and not sync,
        )

    # -------------------------------------------------------------- resume
    resumed = _restore_latest()
    if resumed is not None:
        _tel.count("resilience_resumes_total")
        _event("resume", ckpt_step=resumed)

    try:
        while True:
            # ---------------------------------------------- preemption gate
            _fs.set_step(step)
            if _fs.fires("preempt", ctx=f"step{step}"):
                handler.request()
            if handler.requested():
                result.status = "preempted"
                _tel.count("resilience_preemptions_total")
                # no emergency save mid-anomaly-streak: result.params may be
                # poisoned, and a preemption must not promote them to the
                # newest committed checkpoint (resume replays from the last
                # good one instead — same rule as the periodic save)
                if result.step >= 0 and bad_streak == 0:
                    manager.wait_pending()  # drain in-flight async saves
                    if manager.latest_step() != result.step:
                        _save(result.step, sync=True)
                        _tel.count("resilience_emergency_saves_total")
                        result.emergency_save_step = result.step
                _event(
                    "preempted",
                    at_step=result.step,
                    signum=handler.signum,
                    emergency_save=result.emergency_save_step,
                )
                return result
            if step >= total_steps:
                manager.wait_pending()  # the final async save must commit
                result.status = "completed"
                return result

            # ------------------------------------------------- run one step
            cursor_before = data_cursor
            try:
                # batch fetch INSIDE the try: a loader hard failure (retries
                # exhausted) rides the same restart path as a step exception
                batch = _next_batch()
                _fs.check("oom", ctx=f"step{step}")
                if base_key is not None:
                    out = step_fn(
                        result.params,
                        result.opt_state,
                        batch,
                        jax.random.fold_in(base_key, step),
                    )
                else:
                    out = step_fn(result.params, result.opt_state, batch)
            except KeyboardInterrupt:
                # a fetched-but-never-trained batch must not stay consumed:
                # rewind the stream so the emergency save's cursor matches
                # result.step (otherwise resume silently skips a sample).
                # Only if the fetch actually advanced the cursor — a Ctrl-C
                # inside the fetch itself advanced nothing.
                if data_cursor > cursor_before:
                    data_cursor = cursor_before
                    if loader is not None:
                        st = loader.state()
                        st["batches_served"] = int(st["batches_served"]) - 1
                        loader.load_state(st)
                handler.request()
                continue
            except Exception as e:
                # in-process restart path: flight-record, back off, restore
                _memtrack.maybe_dump_oom(e)
                restart_attempts += 1
                result.restarts += 1
                _tel.count("resilience_restarts_total")
                _event("restart", at_step=step, attempt=restart_attempts, error=repr(e))
                if restart_attempts > max_restarts:
                    raise
                if manager.latest_step() is None:
                    raise  # nothing to restore from: the failure is fatal
                time.sleep(restart_backoff * (2.0 ** (restart_attempts - 1)))
                if _restore_latest() is None:
                    # every committed step was quarantined during restore:
                    # params/step/cursor were never rewound — retrying would
                    # train on from post-exception state with no way back
                    raise RuntimeError(
                        f"restart after step-{step} failure: no checkpoint "
                        "survived restore (all quarantined)"
                    ) from e
                bad_streak = 0
                loss_window.clear()
                continue

            new_params, new_opt_state, loss = out[0], out[1], out[2]
            loss_val = float(loss)
            if _fs.fires("nonfinite_loss", ctx=f"step{step}"):
                loss_val = float("nan")  # observation-level injection: the
                # compiled step is untouched; the guard sees a NaN burst

            # ------------------------------------------------ anomaly guard
            anomalous = not math.isfinite(loss_val) or _skip_count(new_opt_state) > 0
            if (
                not anomalous
                and pol.zscore > 0
                and len(loss_window) >= max(2, pol.min_history)
            ):
                mean = sum(loss_window) / len(loss_window)
                var = sum((v - mean) ** 2 for v in loss_window) / len(loss_window)
                std = var**0.5
                if std > 0 and abs(loss_val - mean) > pol.zscore * std:
                    anomalous = True
            if anomalous:
                bad_streak += 1
                result.anomaly_steps += 1
                _tel.count("resilience_anomaly_steps_total")
            else:
                bad_streak = 0
                loss_window.append(loss_val)

            if anomalous and bad_streak >= pol.threshold:
                # ------------------------------------------------- rollback
                result.rollbacks += 1
                _tel.count("resilience_rollbacks_total")
                _memtrack.dump_now(reason=f"anomaly_rollback@step{step}")
                if result.rollbacks > pol.max_rollbacks:
                    raise RuntimeError(
                        f"anomaly guard: {result.rollbacks} rollbacks exceed "
                        f"max_rollbacks={pol.max_rollbacks}; giving up"
                    )
                bad_step = step  # last (anomalous) step that ran
                if manager.latest_step() is None:
                    raise RuntimeError(
                        f"anomaly at step {step} but no committed checkpoint "
                        "to roll back to (save_every too large?)"
                    )
                manager.wait_pending()  # a pending save may hold a bad step
                target = _restore_latest()
                if target is None:
                    # every committed step was quarantined during restore:
                    # params/step were never rewound — continuing would
                    # train on from the anomalous state with no way back
                    raise RuntimeError(
                        f"anomaly at step {bad_step}: no checkpoint survived "
                        "restore (all quarantined); cannot roll back"
                    )
                escalate_skip = last_rollback_target == target
                if escalate_skip and loader is not None:
                    # the SAME window went bad after a clean replay: its
                    # data is the problem — advance the stream past it
                    st = loader.state()
                    st["batches_served"] = bad_step + 1 - step + int(st["batches_served"])
                    loader.load_state(st)
                    data_cursor += bad_step + 1 - step
                elif escalate_skip:
                    data_cursor += bad_step + 1 - step
                _tel.count("resilience_rollback_data_skips_total" if escalate_skip else "resilience_rollback_replays_total")
                _event(
                    "rollback",
                    bad_step=bad_step,
                    restored_step=target,
                    data_skipped=escalate_skip,
                )
                last_rollback_target = target
                bad_streak = 0
                loss_window.clear()
                continue

            # ------------------------------------------------- commit step
            result.params, result.opt_state = new_params, new_opt_state
            result.step = step
            result.losses[step] = loss_val
            if on_step is not None:
                on_step(step, loss_val)
            # periodic save — but NEVER mid-anomaly-streak: a checkpoint of
            # possibly-poisoned params must not become the rollback target
            if bad_streak == 0 and (
                (step + 1) % max(1, save_every) == 0 or step == total_steps - 1
            ):
                _save(step)
                last_rollback_target = None  # clean committed progress:
                # the next rollback (if any) restores a NEWER step, so
                # re-arm replay-first semantics
            step += 1
    finally:
        if own_handler and install_signal_handlers:
            handler.uninstall()
