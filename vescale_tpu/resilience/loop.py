"""run_resilient — the auto-recovering train loop.

The layer that composes the repo's fault-tolerance ingredients into a run
that actually survives the real world (PAPER.md §L4's reason to exist —
the MegaScale-style recovery loop the checkpoint layer was built to make
cheap): commit-protocol checkpoints (checkpoint/manager.py), sample-exact
loader resume (data/loader.py ``state``/``load_state``), retry/backoff
I/O (resilience/retry.py), preemption handling (resilience/preempt.py),
the optimizer's skip-on-nonfinite signal, and the OOM flight recorder
(telemetry/memtrack.py).  Failure playbook:

  crash / restart        auto-resume from the newest COMMITTED checkpoint:
                         params, optimizer state, RNG stream, loader
                         position, step counter — all one unit.
  corrupt latest ckpt    quarantined (``step_N.corrupt``) and the
                         next-older committed step is tried — a bad disk
                         block costs one checkpoint interval, not the run.
  SIGTERM / SIGINT       stop flag checked at the step boundary: drain
                         in-flight async saves, one emergency SYNCHRONOUS
                         save, clean return (status="preempted").
  capacity change        the faultsim "resize" kind (OR-agreed across
                         ranks in coordinated mode) drains and
                         emergency-saves exactly like a preemption but
                         returns status="resized"; the relaunched run may
                         come back with a DIFFERENT process count/mesh —
                         restore reshards params AND optimizer state from
                         the saved chunks (the writer-mesh block in
                         meta.json routes the shape change to the chunk-box
                         reshard, VSC130) and the elastic loader re-splits
                         its global sample cursor, so the continuation is
                         bit-identical (scripts/elastic_smoke.py proves
                         2->1 and 1->2).
  NaN / loss-spike burst after ``threshold`` consecutive anomalous steps
                         (non-finite loss, optimizer skip, or z-score
                         spike) roll back to the last good checkpoint and
                         REPLAY (transient faults vanish); if the same
                         window goes bad twice, skip its data (bad batch).
  step exception         (RESOURCE_EXHAUSTED, loader hard-failure, ...)
                         flight-record, back off, restore, retry — up to
                         ``max_restarts`` in-process restarts.

Multi-host (``jax.process_count() > 1``) adds the coordinated layer — the
failures that dominate production SPMD runs are CROSS-rank (PAPER.md /
arXiv:1811.02084-scale: one stuck rank stalls every healthy one forever;
arXiv:2004.13336's sharded state lets one divergent rank poison a
checkpoint that looks committed):

  hang                   per-host watchdog (resilience/watchdog.py): no
                         step progress within the deadline -> all-thread
                         stack dump + flight record + (optional) abort so
                         the external restart path takes over; barriers
                         and votes carry ``VESCALE_BARRIER_TIMEOUT`` so a
                         dead peer raises ``BarrierTimeout`` instead of
                         blocking.
  desync                 per-step control-plane exchange (one tiny
                         allgather: step counter, preempt flag, anomaly
                         streak) plus a cadenced consistency fingerprint
                         (resilience/consistency.py: RNG seed, loader
                         position, replicated-param sample, tree/mesh
                         structure) — any mismatch raises ``DesyncError``
                         on EVERY rank before the next save can commit
                         divergent state.
  torn commit            two-phase: every rank votes on its shard writes
                         (``all_processes_ok``) before process 0 writes
                         ``meta.json`` or rotation prunes anything; an
                         async save is committed at the NEXT step boundary
                         (one step of write/compute overlap).  A failed
                         vote means the step is committed NOWHERE and the
                         run continues to the next save.
  partial preemption     any rank's preemption flag is agreed via the
                         control exchange: all ranks drain, emergency-save
                         (two-phase), and exit "preempted" together.
  rollback agreement     restore targets come from
                         ``CheckpointManager.latest_common_step`` (the
                         newest step committed on ALL ranks), so ranks can
                         never roll back to different steps; a step
                         exception is fatal in coordinated mode (peers may
                         be wedged mid-collective — only a process-level
                         restart is safe, and auto-resume makes it cheap).

Every recovery event surfaces as a ``resilience_*`` counter in the
telemetry registry (exporters render them as the ``resilience:`` dashboard
block) and as an event line in ``steps.jsonl``.

Determinism contract: with a seeded loader (or a pure ``batch_fn``) and a
deterministic step, a run that suffers any schedule of transient faults
finishes BIT-IDENTICAL to an uninterrupted run — replay recomputes the
same program on the same data from checkpoint-roundtripped state
(scripts/resilience_smoke.py asserts this end to end).
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from . import consistency as _cons
from . import faultsim as _fs
from .preempt import PreemptionHandler
from .watchdog import Watchdog

__all__ = ["AnomalyPolicy", "RunResult", "run_resilient"]

# control-plane vector: [magic, step, preempt, resize, bad_streak,
# rollbacks, fp_due, <consistency fingerprint fields when fp_due>].
# Exchanged every step in coordinated mode; preempt and resize are ORs,
# everything else must agree.
_COORD_MAGIC = 0x7E5C0
_COORD_FIELDS = ("coord_magic", "step", "preempt", "resize", "bad_streak", "rollbacks", "fp_due")


@dataclass
class AnomalyPolicy:
    """When does a sequence of suspicious steps become a rollback?

    A step is ANOMALOUS when its loss is non-finite, the optimizer's
    dynamic-loss-scale machinery skipped it (``skip_count`` > 0 in the
    opt state), or its loss z-scores beyond ``zscore`` against the rolling
    window of the last ``window`` clean losses (only once ``min_history``
    of them exist — early training is spiky by nature).  ``threshold``
    consecutive anomalous steps trigger the rollback."""

    threshold: int = 3
    zscore: float = 0.0  # 0 disables spike detection (NaN/skip still armed)
    window: int = 64
    min_history: int = 16
    max_rollbacks: int = 8


@dataclass
class RunResult:
    params: Any
    opt_state: Any
    step: int  # last COMPLETED step (-1: none)
    status: str  # "completed" | "preempted" | "resized"
    restarts: int = 0
    rollbacks: int = 0
    quarantined: int = 0
    anomaly_steps: int = 0
    emergency_save_step: Optional[int] = None
    losses: Dict[int, float] = field(default_factory=dict)  # last-run window


def _skip_count(opt_state) -> int:
    """The optimizer's consecutive-skipped-step counter, when it has one
    (DistributedOptimizer with loss_scale='dynamic'); 0 otherwise."""
    if isinstance(opt_state, dict):
        ls = opt_state.get("loss_scale")
        if isinstance(ls, dict) and "skip_count" in ls:
            try:
                return int(ls["skip_count"])
            except (TypeError, ValueError):
                return 0
    return 0


def run_resilient(
    *,
    step_fn: Callable,
    params: Any,
    opt_state: Any,
    manager,
    total_steps: int,
    loader=None,
    batch_fn: Optional[Callable[[int], Any]] = None,
    save_every: int = 100,
    async_save: bool = True,
    rng_seed: Optional[int] = None,
    anomaly: Optional[AnomalyPolicy] = None,
    max_restarts: int = 3,
    restart_backoff: float = 0.5,
    preemption: Optional[PreemptionHandler] = None,
    install_signal_handlers: bool = True,
    on_step: Optional[Callable[[int, float], None]] = None,
    watchdog: Optional[Watchdog] = None,
    watchdog_timeout_s: Optional[float] = None,
    consistency: Optional[_cons.ConsistencyChecker] = None,
    consistency_every: Optional[int] = None,
    coordinate: Optional[bool] = None,
    barrier_timeout_s: Optional[float] = None,
) -> RunResult:
    """Run ``total_steps`` training steps with automatic recovery.

    ``step_fn(params, opt_state, batch[, step_key]) -> (params, opt_state,
    loss, ...)`` — a ``make_train_step`` product or anything
    signature-compatible.  Data comes from ``loader`` (a ``TokenDataLoader``
    or anything with ``next()``/``state()``/``load_state()``) or from a pure
    ``batch_fn(batch_index)``; exactly one must be given.  Batch index i
    feeds step i until an escalated anomaly rollback skips a bad window
    (the loop then rides the data cursor forward of the step counter —
    both are checkpointed, so resume stays sample-exact either way).

    ``rng_seed`` (optional) derives ``step_key = fold_in(PRNGKey(seed),
    step)`` per step — replay-stable and checkpointed.

    Resumes automatically from ``manager``'s newest committed checkpoint;
    a checkpoint that commits but fails to restore is quarantined
    (``step_N.corrupt``) and the next-older one is tried.  A run that
    never saved CANNOT be restarted in-process after a step exception
    (the pre-step state is gone once the step ran) — save early.

    Multi-host: with ``jax.process_count() > 1`` (or ``coordinate=True``)
    the loop runs the coordinated protocol described in the module
    docstring — per-step control exchange, agreed preemption, common
    restore targets, next-boundary two-phase commits, consistency checks
    every ``consistency_every`` steps (env ``VESCALE_CONSISTENCY_EVERY``,
    default 32), and NO in-process step-exception restarts (a peer may be
    wedged mid-collective; abort and auto-resume instead).  ``watchdog``/
    ``watchdog_timeout_s`` (env ``VESCALE_WATCHDOG_TIMEOUT``) arm the hang
    watchdog; ``barrier_timeout_s`` (env ``VESCALE_BARRIER_TIMEOUT``)
    bounds every coordination collective.

    NOTE: the anomaly guard reads the loss on the host every step (the
    same sync ``telemetry.record_step`` opts into); ``VESCALE_BENCH=
    resilience`` / ``VESCALE_BENCH=watchdog`` measure the
    armed-but-quiescent overhead."""
    if (loader is None) == (batch_fn is None):
        raise ValueError("exactly one of loader / batch_fn is required")
    if total_steps <= 0:
        raise ValueError("total_steps must be positive")
    import jax

    from .. import telemetry as _tel
    from ..checkpoint import LAST_LOAD_STATS as _load_stats
    from ..checkpoint.elastic import ElasticMismatchError as _ElasticMismatch
    from ..telemetry import memtrack as _memtrack

    if not _fs.is_armed():
        _fs.arm_from_env()  # VESCALE_FAULTSIM schedules for scripted runs
    pol = anomaly or AnomalyPolicy()
    handler = preemption or PreemptionHandler()
    own_handler = preemption is None
    if own_handler and install_signal_handlers:
        handler.install()

    coord = (jax.process_count() > 1) if coordinate is None else bool(coordinate)

    # ------------------------------------------------- watchdog arming
    own_wd = False
    wd = watchdog
    if wd is None:
        # param deadline overrides the env one (0 = explicit off);
        # abort/exit-code always come from the env (one parser: from_env)
        wd = Watchdog.from_env(timeout_s=watchdog_timeout_s)
        own_wd = wd is not None
    if own_wd:
        wd.start()

    def _beat(at_step: int, phase: str = "step") -> None:
        if wd is not None:
            wd.beat(at_step, phase=phase)

    # ---------------------------------------------- consistency arming
    checker = consistency
    if checker is None:
        from ..analysis import envreg

        env_every = envreg.get_raw("VESCALE_CONSISTENCY_EVERY")
        n = consistency_every if consistency_every is not None else (
            int(env_every) if env_every else 32
        )
        # single-process fingerprints detect nothing (there is no peer to
        # disagree with) — armed by default only when coordinating, or on
        # explicit request (param / env), so bare runs pay zero
        if n > 0 and (coord or consistency_every is not None or env_every):
            checker = _cons.ConsistencyChecker(every=n, timeout_s=barrier_timeout_s)

    base_key = jax.random.PRNGKey(rng_seed) if rng_seed is not None else None

    # ---------------------------------------------------------------- state
    result = RunResult(params=params, opt_state=opt_state, step=-1, status="completed")
    step = 0  # next step to run
    data_cursor = 0  # next batch index (>= step after an escalated skip)
    loss_window: deque = deque(maxlen=max(2, pol.window))
    bad_streak = 0
    restart_attempts = 0
    last_rollback_target: Optional[int] = None
    escalate_skip = False
    resize_requested = False  # faultsim "resize": simulated capacity change

    def _extra_state(completed_step: int) -> Dict[str, Any]:
        # `completed_step` is the step whose output result.params holds;
        # data_cursor / loader position already point at the NEXT batch
        return {
            "step": int(completed_step),
            "rng_seed": int(rng_seed) if rng_seed is not None else -1,
            "data_cursor": int(data_cursor),
            "loader": loader.state() if loader is not None else {},
        }

    def _ckpt_state(completed_step: int) -> Dict[str, Any]:
        return {
            "model": result.params,
            "optimizer": result.opt_state,
            "extra": _extra_state(completed_step),
        }

    def _event(kind: str, **fields) -> None:
        _tel.record_event(f"resilience_{kind}", **fields)

    def _latest() -> Optional[int]:
        """The newest restorable step: committed on ALL ranks when
        coordinating (ranks restoring different steps is a guaranteed
        desync), plain latest otherwise."""
        if coord:
            return manager.latest_common_step(timeout_s=barrier_timeout_s)
        return manager.latest_step()

    def _coordinate() -> tuple:
        """One control-plane allgather: agree on preemption and resize,
        verify the ranks are marching in lockstep, and (on the consistency
        cadence) compare state fingerprints.  Returns the AGREED
        ``(preempt, resize)`` flags (both ORs — any rank's capacity event
        drains everyone); raises ``DesyncError`` on any disagreement —
        symmetric on every rank, and always BEFORE the next save could
        commit divergent state."""
        from ..distributed import allgather_ints

        fp = None
        if checker is not None and checker.due(step):
            checker.checks += 1
            fp = checker.fingerprint(
                step,
                data_cursor=data_cursor,
                rng_seed=rng_seed,
                loader_state=loader.state() if loader is not None else None,
                params=result.params,
                opt_state=result.opt_state,
            )
        vec = [
            _COORD_MAGIC,
            step,
            1 if handler.requested() else 0,
            1 if resize_requested else 0,
            bad_streak,
            result.rollbacks,
            0 if fp is None else 1,
        ]
        # FIXED width always: ranks disagreeing on the fingerprint cadence
        # (the desync case itself) must exchange same-shape rows so the
        # mismatch surfaces as a named DesyncError on fp_due/step, not as
        # an opaque shape error inside the collective
        vec.extend(int(v) for v in fp) if fp is not None else vec.extend(
            [0] * len(_cons.FIELDS)
        )
        rows = allgather_ints(vec, tag="resilience_coord", timeout_s=barrier_timeout_s)
        if rows.shape[0] == 1:
            # coordinate=True on one process (tests, bench): a single row
            # cannot mismatch — skip the compares, keep the counters honest
            if fp is not None:
                _tel.count("consistency_checks_total")
            return bool(vec[2]), bool(vec[3])
        preempt_any = bool(rows[:, 2].any())
        resize_any = bool(rows[:, 3].any())
        mismatched = _cons.compare_rows(rows[:, : len(_COORD_FIELDS)], _COORD_FIELDS)
        mismatched.pop("preempt", None)  # an OR, not an agreement
        mismatched.pop("resize", None)  # likewise
        if not mismatched and fp is not None:
            _tel.count("consistency_checks_total")
            mismatched = _cons.compare_rows(rows[:, len(_COORD_FIELDS) :], _cons.FIELDS)
        if mismatched:
            _tel.count("consistency_mismatches_total")
            _event("desync", at_step=step, fields=sorted(mismatched))
            _memtrack.dump_now(reason=f"desync@step{step}")
            # quarantine the run: raising here (on every rank — the
            # gathered matrix is identical everywhere) guarantees no
            # further save can commit divergent state
            raise _cons.DesyncError(mismatched, rows)
        if preempt_any and not handler.requested():
            handler.request()  # a PEER was preempted; we drain with it
        return preempt_any, resize_any

    def _restore_latest() -> Optional[int]:
        """Restore the newest committed checkpoint, quarantining any that
        commit but will not load.  Returns the restored step or None.
        Mutates result.params/opt_state, step, data_cursor, loader.
        Coordinated mode: the target comes from ``latest_common_step`` and
        per-target restore success is VOTED, so a rank-local read failure
        quarantines the step on every rank together (ranks falling back to
        different steps would desync)."""
        nonlocal step, data_cursor
        while True:
            target = _latest()
            if target is None:
                return None
            template = _ckpt_state(0)
            restore_err: Optional[Exception] = None
            t_restore = time.perf_counter()
            try:
                restored = manager.restore(template, step=target)
            except KeyError as e:
                # missing array key = STRUCTURAL mismatch (e.g. a manual-loop
                # checkpoint without the 'extra' tree, or a renamed state
                # field) — deterministic across every checkpoint, so
                # quarantining would sideline all the good saves and
                # silently restart from scratch.  Refuse instead.
                raise RuntimeError(
                    f"checkpoint step {target} does not match run_resilient's "
                    f"state schema ({e}); refusing to quarantine a "
                    "structurally incompatible (not corrupt) checkpoint — "
                    "restore it manually or resume with matching state"
                ) from e
            except _ElasticMismatch as e:
                # CODED verdict (VSC131/VSC132) from the pre-read preflight:
                # the checkpoint is fine, the worlds are incompatible — a
                # deterministic property of every committed step, so (like
                # the schema case above) quarantining would sideline all
                # the good saves.  A pure mesh/world change never lands
                # here: the writer block routes it to reshard-on-load.
                raise RuntimeError(
                    f"checkpoint step {target} cannot be restored into this "
                    f"run's world ({e}); refusing to quarantine a "
                    "structurally incompatible (not corrupt) checkpoint"
                ) from e
            except Exception as e:  # corrupt-but-committed on THIS rank
                restore_err = e
                restored = None
            ok = restore_err is None
            if coord:
                # restore success is voted: a rank-local read failure must
                # quarantine the step EVERYWHERE (the healthy ranks discard
                # their successful load) or ranks would restore different
                # steps — the desync this whole layer exists to prevent
                from ..distributed import all_processes_ok

                ok = all_processes_ok(
                    ok, tag=f"resilience_restore:{target}", timeout_s=barrier_timeout_s
                )
            if not ok:
                err = repr(restore_err) if restore_err is not None else "peer restore failure"
                result.quarantined += 1
                dst = manager.quarantine(target)
                if dst is None:
                    # rename failed (read-only root?): without it the same
                    # step stays newest-committed and this loop would spin
                    raise RuntimeError(
                        f"checkpoint step {target} is unloadable ({err}) and "
                        "could not be quarantined; aborting restore"
                    ) from restore_err
                _event("quarantine", ckpt_step=target, path=dst, error=err)
                import warnings

                warnings.warn(
                    f"checkpoint step {target} is committed but unloadable "
                    f"({err}); quarantined to {dst} — trying the next-older "
                    "committed step",
                    stacklevel=2,
                )
                continue
            result.params = restored["model"]
            result.opt_state = restored["optimizer"]
            extra = restored["extra"]
            result.step = int(extra["step"])
            step = int(extra["step"]) + 1
            data_cursor = int(extra["data_cursor"])  # already next-batch index
            if _load_stats.get("elastic"):
                # the checkpoint's writer world differed: this restore WAS
                # the cross-world reshard (VSC130); load() already counted
                # resilience_elastic_restores_total / reshard_seconds
                wm = manager.writer_meta(target) if hasattr(manager, "writer_meta") else None
                _event(
                    "elastic_restore",
                    ckpt_step=target,
                    writer=wm,
                    reshard_seconds=time.perf_counter() - t_restore,
                )
            if loader is not None:
                loader.load_state(jax.tree_util.tree_map(int, extra["loader"]))
            saved_seed = int(extra["rng_seed"])
            if rng_seed is not None and saved_seed not in (-1, int(rng_seed)):
                raise ValueError(
                    f"checkpoint was written with rng_seed={saved_seed}, this "
                    f"run uses {rng_seed} — resuming would fork the RNG stream"
                )
            return target

    def _next_batch():
        nonlocal data_cursor
        batch = loader.next() if loader is not None else batch_fn(data_cursor)
        data_cursor += 1
        return batch

    def _save(at_step: int, sync: bool = False) -> None:
        manager.save(
            at_step,
            _ckpt_state(at_step),
            async_checkpoint=async_save and not sync,
        )

    # -------------------------------------------------------------- resume
    resumed = _restore_latest()
    if resumed is not None:
        _tel.count("resilience_resumes_total")
        _event("resume", ckpt_step=resumed)

    commit_due = False  # coordinated mode: an async save awaiting its vote
    try:
        while True:
            # ---------------------------------------------- step-boundary gate
            _fs.set_step(step)
            _beat(step)
            if _fs.fires("hang", ctx=f"step{step}"):
                # simulated wedged collective: stall far past any deadline —
                # the watchdog's detect/dump/abort path is the way out
                from ..analysis import envreg

                time.sleep(envreg.get_float("VESCALE_FAULTSIM_HANG_S"))
            if _fs.fires("preempt", ctx=f"step{step}"):
                handler.request()
            if _fs.fires("resize", ctx=f"step{step}"):
                resize_requested = True  # simulated capacity change: drain
                # and exit "resized" so a supervisor relaunches on the new
                # world size and elastic auto-resume takes over
            # coordinated mode: one control-plane allgather — agreed
            # preemption/resize, lockstep verification, cadenced fingerprints
            if coord:
                preempt_now, resize_now = _coordinate()
            else:
                # an explicitly-armed checker still runs its cadence
                # (trivially consistent alone, but the counters stay honest
                # and the fingerprint computation is validated)
                if checker is not None and checker.due(step):
                    checker.maybe_check(
                        step,
                        data_cursor=data_cursor,
                        rng_seed=rng_seed,
                        loader_state=loader.state() if loader is not None else None,
                        params=result.params,
                        opt_state=result.opt_state,
                    )
                preempt_now = handler.requested()
                resize_now = resize_requested
            if preempt_now or resize_now:
                # preemption wins when both fire in the same boundary (the
                # SIGTERM deadline is the harder constraint); the drain +
                # emergency-save choreography is identical either way
                result.status = "preempted" if preempt_now else "resized"
                _tel.count(
                    "resilience_preemptions_total" if preempt_now
                    else "resilience_resizes_total"
                )
                # no emergency save mid-anomaly-streak: result.params may be
                # poisoned, and a preemption must not promote them to the
                # newest committed checkpoint (resume replays from the last
                # good one instead — same rule as the periodic save)
                if result.step >= 0 and bad_streak == 0:
                    manager.wait_pending()  # drain in-flight async saves
                    if _latest() != result.step:
                        _beat(step, "emergency_save")
                        _save(result.step, sync=True)
                        _tel.count("resilience_emergency_saves_total")
                        result.emergency_save_step = result.step
                _event(
                    result.status,
                    at_step=result.step,
                    signum=handler.signum if preempt_now else None,
                    emergency_save=result.emergency_save_step,
                )
                return result
            if step >= total_steps:
                manager.wait_pending()  # the final async save must commit
                result.status = "completed"
                return result
            if commit_due:
                # two-phase commit of the previous boundary's async save:
                # handle.wait() runs the all-rank vote + meta.json write on
                # this thread — one step of write/compute overlap, and a
                # failed vote means the step committed NOWHERE (the run
                # continues to the next save)
                manager.wait_pending()
                commit_due = False
                _beat(step, "commit")

            # ------------------------------------------------- run one step
            cursor_before = data_cursor
            try:
                # batch fetch INSIDE the try: a loader hard failure (retries
                # exhausted) rides the same restart path as a step exception
                batch = _next_batch()
                _fs.check("oom", ctx=f"step{step}")
                if base_key is not None:
                    out = step_fn(
                        result.params,
                        result.opt_state,
                        batch,
                        jax.random.fold_in(base_key, step),
                    )
                else:
                    out = step_fn(result.params, result.opt_state, batch)
            except KeyboardInterrupt:
                # a fetched-but-never-trained batch must not stay consumed:
                # rewind the stream so the emergency save's cursor matches
                # result.step (otherwise resume silently skips a sample).
                # Only if the fetch actually advanced the cursor — a Ctrl-C
                # inside the fetch itself advanced nothing.
                if data_cursor > cursor_before:
                    data_cursor = cursor_before
                    if loader is not None:
                        st = loader.state()
                        st["batches_served"] = int(st["batches_served"]) - 1
                        loader.load_state(st)
                handler.request()
                continue
            except Exception as e:
                _memtrack.maybe_dump_oom(e)
                if coord:
                    # multi-host: peers may be wedged inside the failed
                    # step's collective — no Python-level restore here can
                    # reach them, so an in-process restart would desync.
                    # Abort; the supervisor restarts every rank and
                    # auto-resume makes it one checkpoint interval cheap.
                    _event("fatal_step_error", at_step=step, error=repr(e))
                    raise
                # in-process restart path: flight-record, back off, restore
                restart_attempts += 1
                result.restarts += 1
                _tel.count("resilience_restarts_total")
                _event("restart", at_step=step, attempt=restart_attempts, error=repr(e))
                if restart_attempts > max_restarts:
                    raise
                if manager.latest_step() is None:
                    raise  # nothing to restore from: the failure is fatal
                time.sleep(restart_backoff * (2.0 ** (restart_attempts - 1)))
                if _restore_latest() is None:
                    # every committed step was quarantined during restore:
                    # params/step/cursor were never rewound — retrying would
                    # train on from post-exception state with no way back
                    raise RuntimeError(
                        f"restart after step-{step} failure: no checkpoint "
                        "survived restore (all quarantined)"
                    ) from e
                bad_streak = 0
                loss_window.clear()
                continue

            new_params, new_opt_state, loss = out[0], out[1], out[2]
            loss_val = float(loss)
            if _fs.fires("nonfinite_loss", ctx=f"step{step}"):
                loss_val = float("nan")  # observation-level injection: the
                # compiled step is untouched; the guard sees a NaN burst

            # ------------------------------------------------ anomaly guard
            anomalous = not math.isfinite(loss_val) or _skip_count(new_opt_state) > 0
            if (
                not anomalous
                and pol.zscore > 0
                and len(loss_window) >= max(2, pol.min_history)
            ):
                mean = sum(loss_window) / len(loss_window)
                var = sum((v - mean) ** 2 for v in loss_window) / len(loss_window)
                std = var**0.5
                if std > 0 and abs(loss_val - mean) > pol.zscore * std:
                    anomalous = True
            if anomalous:
                bad_streak += 1
                result.anomaly_steps += 1
                _tel.count("resilience_anomaly_steps_total")
            else:
                bad_streak = 0
                loss_window.append(loss_val)

            if anomalous and bad_streak >= pol.threshold:
                # ------------------------------------------------- rollback
                result.rollbacks += 1
                _tel.count("resilience_rollbacks_total")
                _memtrack.dump_now(reason=f"anomaly_rollback@step{step}")
                if result.rollbacks > pol.max_rollbacks:
                    raise RuntimeError(
                        f"anomaly guard: {result.rollbacks} rollbacks exceed "
                        f"max_rollbacks={pol.max_rollbacks}; giving up"
                    )
                bad_step = step  # last (anomalous) step that ran
                if not coord and manager.latest_step() is None:
                    raise RuntimeError(
                        f"anomaly at step {step} but no committed checkpoint "
                        "to roll back to (save_every too large?)"
                    )
                manager.wait_pending()  # a pending save may hold a bad step
                if coord and _latest() is None:
                    # checked AFTER the drain (the drained commit may be the
                    # only checkpoint) and via the all-rank intersection so
                    # every rank raises together
                    raise RuntimeError(
                        f"anomaly at step {step} but no committed checkpoint "
                        "to roll back to (save_every too large?)"
                    )
                target = _restore_latest()
                if target is None:
                    # every committed step was quarantined during restore:
                    # params/step were never rewound — continuing would
                    # train on from the anomalous state with no way back
                    raise RuntimeError(
                        f"anomaly at step {bad_step}: no checkpoint survived "
                        "restore (all quarantined); cannot roll back"
                    )
                escalate_skip = last_rollback_target == target
                if escalate_skip and loader is not None:
                    # the SAME window went bad after a clean replay: its
                    # data is the problem — advance the stream past it
                    st = loader.state()
                    st["batches_served"] = bad_step + 1 - step + int(st["batches_served"])
                    loader.load_state(st)
                    data_cursor += bad_step + 1 - step
                elif escalate_skip:
                    data_cursor += bad_step + 1 - step
                _tel.count("resilience_rollback_data_skips_total" if escalate_skip else "resilience_rollback_replays_total")
                _event(
                    "rollback",
                    bad_step=bad_step,
                    restored_step=target,
                    data_skipped=escalate_skip,
                )
                last_rollback_target = target
                bad_streak = 0
                loss_window.clear()
                continue

            # ------------------------------------------------- commit step
            result.params, result.opt_state = new_params, new_opt_state
            result.step = step
            result.losses[step] = loss_val
            if on_step is not None:
                on_step(step, loss_val)
            # periodic save — but NEVER mid-anomaly-streak: a checkpoint of
            # possibly-poisoned params must not become the rollback target
            if bad_streak == 0 and (
                (step + 1) % max(1, save_every) == 0 or step == total_steps - 1
            ):
                _beat(step, "save")
                _save(step)
                if coord and async_save:
                    commit_due = True  # voted at the next step boundary
                last_rollback_target = None  # clean committed progress:
                # the next rollback (if any) restores a NEWER step, so
                # re-arm replay-first semantics
            step += 1
    finally:
        if own_wd:
            wd.stop()
        if own_handler and install_signal_handlers:
            handler.uninstall()
