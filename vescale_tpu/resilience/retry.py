"""Retry/backoff policy for flaky I/O — checkpoint storage + data loader.

A production checkpoint write hits object-store throttling, NFS hiccups
and transient ``OSError``s that a single retry absorbs; without one, a
40k-step run dies on a 50 ms blip.  ``RetryPolicy`` is the one shared
mechanism: bounded attempts, exponential backoff with deterministic
jitter, an optional per-attempt timeout, and telemetry counters
(``resilience_io_retries_total`` / ``resilience_io_giveups_total``) so
every absorbed fault is still visible.

Wiring (this PR): ``FileSystemStorage.read_bytes/write_bytes``
(checkpoint/storage.py) and ``TokenDataLoader.next`` (data/loader.py)
route through module-default policies built from env knobs:

  ===========================  ======== =====================================
  env                          default  meaning
  ---------------------------  -------- -------------------------------------
  VESCALE_CKPT_RETRIES         3        max attempts for checkpoint I/O
  VESCALE_LOADER_RETRIES       3        max attempts for loader batch fetch
  VESCALE_IO_BACKOFF_BASE      0.05     first backoff sleep (seconds)
  VESCALE_IO_BACKOFF_MAX       5.0      backoff ceiling (seconds)
  VESCALE_IO_BACKOFF_JITTER    0.25     +/- fraction of jitter on each sleep
  VESCALE_IO_ATTEMPT_TIMEOUT   0        per-attempt timeout (s); 0 disables
  ===========================  ======== =====================================

Setting a ``*_RETRIES`` knob to 1 restores fail-fast semantics.  The
jitter is seeded (attempt index + policy seed), so two runs of the same
faultsim schedule sleep identically — retries never break determinism of
anything but wall clock.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

__all__ = ["RetryPolicy", "ckpt_policy", "loader_policy", "reset_default_policies"]


@dataclass
class RetryPolicy:
    """Bounded-retry executor.  ``call(fn, *args)`` runs ``fn`` up to
    ``max_attempts`` times, sleeping ``base * 2**attempt`` (+/- seeded
    jitter, capped at ``max_backoff``) between attempts; only
    ``retry_on`` exceptions are retried, everything else propagates
    immediately.  ``attempt_timeout`` > 0 bounds each attempt by running
    it on a helper thread (an attempt that never returns leaks that
    thread until it finishes — the price of killing a hung NFS write)."""

    max_attempts: int = 3
    base_backoff: float = 0.05
    max_backoff: float = 5.0
    jitter: float = 0.25
    attempt_timeout: float = 0.0
    seed: int = 0
    retry_on: Tuple[Type[BaseException], ...] = (OSError, TimeoutError)
    # deterministic-failure subtypes a retry cannot fix: pass through at once
    no_retry: Tuple[Type[BaseException], ...] = (
        FileNotFoundError,
        IsADirectoryError,
        NotADirectoryError,
        PermissionError,
    )
    name: str = "io"

    @classmethod
    def from_env(cls, attempts_var: str, name: str = "io"):
        """Policy from the env registry: ``attempts_var`` must be a
        registered VESCALE_*_RETRIES knob (its declared default applies
        when unset — there is deliberately no shadow default here)."""
        from ..analysis import envreg

        attempts = envreg.get_int(attempts_var)
        return cls(
            max_attempts=max(1, attempts if attempts is not None else 1),
            base_backoff=envreg.get_float("VESCALE_IO_BACKOFF_BASE"),
            max_backoff=envreg.get_float("VESCALE_IO_BACKOFF_MAX"),
            jitter=envreg.get_float("VESCALE_IO_BACKOFF_JITTER"),
            attempt_timeout=envreg.get_float("VESCALE_IO_ATTEMPT_TIMEOUT"),
            name=name,
        )

    # ------------------------------------------------------------- backoff
    def backoff_for(self, attempt: int) -> float:
        """Deterministic sleep before retry ``attempt`` (1-based)."""
        import zlib

        raw = min(self.max_backoff, self.base_backoff * (2.0 ** (attempt - 1)))
        if self.jitter <= 0:
            return raw
        h = zlib.crc32(f"{self.name}:{self.seed}:{attempt}".encode()) / 0xFFFFFFFF
        return raw * (1.0 + self.jitter * (2.0 * h - 1.0))

    def _run_once(self, fn: Callable, args, kwargs):
        if self.attempt_timeout <= 0:
            return fn(*args, **kwargs)
        # one daemon thread PER timed attempt — never a shared pool: two
        # hung NFS writes would occupy a pool forever and every later
        # attempt would "time out" queued without ever executing.  A hung
        # thread is abandoned (leaks until the syscall returns — the price
        # of bounding a hung write) and its late result is discarded.
        box: list = []

        def _runner():
            try:
                box.append(("ok", fn(*args, **kwargs)))
            except BaseException as e:  # delivered to the waiting caller
                box.append(("err", e))

        t = threading.Thread(
            target=_runner, name=f"retry-{self.name}-attempt", daemon=True
        )
        t.start()
        t.join(self.attempt_timeout)
        if not box:
            raise TimeoutError(
                f"{self.name}: attempt exceeded {self.attempt_timeout}s"
            )
        kind, payload = box[0]
        if kind == "err":
            raise payload
        return payload

    # ---------------------------------------------------------------- call
    def call(self, fn: Callable, *args, description: str = "", **kwargs):
        """Run ``fn`` under the policy.  ``description`` names the resource
        in the absorbed-fault log line — every retried op is visible on
        stderr even when the run ultimately succeeds."""
        import sys

        from .. import telemetry as _tel

        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return self._run_once(fn, args, kwargs)
            except self.retry_on as e:
                if isinstance(e, self.no_retry):
                    raise  # a retry cannot make a missing file appear
                last = e
                if attempt >= self.max_attempts:
                    break
                _tel.count("resilience_io_retries_total")
                _tel.count(f"resilience_{self.name}_retries_total")
                delay = self.backoff_for(attempt)
                print(
                    f"[resilience] {self.name} "
                    f"{description or getattr(fn, '__name__', 'op')}: attempt "
                    f"{attempt}/{self.max_attempts} failed ({e!r}); retrying "
                    f"in {delay:.3f}s",
                    file=sys.stderr,
                )
                if delay > 0:
                    time.sleep(delay)
        # retry-exhausted hard failure: count it, then re-raise the ORIGINAL
        # exception (callers' except clauses keep their established types)
        _tel.count("resilience_io_giveups_total")
        assert last is not None
        raise last

    def wrap(self, fn: Callable, description: str = "") -> Callable:
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, description=description, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped


# ----------------------------------------------------- module default policies
# Built lazily (first I/O op) so env knobs set by a launcher before the first
# checkpoint/batch are honored; reset_default_policies() re-reads them (tests).
_CKPT: Optional[RetryPolicy] = None
_LOADER: Optional[RetryPolicy] = None
_LOCK = threading.Lock()


def ckpt_policy() -> RetryPolicy:
    global _CKPT
    if _CKPT is None:
        with _LOCK:
            if _CKPT is None:
                _CKPT = RetryPolicy.from_env("VESCALE_CKPT_RETRIES", name="ckpt_io")
    return _CKPT


def loader_policy() -> RetryPolicy:
    global _LOADER
    if _LOADER is None:
        with _LOCK:
            if _LOADER is None:
                _LOADER = RetryPolicy.from_env("VESCALE_LOADER_RETRIES", name="loader")
                # native-loader failures surface as RuntimeError, not OSError
                _LOADER.retry_on = (OSError, RuntimeError, TimeoutError)
    return _LOADER


def reset_default_policies() -> None:
    """Drop the cached env-derived policies (tests mutate the env)."""
    global _CKPT, _LOADER
    with _LOCK:
        _CKPT = None
        _LOADER = None
