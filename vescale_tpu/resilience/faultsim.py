"""Deterministic fault injection — the test substrate of the resilience
layer.

Production LLM training fails in a handful of well-known ways (PAPER.md
§L4; MegaScale §5): flaky storage during checkpoint save/restore, a data
loader hiccup, a burst of non-finite losses after a bad batch or a flipped
bit, a scheduler preemption, and device-memory exhaustion.  None of these
can be waited for in CI — this module *schedules* them.

A fault fires at a deterministic point keyed on the per-kind **call
counter** (the Nth time the hook is consulted) or on the **training step**
(as published by ``set_step``), optionally repeating ``count`` consecutive
times; a seeded-probability mode (``p`` + ``seed``) hashes the call index
so even "random" faults replay exactly.  The schedule comes from
``arm([...])`` in tests or the ``VESCALE_FAULTSIM`` env var in scripted
runs:

    VESCALE_FAULTSIM="storage_write:call=3;preempt:step=10;nonfinite_loss:step=6,count=4"
    VESCALE_FAULTSIM="storage_write:step=3,rank=1"     # only process 1 fails

Grammar: ``kind:key=value[,key=value...]`` joined by ``;`` where keys are
``call`` (0-based per-kind call index), ``step``, ``count`` (default 1),
``p`` (probability per call), ``seed`` and ``rank`` (restrict firing to
one process — the same schedule text armed on every process injects the
fault on exactly that rank, the multi-host failure-path substrate).

Fault kinds and their hook sites:

  ================  ====================================================
  kind              raised / observed at
  ----------------  ----------------------------------------------------
  storage_write     ``OSError`` from ``FileSystemStorage.write_bytes``
  storage_read      ``OSError`` from ``FileSystemStorage.read_bytes``
  loader_next       native-loader failure in ``TokenDataLoader.next``
  nonfinite_loss    observed by ``run_resilient`` — the step's loss reads
                    as NaN to the anomaly guard (the compiled program is
                    untouched; real NaNs come from hardware)
  preempt           sets the run's preemption stop flag (as if SIGTERM)
  oom               ``RuntimeError("RESOURCE_EXHAUSTED...")`` around the
                    train step (exercises flight recorder + restart path)
  hang              observed by ``run_resilient`` — the step boundary
                    sleeps ``VESCALE_FAULTSIM_HANG_S`` (default 3600)
                    seconds, simulating a wedged collective so the
                    watchdog's detect/dump/abort path is exercisable
  resize            observed by ``run_resilient`` — simulated capacity
                    change at step N: the loop drains in-flight saves,
                    emergency-saves, and returns ``status="resized"`` so
                    a supervisor can relaunch on a different world size
                    (the elastic-restore test substrate,
                    scripts/elastic_smoke.py)
  request_timeout   observed by ``run_serve_resilient`` — the oldest
                    in-flight request's deadline is forced expired at the
                    decode-step boundary, exercising timeout cancellation
                    (the request is explicitly rejected, never lost)
  slow_decode       observed by ``run_serve_resilient`` — the decode step
                    sleeps ``VESCALE_FAULTSIM_SLOW_DECODE_S`` (default
                    0.05) seconds, simulating a straggling decode so
                    latency-SLO shedding and the p99 budget are testable
  replica_kill      observed by ``run_serve_resilient`` — after the Nth
                    decode step WITH in-flight work the process dies
                    abruptly (``os._exit`` with
                    ``VESCALE_FAULTSIM_KILL_EXIT_CODE``, default 29):
                    no drain, no cleanup — the crashed-replica substrate
                    the fleet router's failover path is proven against
                    (scripts/fleet_smoke.py)
  poll_blackhole    observed by ``telemetry.ops_server`` — a due
                    ``/router`` or ``/healthz`` GET is answered with an
                    abrupt connection close (no bytes), simulating a
                    network partition between a healthy replica and the
                    fleet router's poller: the breaker opens without the
                    replica dying, and readmission is probe-driven
  canary_diverge    observed by the rollout canary replay
                    (serve/autoscale.py) — ONE logit's sign is flipped
                    while the pinned golden prompts replay through
                    freshly swapped weights, so the canary's token-stream
                    comparison diverges and the auto-rollback path is
                    provable without a genuinely bad checkpoint
  router_kill       observed by ``FleetRouter.pump`` — the ROUTER process
                    dies abruptly at the Nth pump boundary (``os._exit``
                    with ``VESCALE_FAULTSIM_KILL_EXIT_CODE``, default
                    29): no drain, no lease release — the crashed-leader
                    substrate the journal recovery and warm-standby
                    takeover paths are proven against
                    (scripts/router_ha_smoke.py)
  journal_torn_write  observed by ``FleetJournal.flush`` — the LAST
                    buffered record of the Nth flush is written torn
                    (truncated mid-frame, as if the process died inside
                    ``write``), exercising the replayer's torn-tail
                    tolerance without killing anything
  ================  ====================================================

Gating contract (the ``telemetry.init()`` pattern): while disarmed the
module hooks ``check`` / ``fires`` ARE the no-op function references
``_noop_check`` / ``_noop_fires`` (tests assert identity) — a production
run pays one attribute access + call per hook site and nothing else.
Callers must use ``faultsim.check(...)`` attribute access, never
``from faultsim import check`` (which would freeze the disarmed binding).
"""

from __future__ import annotations

import threading
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = [
    "KINDS",
    "Fault",
    "FaultInjector",
    "arm",
    "disarm",
    "is_armed",
    "get_injector",
    "parse_schedule",
    "arm_from_env",
    "check",
    "fires",
    "set_step",
]

KINDS = (
    "storage_write",
    "storage_read",
    "loader_next",
    "nonfinite_loss",
    "preempt",
    "oom",
    "hang",
    "resize",
    "request_timeout",
    "slow_decode",
    "replica_kill",
    "poll_blackhole",
    "canary_diverge",
    "router_kill",
    "journal_torn_write",
)

# errors raised by `check` per kind; observation-level kinds (nonfinite_loss,
# preempt) never raise — callers use `fires` and act on the bool
_RAISES = {
    "storage_write": lambda ctx: OSError(f"[faultsim] injected storage write failure ({ctx})"),
    "storage_read": lambda ctx: OSError(f"[faultsim] injected storage read failure ({ctx})"),
    "loader_next": lambda ctx: RuntimeError(f"[faultsim] injected native loader failure ({ctx})"),
    "oom": lambda ctx: RuntimeError(
        f"RESOURCE_EXHAUSTED: [faultsim] injected out-of-memory ({ctx})"
    ),
}


def _splitmix64(x: int) -> int:
    """Deterministic 64-bit mix (the data loader's SplitMix64 finalizer) —
    the seeded-probability mode must replay bit-exactly across runs."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def _process_rank() -> int:
    """This process's rank for the ``rank=`` selector.  Prefers the env
    bootstrap (set before jax initializes in spawned-worker rigs) so a
    schedule can be parsed and filtered without touching jax; falls back
    to ``jax.process_index()``."""
    from ..analysis import envreg

    env = envreg.get_int("VESCALE_PROCESS_ID")
    if env is not None:
        return env
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


@dataclass
class Fault:
    """One scheduled fault.  Exactly one trigger: ``at_call`` (0-based
    per-kind call index), ``at_step`` (training step, via ``set_step``), or
    ``p`` (seeded per-call probability).  ``count`` consecutive firings.
    ``rank`` (optional) restricts firing to one process — the selector that
    makes MULTI-process failure paths injectable (one rank's storage dies,
    one rank hangs, one rank's RNG skews) while the peers stay healthy."""

    kind: str
    at_call: Optional[int] = None
    at_step: Optional[int] = None
    p: float = 0.0
    seed: int = 0
    count: int = 1
    rank: Optional[int] = None
    fired: int = field(default=0, init=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (one of {KINDS})")
        triggers = (self.at_call is not None) + (self.at_step is not None) + (self.p > 0)
        if triggers != 1:
            raise ValueError(
                f"fault {self.kind}: exactly one trigger of call/step/p required"
            )

    def should_fire(self, call_index: int, step: Optional[int]) -> bool:
        # rank selector is a FILTER, not a trigger: the schedule text is
        # identical on every process, only the selected rank fires
        if self.rank is not None and self.rank != _process_rank():
            return False
        # a fault fires at most `count` times TOTAL: a step-keyed fault that
        # re-fired when the recovery loop replays the same step would make
        # every rollback loop forever (transient-fault semantics)
        if self.fired >= self.count:
            return False
        if self.at_call is not None:
            return self.at_call <= call_index < self.at_call + self.count
        if self.at_step is not None:
            return step is not None and self.at_step <= step < self.at_step + self.count
        # seeded probability: hash (seed, kind, call index) to a replayable
        # coin — crc32, not hash() (str hashing is salted per process)
        h = _splitmix64(
            self.seed * 1000003 + zlib.crc32(self.kind.encode()) + call_index * 2654435761
        )
        return (h / 2.0**64) < self.p


class FaultInjector:
    """Live schedule state: per-kind call counters + the current step.
    Exists only between ``arm`` and ``disarm`` — its absence IS off."""

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)
        self.calls: Dict[str, int] = {k: 0 for k in KINDS}
        self.fired_total: Dict[str, int] = {k: 0 for k in KINDS}
        self.step: Optional[int] = None
        self._lock = threading.Lock()  # storage hooks run on io pool threads

    def _consult(self, kind: str, ctx: str) -> bool:
        with self._lock:
            idx = self.calls[kind]
            self.calls[kind] = idx + 1
            hit = False
            for f in self.faults:
                if f.kind == kind and f.should_fire(idx, self.step):
                    f.fired += 1
                    hit = True
            if hit:
                self.fired_total[kind] += 1
        if hit:
            from .. import telemetry as _tel

            _tel.count("resilience_faults_injected_total")
        return hit

    # --------------------------------------------------------- live hooks
    def check(self, kind: str, ctx: str = "") -> None:
        """Raise the kind's injected error if a fault is due (raising
        kinds), else return None.  Observation kinds never raise here."""
        if self._consult(kind, ctx) and kind in _RAISES:
            raise _RAISES[kind](ctx or f"call#{self.calls[kind] - 1}")

    def fires(self, kind: str, ctx: str = "") -> bool:
        """Consume one call slot and report whether a fault fires —
        the non-raising twin of ``check`` for observation-level kinds."""
        return self._consult(kind, ctx)

    def set_step(self, step: int) -> None:
        self.step = int(step)


# ----------------------------------------------------------- disarmed hooks
def _noop_check(kind: str, ctx: str = "") -> None:
    return None


def _noop_fires(kind: str, ctx: str = "") -> bool:
    return False


def _noop_set_step(step: int) -> None:
    return None


check = _noop_check
fires = _noop_fires
set_step = _noop_set_step

_INJECTOR: Optional[FaultInjector] = None


def is_armed() -> bool:
    return _INJECTOR is not None


def get_injector() -> Optional[FaultInjector]:
    return _INJECTOR


def arm(faults: List[Fault]) -> FaultInjector:
    """Install a fault schedule and rebind the live hooks.  Re-arming
    replaces the previous schedule (counters restart at zero)."""
    global _INJECTOR, check, fires, set_step
    _INJECTOR = FaultInjector(faults)
    check = _INJECTOR.check
    fires = _INJECTOR.fires
    set_step = _INJECTOR.set_step
    return _INJECTOR


def disarm() -> None:
    """Drop the schedule and restore the no-op hook references."""
    global _INJECTOR, check, fires, set_step
    _INJECTOR = None
    check = _noop_check
    fires = _noop_fires
    set_step = _noop_set_step


def parse_schedule(text: str) -> List[Fault]:
    """Parse the ``VESCALE_FAULTSIM`` grammar (module docstring)."""
    faults: List[Fault] = []
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, argstr = part.partition(":")
        kind = kind.strip()
        kwargs: Dict[str, float] = {}
        if argstr:
            for kv in argstr.split(","):
                k, _, v = kv.partition("=")
                k = k.strip()
                if k not in ("call", "step", "count", "p", "seed", "rank"):
                    raise ValueError(f"faultsim spec {part!r}: unknown key {k!r}")
                kwargs[k] = float(v) if k == "p" else int(v)
        faults.append(
            Fault(
                kind,
                at_call=int(kwargs["call"]) if "call" in kwargs else None,
                at_step=int(kwargs["step"]) if "step" in kwargs else None,
                p=float(kwargs.get("p", 0.0)),
                seed=int(kwargs.get("seed", 0)),
                count=int(kwargs.get("count", 1)),
                rank=int(kwargs["rank"]) if "rank" in kwargs else None,
            )
        )
    return faults


def arm_from_env(var: str = "VESCALE_FAULTSIM") -> Optional[FaultInjector]:
    """Arm from the env schedule if set (scripted runs); None otherwise.
    ``var`` may name any env var (custom harnesses); only registered
    VESCALE_* names route through the registry."""
    from ..analysis import envreg

    if envreg.is_registered(var):
        text = envreg.get_str(var)
    else:
        import os

        text = os.environ.get(var)  # vescale-lint: disable=VSC201 (caller-chosen non-registry name)
    if not text:
        return None
    return arm(parse_schedule(text))
