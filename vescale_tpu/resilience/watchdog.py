"""Hang watchdog — a per-host heartbeat with a step-progress deadline.

At multi-host scale the dominant production failure is not a crash but a
HANG: one rank stuck in a collective (dead peer, wedged DMA, deadlocked
host thread) stalls every healthy rank forever, burning the whole
allocation while producing zero signal (arXiv:1811.02084-scale jobs make
this a daily event).  A crash restarts; a hang needs something on the host
that notices the training loop stopped making progress and turns the
silence into a diagnosable artifact.

``Watchdog`` runs one daemon thread per process.  The training loop calls
``beat(step)`` at every step boundary; if no beat lands within
``timeout_s`` the watchdog fires:

  1. dumps every thread's Python stack (``sys._current_frames``) plus the
     memory flight-recorder bundle (telemetry/memtrack.py) to
     ``watchdog_hang_*.json`` — the forensic record of WHERE each thread
     was stuck;
  2. emits ``resilience_hang_detected_total`` / a ``resilience_hang``
     event line so dashboards see the stall;
  3. optionally aborts the process (``os._exit(exit_code)``) so the
     external supervisor's restart path takes over — the only way out of
     a wedged collective, since no Python-level unwind can cancel it.

Pairs with ``distributed.barrier(timeout_s=...)``: the barrier timeout
diagnoses a dead peer at an explicit sync point; the watchdog catches
everything else (hangs inside compiled steps, storage stalls, deadlocks).

Env knobs (read by ``run_resilient`` when arming from the environment):

  VESCALE_WATCHDOG_TIMEOUT    step-progress deadline in seconds (unset/0:
                              watchdog disarmed)
  VESCALE_WATCHDOG_ABORT      "1" (default): abort the process on hang
  VESCALE_WATCHDOG_EXIT_CODE  process exit code on abort (default 17 —
                              distinguishable from crash/OOM codes so the
                              supervisor can count hangs separately)

Quiescent cost: one ``time.monotonic()`` + two attribute writes per
``beat`` and a sleeping thread — ``VESCALE_BENCH=watchdog`` measures the
armed-but-quiescent per-step overhead end to end (target <<1%).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Watchdog", "dump_all_stacks", "DEFAULT_EXIT_CODE"]

DEFAULT_EXIT_CODE = 17


def dump_all_stacks() -> Dict[str, List[str]]:
    """Every live thread's Python stack, keyed by ``name (tid=...)`` —
    the core of the hang forensic bundle.  Pure-read: safe to call from
    the watchdog thread while the main thread is wedged."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in frames.items():
        label = f"{names.get(ident, 'unknown')} (tid={ident})"
        out[label] = traceback.format_stack(frame)
    return out


class Watchdog:
    """Per-host heartbeat thread with a step-progress deadline.

        wd = Watchdog(timeout_s=300, abort=True).start()
        for step in ...:
            wd.beat(step)
            ...
        wd.stop()

    ``beat`` re-arms the deadline; a beat-free window longer than
    ``timeout_s`` triggers the hang dump (once per stall — a later beat
    re-arms detection).  ``on_hang(bundle)`` runs before any abort, so
    tests and orchestrators can observe the firing without dying."""

    def __init__(
        self,
        timeout_s: float,
        poll_s: Optional[float] = None,
        abort: bool = True,
        exit_code: int = DEFAULT_EXIT_CODE,
        dump_dir: Optional[str] = None,
        on_hang: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        if timeout_s <= 0:
            raise ValueError("watchdog timeout_s must be positive")
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s) if poll_s else min(1.0, self.timeout_s / 4.0)
        self.abort = bool(abort)
        self.exit_code = int(exit_code)
        self.dump_dir = dump_dir
        self.on_hang = on_hang
        self.fired = 0  # stalls detected (tests/bench read this)
        self.last_bundle: Optional[Dict[str, Any]] = None
        self._last_beat = time.monotonic()
        self._step: Optional[int] = None
        self._phase = "startup"
        self._tripped = False  # one dump per stall
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self.beat(self._step, phase="startup")
        self._thread = threading.Thread(
            target=self._run, name="vescale-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.poll_s)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ heartbeat
    def beat(self, step: Optional[int] = None, phase: str = "step") -> None:
        """Record progress: the deadline restarts now.  Cheap enough for
        every step boundary (no locks — monotonic float + attribute
        writes; the watchdog thread reads a slightly-stale view at worst,
        which only ever DELAYS a firing by one poll)."""
        if step is not None:
            self._step = int(step)
        self._phase = phase
        # _last_beat BEFORE _tripped: the reverse order opens a window
        # where the watchdog thread sees the trip latch cleared while the
        # stale timestamp still reads as a stall — a duplicate dump (or
        # abort) for a stall that just ended
        self._last_beat = time.monotonic()
        if self._tripped:
            # progress after a trip: the stall episode is over — resolve
            # the alert (no-op while the engine is dormant)
            from ..telemetry import alerts as _alerts

            _alerts.resolve("watchdog-stall")
        self._tripped = False

    @property
    def stalled_s(self) -> float:
        return time.monotonic() - self._last_beat

    # ------------------------------------------------------------- firing
    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self._tripped:
                continue  # already dumped this stall; wait for a beat
            if self.stalled_s > self.timeout_s:
                self._tripped = True
                self._trigger()

    def _trigger(self) -> None:
        self.fired += 1
        bundle: Dict[str, Any] = {
            "reason": "hang",
            "step": self._step,
            "phase": self._phase,
            "stalled_s": round(self.stalled_s, 3),
            "timeout_s": self.timeout_s,
            "pid": os.getpid(),
            "ts": time.time(),
            "threads": dump_all_stacks(),
        }
        from .. import telemetry as _tel
        from ..telemetry import memtrack as _memtrack

        try:  # the flight recorder rides along when memtrack is live
            mem = _memtrack.dump_now(reason=f"watchdog_hang@step{self._step}")
            if mem is not None:
                bundle["flight_record"] = mem.get("path", "<in-memory>")
        except Exception:
            pass  # diagnostics must never mask the hang handling itself
        path = self._dump_path()
        if path is not None:
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                with open(path, "w") as f:
                    json.dump(bundle, f, indent=2, default=str)
                bundle["path"] = path
            except OSError:
                path = None
        _tel.count("resilience_hang_detected_total")
        _tel.record_event(
            "resilience_hang",
            at_step=self._step,
            phase=self._phase,
            stalled_s=bundle["stalled_s"],
            dump=path,
            abort=self.abort,
        )
        # the stall watcher routes through the alert engine (one lifecycle,
        # /alerts visibility, ALERT timeline span); the stderr print below
        # stays — an aborting process must leave SOMETHING on the console
        from ..telemetry import alerts as _alerts

        _alerts.raise_alert(
            "watchdog-stall",
            message=(
                f"no step progress for {bundle['stalled_s']:.1f}s (deadline "
                f"{self.timeout_s:g}s) at step={self._step} "
                f"phase={self._phase}; stacks -> {path or '<not written>'}"
            ),
            severity="critical",
            value=bundle["stalled_s"],
        )
        print(
            f"[watchdog] no step progress for {bundle['stalled_s']:.1f}s "
            f"(deadline {self.timeout_s:g}s) at step={self._step} "
            f"phase={self._phase}; stacks -> {path or '<not written>'}"
            + ("; aborting" if self.abort else ""),
            file=sys.stderr,
        )
        for label, stack in bundle["threads"].items():
            print(f"[watchdog] --- {label} ---\n{''.join(stack)}", file=sys.stderr)
        self.last_bundle = bundle
        if self.on_hang is not None:
            try:
                self.on_hang(bundle)
            except Exception:
                pass
        if self.abort:
            _tel.count("resilience_hang_aborts_total")
            sys.stderr.flush()
            sys.stdout.flush()
            # os._exit, not sys.exit: the main thread is wedged in a
            # collective no exception can unwind — this is the restart
            # path's entry point, not an error to handle
            os._exit(self.exit_code)

    def _dump_path(self) -> Optional[str]:
        if self.dump_dir is not None:
            root: Optional[str] = self.dump_dir
        else:
            from ..telemetry import api as _api

            st = _api.get_state()
            root = st.out_dir if st is not None else None
            if root is None:
                from ..analysis import envreg

                root = envreg.get_str("VESCALE_WATCHDOG_DIR")
        if root is None:
            return None
        from .faultsim import _process_rank

        # rank-qualified: in a multi-host run every rank's watchdog dumps
        # into the same shared dir and each rank's stacks matter (the hung
        # rank shows WHERE it wedged; the healthy ranks show the collective
        # they were blocked in)
        return os.path.join(
            root, f"watchdog_hang_rank{_process_rank()}_step{self._step}_{self.fired}.json"
        )

    # --------------------------------------------------------- env arming
    @classmethod
    def from_env(
        cls, dump_dir: Optional[str] = None, timeout_s: Optional[float] = None
    ) -> Optional["Watchdog"]:
        """A Watchdog per VESCALE_WATCHDOG_* (module docstring); None when
        the deadline is unset/<=0.  ``timeout_s`` overrides the env
        deadline (an explicit 0 disables even with the env set) while
        abort/exit-code still come from the env — the single parser both
        direct callers and ``run_resilient`` share."""
        from ..analysis import envreg

        if timeout_s is None:
            timeout_s = envreg.get_float("VESCALE_WATCHDOG_TIMEOUT")
        if timeout_s is None or timeout_s <= 0:
            return None
        return cls(
            timeout_s=float(timeout_s),
            abort=envreg.get_bool("VESCALE_WATCHDOG_ABORT"),
            exit_code=envreg.get_int("VESCALE_WATCHDOG_EXIT_CODE"),
            dump_dir=dump_dir,
        )
