"""vescale_tpu — a TPU-native SPMD LLM-training framework with the
capabilities of veScale (volcengine/veScale), built on JAX/XLA/pjit/Pallas.

Everything is exported flat, mirroring the reference's
legacy/vescale/__init__.py:41-76.
"""

__version__ = "0.1.0"

from .placements import (
    Placement,
    Shard,
    Replicate,
    Partial,
    InterleavedShard,
    RaggedShard,
    StridedRaggedShard,
    normalize_placements,
)
from .spec import DArraySpec, TensorMeta
from .mesh import DeviceMesh, init_device_mesh
from .darray import (
    DArray,
    from_local,
    distribute_tensor,
    redistribute_dtensor,
    full_tensor,
    zeros,
    ones,
    empty,
    full,
    randn,
    rand,
    arange,
)
from .redistribute import redistribute, redistribute_local_tensor
from .api import vescale_all_gather, vescale_all_reduce, vescale_reduce_scatter
from .random import manual_seed, get_rng_tracker
from .loss import loss_parallel, vocab_parallel_cross_entropy
from .devicemesh_api import VeDeviceMesh, VESCALE_DEVICE_MESH
from .dmodule import parallelize_module
from .initialize import deferred_init, materialize_dtensor, materialize_dparameter
from . import collectives

# heavier subsystems are plain submodules:
#   vescale_tpu.parallel    (DDP / DistributedOptimizer / FSDP)
#   vescale_tpu.pipe        (pipeline parallel)  + vescale_tpu.plan
#   vescale_tpu.moe         (expert parallel)
#   vescale_tpu.checkpoint  (distributed save/load + reshard)
#   vescale_tpu.resilience  (fault injection / retry / preemption / recovery loop)
#   vescale_tpu.ndtimeline  (profiler)
#   vescale_tpu.telemetry   (metrics registry / step reports / exporters)
#   vescale_tpu.emulator    (bitwise collective replay)
#   vescale_tpu.debug       (CommDebugMode / DebugLogger)
#   vescale_tpu.dmp         (auto-plan)
#   vescale_tpu.models      (nanoGPT / llama / mixtral)

# DTensor-compatible aliases for migration from the reference API
DTensor = DArray
DTensorSpec = DArraySpec
