"""vescale_tpu — a TPU-native SPMD LLM-training framework with the
capabilities of veScale (volcengine/veScale), built on JAX/XLA/pjit/Pallas.

Everything is exported flat, mirroring the reference's
legacy/vescale/__init__.py:41-76.
"""

__version__ = "0.1.0"

from .placements import (
    Placement,
    Shard,
    Replicate,
    Partial,
    InterleavedShard,
    RaggedShard,
    StridedRaggedShard,
    normalize_placements,
)
from .spec import DArraySpec, TensorMeta
from .mesh import DeviceMesh, init_device_mesh
from .darray import (
    DArray,
    from_local,
    distribute_tensor,
    redistribute_dtensor,
    full_tensor,
    zeros,
    ones,
    empty,
    full,
    randn,
    rand,
    arange,
)
from .redistribute import redistribute, redistribute_local_tensor
from .api import vescale_all_gather, vescale_all_reduce, vescale_reduce_scatter
from .random import manual_seed, get_rng_tracker
from . import collectives

# DTensor-compatible aliases for migration from the reference API
DTensor = DArray
DTensorSpec = DArraySpec
