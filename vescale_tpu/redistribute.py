"""Redistribute engine — placement transitions as XLA collectives.

Reference: legacy/vescale/dtensor/redistribute.py:223 implements a per-pair
transition table (allgather / reduce-scatter / all-reduce / all-to-all /
scatter, with pad/unpad for uneven shards) issuing NCCL ops eagerly.

TPU-native design: a transition is ``unpack -> reduce partials -> pack`` in
the logical domain with the *destination* sharding attached.  Under ``jit``
XLA compiles exactly the collectives of the reference's table:

  Partial -> Replicate    == psum (all-reduce)
  Partial -> Shard(d)     == psum_scatter (reduce-scatter)
  Shard(d) -> Replicate   == all-gather (+ implicit unpad for uneven)
  Shard(d) -> Shard(d')   == all-to-all
  Replicate -> Shard(d)   == local slice (no comm)
  RaggedShard -> Replicate== all-gather-v  (gather + unpad, placement_types.py:128)
  RaggedShard -> RaggedShard' == all-to-all-v (placement_types.py:152)

Eagerly, ``jax.device_put`` between shardings performs the device-to-device
resharding transfer.  Cross-mesh redistribution (reference
CrossMeshRedistribute, redistribute.py:562) round-trips through the logical
value as well.
"""

from __future__ import annotations

from typing import Optional

import jax

from .darray import DArray, _apply_sharding, _is_traced
from .mesh import DeviceMesh
from .placements import normalize_placements
from .spec import DArraySpec, TensorMeta

__all__ = ["redistribute", "redistribute_local_tensor", "classify_transition"]


def redistribute(darr: DArray, placements, mesh: Optional[DeviceMesh] = None) -> DArray:
    dst_mesh = mesh or darr.mesh
    dst_placements = normalize_placements(placements, dst_mesh.ndim, darr.ndim)
    src = darr.spec
    dst = DArraySpec(dst_mesh, dst_placements, TensorMeta(src.shape, src.dtype))
    if dst == src:
        return darr

    # Gated LOSSY int8 overlay (VESCALE_REDISTRIBUTE_QUANT, default off):
    # before the exact tiers, let a quantize->move->dequantize hop compete
    # on the planner's cost model — taken only where packed int8 payloads
    # beat the unquantized wire pattern; otherwise a structured VSC127
    # decline is recorded (redistribute_plan.quant_decline_finding), never
    # a silent fallback.  Multi-hop composite pairs get the same edge via
    # the planner's lattice search below.
    if dst_mesh == darr.mesh:
        from .analysis import envreg

        if envreg.get_bool("VESCALE_REDISTRIBUTE_QUANT"):
            from .redistribute_plan import quant_single_hop_plan

            qplan = quant_single_hop_plan(src, dst)
            if qplan is not None:
                return DArray(qplan.execute(darr.data), dst)

    # Fast path: same mesh, no partial/ragged/interleave on either side —
    # the physical array is the logical array; let XLA/jax reshard directly
    # without a pack/unpack round-trip.
    trivial = (
        dst_mesh == darr.mesh
        and not src.has_partial()
        and not dst.has_partial()
        and not src.has_ragged()
        and not dst.has_ragged()
        and not src.layout().interleaves
        and not dst.layout().interleaves
        and not src.layout().any_padded
        and not dst.layout().any_padded
    )
    if trivial:
        return DArray(_apply_sharding(darr.data, dst), dst)

    # Per-shard transition kernels (transfer.py): each rank touches only its
    # shard; the collective is the exact reference-table op (all-gather /
    # reduce-scatter / all-to-all / all-gather-v / all-to-all-v /
    # interleaved piece-exchange) — no logical-size allocation.
    from .transfer import (
        fallback_fn,
        interleaved_transition_fn,
        ragged_transition_fn,
        transition_fn,
    )

    fn = transition_fn(src, dst)
    if fn is None and (src.has_ragged() or dst.has_ragged()):
        fn = ragged_transition_fn(src, dst)
    if fn is None and (src.layout().interleaves or dst.layout().interleaves):
        fn = interleaved_transition_fn(src, dst)
    if fn is not None:
        return DArray(fn(darr.data), dst)

    # composite transition with no single-hop kernel: the multi-hop planner
    # (redistribute_plan.py) searches for a short sequence of per-shard hops
    # — axis-swap cycles, Partial/reshard combinations, multi-dim interleave
    # changes, cross-mesh bridges — whose intermediates stay within a small
    # multiple of the shard size.  Plans are memoized with their jitted hop
    # fns: repeated boundary transitions re-plan and retrace nothing.
    from .redistribute_plan import plan_redistribute

    plan = plan_redistribute(src, dst)
    if plan is not None:
        return DArray(plan.execute(darr.data), dst)

    # cross-mesh without logical materialization: strip each side to a
    # plain physical==logical form with SAME-mesh per-shard kernels, then
    # let the runtime reshard device-to-device (jax.device_put between
    # shardings copies shards, reference CrossMeshRedistribute
    # redistribute.py:562 — which round-trips through the logical value;
    # this path never does)
    if dst_mesh != darr.mesh:
        out = _cross_mesh_per_shard(darr, src, dst)
        if out is not None:
            return out

    # fallback (nested+padded shards, out-of-budget ragged moves, exotic
    # cross-mesh): pack∘unpack, jit-compiled with the destination sharding
    # where possible.  The logical value may materialize: surface that
    # loudly — including WHY the planner declined — and hard-fail under
    # VESCALE_STRICT_REDISTRIBUTE=1.
    _warn_fallback(src, dst)
    phys = fallback_fn(src, dst)(darr.data)
    return DArray(_apply_sharding(phys, dst), dst)


def classify_transition(src: DArraySpec, dst: DArraySpec) -> str:
    """Which tier of ``redistribute()``'s dispatch serves src -> dst,
    WITHOUT executing it: ``trivial`` (respec) | ``kernel`` (single-hop
    per-shard) | ``planned`` (multi-hop planner) | ``cross_mesh_plain``
    (strip / device_put / dress) | ``fallback`` (pack∘unpack, may
    materialize).  Kept NEXT to the dispatch above so the two cannot
    drift — scripts/redistribute_bench.py reports this label per pair."""
    from .redistribute_plan import plan_redistribute
    from .transfer import (
        interleaved_transition_fn,
        ragged_transition_fn,
        transition_fn,
    )

    def plain(s: DArraySpec) -> bool:
        return not (
            s.has_partial() or s.has_ragged() or s.layout().interleaves or s.layout().any_padded
        )

    if dst == src or (src.mesh == dst.mesh and plain(src) and plain(dst)):
        return "trivial"
    if transition_fn(src, dst) is not None:
        return "kernel"
    if (src.has_ragged() or dst.has_ragged()) and ragged_transition_fn(src, dst) is not None:
        return "kernel"
    if (src.layout().interleaves or dst.layout().interleaves) and (
        interleaved_transition_fn(src, dst) is not None
    ):
        return "kernel"
    if plan_redistribute(src, dst) is not None:
        return "planned"
    if src.mesh != dst.mesh:
        sp, dp = _plain_placements(src), _plain_placements(dst)
        if sp is not None and dp is not None:
            mid = DArraySpec(src.mesh, sp, src.meta)
            dmid = DArraySpec(dst.mesh, dp, dst.meta)
            if all(
                not (s.layout().any_padded or s.layout().interleaves or s.has_partial())
                for s in (mid, dmid)
            ):
                return "cross_mesh_plain"
    return "fallback"


def _plain_placements(spec: DArraySpec):
    """Same-mesh placements with physical==logical semantics: interleaves
    become plain shards, partials reduce to Replicate.  None when the spec
    is out of scope (ragged) or the plain form still pads."""
    from .placements import InterleavedShard, Replicate as R, Shard as S

    if spec.has_ragged():
        return None
    out = []
    for p in spec.placements:
        if isinstance(p, InterleavedShard):
            out.append(S(p.dim))
        elif p.is_partial():
            out.append(R())
        else:
            out.append(p)
    return tuple(out)


def _cross_mesh_per_shard(darr: DArray, src: DArraySpec, dst: DArraySpec) -> Optional[DArray]:
    src_plain = _plain_placements(src)
    dst_plain = _plain_placements(dst)
    if src_plain is None or dst_plain is None:
        return None
    mid_spec = DArraySpec(src.mesh, src_plain, src.meta)
    dst_mid_spec = DArraySpec(dst.mesh, dst_plain, dst.meta)
    # both plain forms must BE the logical array shard-wise (no padding/
    # interleave left), or device_put would move a padded physical layout
    # into a differently-padded one
    for s in (mid_spec, dst_mid_spec):
        if s.layout().any_padded or s.layout().interleaves or s.has_partial():
            return None
    mid = darr if mid_spec == src else redistribute(darr, src_plain)
    data = jax.device_put(mid.data, dst_mid_spec.named_sharding())
    out = DArray(data, dst_mid_spec)
    return out if dst_mid_spec == dst else redistribute(out, dst.placements)


_warned_pairs = set()


def _warn_fallback(src: DArraySpec, dst: DArraySpec) -> None:
    import warnings

    from . import telemetry as _tel
    from .analysis import envreg
    from .debug import DebugLogger
    from .redistribute_plan import decline_reason

    _tel.count("redistribute.fallbacks")
    itemsize = jax.numpy.dtype(src.dtype).itemsize
    logical = itemsize
    for s in src.shape:
        logical *= s
    shard = max(
        logical // max(1, src.mesh.size()), logical // max(1, dst.mesh.size())
    )
    msg = (
        f"redistribute fallback for {src.placements} -> {dst.placements} "
        f"(mesh {src.mesh.mesh_dim_names}{'->' + str(dst.mesh.mesh_dim_names) if dst.mesh != src.mesh else ''}) "
        f"may materialize the LOGICAL tensor: ~{logical / 2**20:.1f} MiB vs "
        f"~{shard / 2**20:.1f} MiB per-shard; multi-hop planner declined: "
        f"{decline_reason(src, dst)}"
    )
    if envreg.get_bool("VESCALE_STRICT_REDISTRIBUTE"):
        raise RuntimeError(msg + " (VESCALE_STRICT_REDISTRIBUTE=1)")
    key = (src, dst)
    if key not in _warned_pairs:
        _warned_pairs.add(key)
        from .telemetry import alerts as _alerts

        if _alerts.is_active():
            # live engine: one lifecycle-managed alert (refreshed per new
            # pair — the message names the pair), /alerts visibility
            _alerts.raise_alert(
                "redistribute-fallback", message=msg, severity="warning"
            )
        else:
            # dormant-engine legacy fallback, deduped by _warned_pairs
            warnings.warn(msg, stacklevel=3)  # vescale-lint: disable=VSC207
    DebugLogger.log("redistribute", msg)


def redistribute_local_tensor(locals_, src_spec: DArraySpec, dst_spec: DArraySpec, rank: int = 0):
    """Transition local tensors between specs (reference redistribute.py:223)
    and return ``rank``'s destination local.  Single-controller semantics:
    ``locals_`` must be the full per-rank list (flat-rank order), or a single
    tensor only when the source is fully replicated — any other transition
    would require the other ranks' data and cannot be fabricated."""
    from .darray import from_local

    if not isinstance(locals_, (list, tuple)):
        if not src_spec.is_replicated():
            raise ValueError(
                "single-local redistribute is only defined for a replicated "
                "source; pass the full per-rank list of locals"
            )
        locals_ = [locals_] * src_spec.mesh.size()
    d = from_local(list(locals_), src_spec.mesh, src_spec.placements, shape=src_spec.shape)
    return redistribute(d, dst_spec.placements, mesh=dst_spec.mesh).to_local(rank=rank)
