"""Redistribute engine — placement transitions as XLA collectives.

Reference: legacy/vescale/dtensor/redistribute.py:223 implements a per-pair
transition table (allgather / reduce-scatter / all-reduce / all-to-all /
scatter, with pad/unpad for uneven shards) issuing NCCL ops eagerly.

TPU-native design: a transition is ``unpack -> reduce partials -> pack`` in
the logical domain with the *destination* sharding attached.  Under ``jit``
XLA compiles exactly the collectives of the reference's table:

  Partial -> Replicate    == psum (all-reduce)
  Partial -> Shard(d)     == psum_scatter (reduce-scatter)
  Shard(d) -> Replicate   == all-gather (+ implicit unpad for uneven)
  Shard(d) -> Shard(d')   == all-to-all
  Replicate -> Shard(d)   == local slice (no comm)
  RaggedShard -> Replicate== all-gather-v  (gather + unpad, placement_types.py:128)
  RaggedShard -> RaggedShard' == all-to-all-v (placement_types.py:152)

Eagerly, ``jax.device_put`` between shardings performs the device-to-device
resharding transfer.  Cross-mesh redistribution (reference
CrossMeshRedistribute, redistribute.py:562) round-trips through the logical
value as well.
"""

from __future__ import annotations

from typing import Optional

import jax

from .darray import DArray, _apply_sharding, _is_traced
from .mesh import DeviceMesh
from .placements import normalize_placements
from .spec import DArraySpec, TensorMeta

__all__ = ["redistribute", "redistribute_local_tensor"]


def redistribute(darr: DArray, placements, mesh: Optional[DeviceMesh] = None) -> DArray:
    dst_mesh = mesh or darr.mesh
    dst_placements = normalize_placements(placements, dst_mesh.ndim, darr.ndim)
    src = darr.spec
    dst = DArraySpec(dst_mesh, dst_placements, TensorMeta(src.shape, src.dtype))
    if dst == src:
        return darr

    # Fast path: same mesh, no partial/ragged/interleave on either side —
    # the physical array is the logical array; let XLA/jax reshard directly
    # without a pack/unpack round-trip.
    trivial = (
        dst_mesh == darr.mesh
        and not src.has_partial()
        and not dst.has_partial()
        and not src.has_ragged()
        and not dst.has_ragged()
        and not src.layout().interleaves
        and not dst.layout().interleaves
        and not src.layout().any_padded
        and not dst.layout().any_padded
    )
    if trivial:
        return DArray(_apply_sharding(darr.data, dst), dst)

    # Per-shard transition kernels (transfer.py): each rank touches only its
    # shard; the collective is the exact reference-table op (all-gather /
    # reduce-scatter / all-to-all / all-gather-v / all-to-all-v) — no
    # logical-size allocation.
    from .transfer import fallback_fn, ragged_transition_fn, transition_fn

    fn = transition_fn(src, dst)
    if fn is None and (src.has_ragged() or dst.has_ragged()):
        fn = ragged_transition_fn(src, dst)
    if fn is not None:
        return DArray(fn(darr.data), dst)

    # fallback (ragged / interleaved / nested / cross-mesh): pack∘unpack,
    # jit-compiled with the destination sharding where possible
    phys = fallback_fn(src, dst)(darr.data)
    return DArray(_apply_sharding(phys, dst), dst)


def redistribute_local_tensor(locals_, src_spec: DArraySpec, dst_spec: DArraySpec, rank: int = 0):
    """Transition local tensors between specs (reference redistribute.py:223)
    and return ``rank``'s destination local.  Single-controller semantics:
    ``locals_`` must be the full per-rank list (flat-rank order), or a single
    tensor only when the source is fully replicated — any other transition
    would require the other ranks' data and cannot be fabricated."""
    from .darray import from_local

    if not isinstance(locals_, (list, tuple)):
        if not src_spec.is_replicated():
            raise ValueError(
                "single-local redistribute is only defined for a replicated "
                "source; pass the full per-rank list of locals"
            )
        locals_ = [locals_] * src_spec.mesh.size()
    d = from_local(list(locals_), src_spec.mesh, src_spec.placements, shape=src_spec.shape)
    return redistribute(d, dst_spec.placements, mesh=dst_spec.mesh).to_local(rank=rank)
