"""DeviceMesh — the n-D logical device grid over TPU ICI/DCN.

Capability parity with the reference DeviceMesh / _MeshEnv / init_device_mesh
(legacy/vescale/dtensor/device_mesh.py:44,168,599), re-designed TPU-native:
a thin, functional wrapper around ``jax.sharding.Mesh``.  The reference builds
NCCL process groups per mesh dim; here mesh dims are named axes and every
collective is an XLA op over those axes — no groups to manage.

Also provides the "fake" mesh used throughout the test-suite: with
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` a single CPU process
exposes N devices, mirroring the reference's fake/meta-pg test strategy
(legacy/test/common_dtensor.py).
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple, Union

import numpy as np
import jax
from jax.sharding import Mesh as JaxMesh
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["DeviceMesh", "init_device_mesh"]


class _MeshEnv(threading.local):
    """Tracks the current-mesh stack (for `with mesh:` scoping) and caches
    submeshes, mirroring reference _MeshEnv (device_mesh.py:44)."""

    def __init__(self) -> None:
        self.mesh_stack: list = []

    def get_current_mesh(self) -> "DeviceMesh":
        if not self.mesh_stack:
            raise RuntimeError("No device mesh is currently active")
        return self.mesh_stack[-1]


_mesh_env = _MeshEnv()


class DeviceMesh:
    """An n-D array of devices with named dims.

    ``DeviceMesh(("dp","tp"), (4, 2))`` lays the first 8 local devices out in
    a 4x2 grid.  Dim names are the axis names used by every sharding and
    collective in the framework.
    """

    def __init__(
        self,
        mesh_dim_names: Sequence[str],
        mesh_shape: Optional[Sequence[int]] = None,
        *,
        devices: Optional[Sequence] = None,
        _jax_mesh: Optional[JaxMesh] = None,
    ) -> None:
        if _jax_mesh is not None:
            self._mesh = _jax_mesh
        else:
            mesh_dim_names = tuple(mesh_dim_names)
            if devices is None:
                n = int(np.prod(mesh_shape)) if mesh_shape is not None else len(jax.devices())
                devices = jax.devices()[:n]
            if mesh_shape is None:
                if len(mesh_dim_names) != 1:
                    raise ValueError("mesh_shape required for >1-D meshes")
                mesh_shape = (len(devices),)
            if int(np.prod(mesh_shape)) != len(devices):
                raise ValueError(f"mesh_shape {tuple(mesh_shape)} does not match {len(devices)} devices")
            if len(mesh_dim_names) != len(mesh_shape):
                raise ValueError("mesh_dim_names / mesh_shape length mismatch")
            dev_array = np.asarray(devices, dtype=object).reshape(tuple(mesh_shape))
            self._mesh = JaxMesh(dev_array, axis_names=mesh_dim_names)

    # ------------------------------------------------------------------ info
    @property
    def jax_mesh(self) -> JaxMesh:
        return self._mesh

    @property
    def mesh_dim_names(self) -> Tuple[str, ...]:
        return tuple(self._mesh.axis_names)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._mesh.devices.shape)

    @property
    def ndim(self) -> int:
        return self._mesh.devices.ndim

    @property
    def device_type(self) -> str:
        return self._mesh.devices.flat[0].platform

    def size(self, mesh_dim: Optional[Union[int, str]] = None) -> int:
        if mesh_dim is None:
            return int(self._mesh.devices.size)
        return self.shape[self._dim_index(mesh_dim)]

    def _dim_index(self, mesh_dim: Union[int, str]) -> int:
        if isinstance(mesh_dim, str):
            return self.mesh_dim_names.index(mesh_dim)
        return mesh_dim

    def dim_name(self, mesh_dim: Union[int, str]) -> str:
        return self.mesh_dim_names[self._dim_index(mesh_dim)]

    @property
    def devices(self) -> np.ndarray:
        return self._mesh.devices

    def get_rank(self, device=None) -> int:
        """Flat index of ``device`` (default: first addressable device) in
        the mesh — the analog of the reference's global rank."""
        device = device if device is not None else self._mesh.devices.flat[0]
        flat = list(self._mesh.devices.flat)
        return flat.index(device)

    def get_coordinate(self, device=None) -> Tuple[int, ...]:
        """n-D coordinate of ``device`` in the mesh
        (reference DeviceMesh.get_coordinate, device_mesh.py:168)."""
        device = device if device is not None else self._mesh.devices.flat[0]
        pos = np.argwhere(self._mesh.devices == device)
        if pos.size == 0:
            raise ValueError(f"{device} is not in this mesh")
        return tuple(int(x) for x in pos[0])

    def coordinate_of_rank(self, rank: int) -> Tuple[int, ...]:
        return tuple(int(x) for x in np.unravel_index(rank, self.shape))

    # ------------------------------------------------------ submesh slicing
    def __getitem__(self, mesh_dims: Union[str, Sequence[str]]) -> "DeviceMesh":
        """Slice out the submesh spanning the given dims, holding the other
        coordinates fixed at this process's first device (reference
        DeviceMesh.__getitem__ / _MeshEnv submesh creation)."""
        if isinstance(mesh_dims, str):
            mesh_dims = (mesh_dims,)
        keep = [self._dim_index(d) for d in mesh_dims]
        coord = self.get_coordinate()
        index = tuple(
            slice(None) if i in keep else coord[i] for i in range(self.ndim)
        )
        sub_devices = self._mesh.devices[index]
        # reorder axes to requested order
        order = [sorted(keep).index(k) for k in keep]
        sub_devices = np.transpose(sub_devices, order)
        return DeviceMesh(
            tuple(mesh_dims),
            _jax_mesh=JaxMesh(sub_devices, axis_names=tuple(self.dim_name(d) for d in mesh_dims)),
        )

    # ----------------------------------------------------------- shardings
    def sharding(self, pspec: PartitionSpec) -> NamedSharding:
        return NamedSharding(self._mesh, pspec)

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self._mesh, PartitionSpec())

    # ----------------------------------------------------------- ctx mgr
    def __enter__(self) -> "DeviceMesh":
        _mesh_env.mesh_stack.append(self)
        self._ctx = self._mesh.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        _mesh_env.mesh_stack.pop()
        self._mesh.__exit__(*exc)

    def __eq__(self, other) -> bool:
        return isinstance(other, DeviceMesh) and self._mesh == other._mesh

    def __hash__(self) -> int:
        return hash(self._mesh)

    def __repr__(self) -> str:
        return f"DeviceMesh(dims={dict(zip(self.mesh_dim_names, self.shape))}, devices={self.device_type})"


def init_device_mesh(
    device_type: Optional[str] = None,
    mesh_shape: Sequence[int] = (),
    *,
    mesh_dim_names: Optional[Sequence[str]] = None,
) -> DeviceMesh:
    """Create a DeviceMesh from the process's visible devices
    (reference init_device_mesh, device_mesh.py:599).

    ``device_type`` is advisory on TPU (kept for API parity); devices come
    from ``jax.devices()``.
    """
    if mesh_dim_names is None:
        mesh_dim_names = tuple(f"dim{i}" for i in range(len(mesh_shape)))
    devices = jax.devices(device_type) if device_type not in (None, "cuda", "cpu", "tpu") else jax.devices()
    n = int(np.prod(mesh_shape))
    if n > len(devices):
        raise ValueError(f"mesh_shape {tuple(mesh_shape)} needs {n} devices, have {len(devices)}")
    return DeviceMesh(mesh_dim_names, mesh_shape, devices=devices[:n])
