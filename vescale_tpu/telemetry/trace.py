"""Unified distributed trace timeline — one step, every rank, one file.

ndtimeline gives each rank a host-span stream (timer.py) and the streamer a
live merge (streamer.py), but there has been no way to LOOK at a step across
ranks on a single timeline: per-rank span dumps carry raw host clocks (which
skew by milliseconds across hosts — longer than many of the spans), the
chrome-trace handler wrote one rank's view, and nothing extracted where the
step's time actually went.

This module closes that loop:

  * :func:`estimate_clock_offsets` — cross-rank clock-offset estimation
    over the resilience layer's ``allgather_ints`` control plane: K rounds
    of wall-clock exchange, per-rank offsets relative to rank 0 with a
    reported residual bound (the spread across rounds).  Feed the result to
    ``NDTimerManager.calibrate`` (record-time alignment) or to
    :func:`merge_traces` (merge-time alignment).
  * :func:`merge_traces` — merge per-rank span streams into one skew-
    corrected stream, ready for :func:`write_perfetto` (the upgraded
    ``ChromeTraceHandler``: pid/tid metadata from ``world_info`` rank
    coordinates, flow events between tagged send/recv pairs).
  * :func:`critical_path` — per-step critical-path extraction: the
    backward-chained sequence of spans covering the step's makespan, with
    the coverage fraction (1 - coverage = time no recorded span explains).
  * :func:`bubble_fraction` — pipeline bubble fraction from the
    PipeEngine's per-instruction spans: 1 - mean per-stage busy fraction
    over the step window.
  * :func:`record_trace_metrics` — feeds the ``trace:`` and
    ``critical-path:`` dashboard blocks (exporters.py) from a merge.
  * :func:`step_span_summary` — the per-step span rollup telemetry's
    ``record_step`` embeds in ``steps.jsonl``.
"""

from __future__ import annotations

import dataclasses
import json
import statistics
import time
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Union

from ..ndtimeline import predefined as _predefined
from ..ndtimeline.timer import Span

__all__ = [
    "ClockSync",
    "estimate_clock_offsets",
    "merge_traces",
    "stream_process_names",
    "write_perfetto",
    "load_perfetto",
    "spans_from_perfetto",
    "critical_path",
    "critical_paths_by_step",
    "bubble_fraction",
    "step_span_summary",
    "record_trace_metrics",
    "PIPE_METRICS",
]


# the PipeEngine instruction spans the bubble-fraction computation reads
PIPE_METRICS = frozenset(
    (
        _predefined.FORWARD_COMPUTE,
        _predefined.BACKWARD_COMPUTE,
        _predefined.WGRAD_COMPUTE,
    )
)


# ------------------------------------------------------------- clock sync
@dataclasses.dataclass
class ClockSync:
    """Per-rank host-clock offsets relative to rank 0 (microseconds,
    ``offset_us[p]`` = rank p's clock minus rank 0's), plus the residual
    bound: half the worst cross-round spread — aligned timestamps from two
    ranks are comparable only down to this granularity."""

    offsets_us: List[float]
    residual_us: float
    rounds: int

    def offset_s(self, rank: int) -> float:
        if 0 <= rank < len(self.offsets_us):
            return self.offsets_us[rank] / 1e6
        return 0.0

    def as_dict(self) -> Dict:
        return {
            "offsets_us": list(self.offsets_us),
            "residual_us": self.residual_us,
            "rounds": self.rounds,
        }

    @classmethod
    def from_dict(cls, d: Mapping) -> "ClockSync":
        return cls(
            offsets_us=[float(x) for x in d["offsets_us"]],
            residual_us=float(d["residual_us"]),
            rounds=int(d.get("rounds", 0)),
        )


def estimate_clock_offsets(
    rounds: Optional[int] = None, tag: str = "vescale_clock_sync"
) -> ClockSync:
    """Estimate per-rank clock offsets over ``allgather_ints`` (the PR-5
    control plane): each round, every rank samples its wall clock
    immediately before entering the gather; rank p's offset is the
    cross-round MEDIAN of ``t_p - t_0`` (the median discards rounds where a
    straggling entry skewed the exchange).  Single-process: all zeros.

    Every rank computes the full offset vector (the gather is symmetric),
    so any rank can merge any rank's spans.  Accuracy is bounded by the
    gather's own duration — ``residual_us`` reports the observed bound so
    downstream skew claims stay honest."""
    from ..analysis import envreg
    from ..distributed import allgather_ints

    if rounds is None:
        rounds = envreg.get_int("VESCALE_CLOCK_SYNC_ROUNDS") or 8
    rounds = max(1, int(rounds))
    samples: List[List[int]] = []
    for _ in range(rounds):
        now_us = int(time.time() * 1e6)
        rows = allgather_ints([now_us], tag=tag)
        samples.append([int(r[0]) for r in rows])
    world = len(samples[0])
    offsets: List[float] = []
    residual = 0.0
    for p in range(world):
        deltas = [row[p] - row[0] for row in samples]
        offsets.append(float(statistics.median(deltas)))
        if len(deltas) > 1:
            residual = max(residual, (max(deltas) - min(deltas)) / 2.0)
    return ClockSync(offsets_us=offsets, residual_us=residual, rounds=rounds)


# ---------------------------------------------------------------- merging
def _offset_fn(clock) -> "callable":
    if clock is None:
        return lambda key: 0.0
    if hasattr(clock, "offset_s"):  # ClockSync (int ranks) or a
        return clock.offset_s  # fleet-style sync keyed by stream id
    if isinstance(clock, Mapping):
        return lambda key: float(clock.get(key, 0.0))
    raise TypeError(f"clock must be ClockSync, mapping or None, got {type(clock)}")


def _is_replica_qualified(span_streams) -> bool:
    return (
        isinstance(span_streams, Mapping)
        and bool(span_streams)
        and any(not isinstance(k, int) for k in span_streams)
    )


def merge_traces(
    span_streams: Union[Sequence[Span], Mapping[int, Sequence[Span]], Mapping[str, Sequence[Span]]],
    clock=None,
) -> List[Span]:
    """Merge per-rank span streams into ONE stream on rank 0's clock.

    ``span_streams``: either a flat span iterable (ranks read from each
    span) or ``{rank: spans}`` (the mapping's rank wins — the shape you get
    from per-rank ``parse_raw_spans`` files).  ``clock``: a
    :class:`ClockSync` or ``{rank: offset_seconds}``; each span's start is
    shifted by ``-offset(rank)``.  Returns NEW spans sorted by aligned
    start (inputs are never mutated).

    **Replica-qualified stream identities** (fleet mode): the mapping keys
    may be STRINGS (``"router"``, ``"r0"``, ``"r1"``, …) — the shape a
    multi-replica fleet produces, where two replicas' rank-0 spans would
    otherwise collide on one pid lane.  Each stream is then assigned its
    own pid lane (sorted-key order, deterministic), every span gains a
    ``stream`` tag naming its origin, and the clock offsets are looked up
    by the SAME key (a ``{key: offset_seconds}`` mapping or anything with
    an ``offset_s(key)`` method, e.g. ``fleettrace.FleetClockSync``).
    :func:`stream_process_names` yields the matching
    ``write_perfetto(process_names=...)`` labels."""
    off = _offset_fn(clock)
    out: List[Span] = []
    if _is_replica_qualified(span_streams):
        keys = sorted(span_streams, key=str)
        pid_of = {k: i for i, k in enumerate(keys)}
        for k in keys:
            for s in span_streams[k]:
                tags = dict(s.tags) if s.tags else {}
                tags.setdefault("stream", str(k))
                out.append(
                    Span(
                        metric=s.metric,
                        start=s.start - off(k),
                        duration=s.duration,
                        step=s.step,
                        rank=pid_of[k],
                        tags=tags,
                    )
                )
        out.sort(key=lambda s: (s.start, s.rank, s.metric))
        return out
    if isinstance(span_streams, Mapping):
        items: Iterable = (
            (rank, s) for rank, spans in span_streams.items() for s in spans
        )
    else:
        items = ((s.rank, s) for s in span_streams)
    for rank, s in items:
        out.append(
            Span(
                metric=s.metric,
                start=s.start - off(rank),
                duration=s.duration,
                step=s.step,
                rank=int(rank),
                tags=dict(s.tags) if s.tags else None,
            )
        )
    out.sort(key=lambda s: (s.start, s.rank, s.metric))
    return out


def stream_process_names(span_streams: Mapping) -> Dict[int, str]:
    """The ``write_perfetto(process_names=...)`` labels matching
    :func:`merge_traces`' pid assignment: replica-qualified (string-keyed)
    streams map sorted-key order onto pids 0..n-1; int-keyed streams keep
    rank == pid."""
    if not isinstance(span_streams, Mapping):
        return {}
    keys = sorted(span_streams, key=str)
    if _is_replica_qualified(span_streams):
        return {i: str(k) for i, k in enumerate(keys)}
    return {int(k): f"rank {k}" for k in keys}


def write_perfetto(
    spans: Sequence[Span],
    path: str,
    process_names: Optional[Mapping[int, str]] = None,
    world_infos: Optional[Mapping[int, object]] = None,
) -> str:
    """Write a merged span stream as one Perfetto/Chrome trace.  Rank ->
    pid; ``world_infos`` (``{rank: WorldInfo}``) names each process lane
    with its nD coordinate (``rank 1 [dp=1 tp=0 pp=0]``) so the timeline
    reads in topology terms, not bare integers."""
    from ..ndtimeline.handlers import ChromeTraceHandler

    names = dict(process_names or {})
    for rank, wi in (world_infos or {}).items():
        names.setdefault(
            int(rank),
            f"rank {rank} [dp={getattr(wi, 'dp_rank', 0)} "
            f"tp={getattr(wi, 'tp_rank', 0)} pp={getattr(wi, 'pp_rank', 0)}]",
        )
    handler = ChromeTraceHandler(path, process_names=names)
    handler(list(spans))
    return handler.write()


def load_perfetto(path: str) -> Dict:
    """Load a trace written by :func:`write_perfetto` /
    ``ChromeTraceHandler.write`` back into its JSON dict (the round-trip
    surface the handler tests assert on)."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "traceEvents" not in data:
        raise ValueError(f"{path}: not a chrome-trace JSON (no traceEvents)")
    return data


def spans_from_perfetto(path: str) -> List[Span]:
    """Reconstruct :class:`Span` objects from a written trace's duration
    ('X') events — the load half of the round-trip test."""
    out = []
    for ev in load_perfetto(path)["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args") or {})
        step = int(args.pop("step", 0))
        out.append(
            Span(
                metric=ev["name"],
                start=ev["ts"] / 1e6,
                duration=ev.get("dur", 0) / 1e6,
                step=step,
                rank=int(ev.get("pid", 0)),
                tags=args or None,
            )
        )
    out.sort(key=lambda s: (s.start, s.rank, s.metric))
    return out


# ---------------------------------------------------------- critical path
def critical_path(spans: Sequence[Span]) -> Dict:
    """Backward-chained critical path through a (merged, aligned) span set:
    start from the latest-ending span, repeatedly hop to the latest-ending
    span that finishes before the current one starts.  The chain is the
    sequence of host regions that bound the makespan; ``coverage`` is the
    fraction of the chained window the spans themselves explain (the rest
    is time no recorded span accounts for — device work, scheduling gaps,
    or genuinely idle bubble).

    Returns ``{spans, total_ms, window_ms, coverage, n_spans}`` (empty
    input -> zeros)."""
    spans = [s for s in spans if s.duration >= 0]
    if not spans:
        return {"spans": [], "total_ms": 0.0, "window_ms": 0.0, "coverage": 0.0, "n_spans": 0}
    by_end = sorted(spans, key=lambda s: s.start + s.duration)
    cur = by_end[-1]
    chain = [cur]
    # walk the end-sorted list backwards: the first span ending at or
    # before cur.start is the latest such span (the binding predecessor).
    # i strictly decreases across chain links so the walk terminates even
    # on zero-duration spans (a span that "ends at or before" its own
    # start must never become its own predecessor)
    i = len(by_end) - 1
    while True:
        pred = None
        while i >= 0:
            cand = by_end[i]
            if cand is not cur and cand.start + cand.duration <= cur.start:
                pred = cand
                break
            i -= 1
        if pred is None:
            break
        chain.append(pred)
        cur = pred
        i -= 1
    chain.reverse()
    total = sum(s.duration for s in chain)
    window = (by_end[-1].start + by_end[-1].duration) - chain[0].start
    return {
        "spans": chain,
        "total_ms": total * 1e3,
        "window_ms": window * 1e3,
        "coverage": (total / window) if window > 0 else 1.0,
        "n_spans": len(chain),
    }


def critical_paths_by_step(spans: Sequence[Span]) -> Dict[int, Dict]:
    """Per-step critical paths: group by ``span.step`` and extract each
    step's chain independently (cross-step chains would bind on the flush
    boundary, not the work)."""
    by_step: Dict[int, List[Span]] = {}
    for s in spans:
        by_step.setdefault(int(s.step), []).append(s)
    return {step: critical_path(ss) for step, ss in sorted(by_step.items())}


def bubble_fraction(spans: Sequence[Span], step: Optional[int] = None) -> Optional[float]:
    """Pipeline bubble fraction from PipeEngine instruction spans
    (forward/backward/wgrad compute, tagged with their stage): over the
    step window (earliest pipe-span start to latest end), each stage's busy
    time is the sum of its span durations; the bubble fraction is
    ``1 - mean_stage(busy / window)``.  ``step=None`` pools all steps.
    Returns None when the stream carries no stage-tagged pipe spans."""
    pipe = PIPE_METRICS
    rows = [
        s
        for s in spans
        if s.metric in pipe
        and (step is None or int(s.step) == int(step))
        and s.tags is not None
        and "stage" in s.tags
    ]
    if not rows:
        return None
    t0 = min(s.start for s in rows)
    t1 = max(s.start + s.duration for s in rows)
    window = t1 - t0
    if window <= 0:
        return None
    busy: Dict[int, float] = {}
    for s in rows:
        busy[int(s.tags["stage"])] = busy.get(int(s.tags["stage"]), 0.0) + s.duration
    frac = sum(min(1.0, b / window) for b in busy.values()) / len(busy)
    return max(0.0, min(1.0, 1.0 - frac))


# ---------------------------------------------------------- telemetry feed
def step_span_summary(
    step: Optional[int] = None, manager=None, limit: int = 512
) -> Optional[Dict[str, Dict[str, float]]]:
    """Per-metric rollup of one step's spans from the live manager's ring
    (``tail`` — peeked, never drained): ``{metric: {count, total_ms}}``.
    The feed ``telemetry.record_step`` embeds as the ``spans`` object of a
    steps.jsonl line.  Bounded by ``limit`` tail spans so a long-buffered
    run never pays an O(ring) copy per step.

    ``step=None`` summarizes the NEWEST buffered span's step — the step
    that just finished.  Not ``manager.step``: on the default train path
    ``auto_inc_step`` advances the counter BEFORE telemetry records the
    step, so the counter already names the (empty) next step."""
    from ..ndtimeline.api import get_manager, is_active

    if manager is None:
        if not is_active():
            return None
        manager = get_manager()
    tail = manager.tail(limit)
    if step is None:
        if not tail:
            return None
        step = tail[-1].step
    out: Dict[str, Dict[str, float]] = {}
    for s in tail:
        if int(s.step) != int(step):
            continue
        cell = out.setdefault(s.metric, {"count": 0, "total_ms": 0.0})
        cell["count"] += 1
        cell["total_ms"] += s.duration * 1e3
    for cell in out.values():
        cell["total_ms"] = round(cell["total_ms"], 4)
    return out or None


def record_trace_metrics(
    merged: Sequence[Span],
    clock: Optional[ClockSync] = None,
    bubble: Optional[float] = None,
    cp: Optional[Dict] = None,
) -> None:
    """Publish a merge's headline numbers into the telemetry registry —
    the ``trace:`` and ``critical-path:`` dashboard blocks (exporters.py
    group on the ``trace_`` / ``critical_path_`` prefixes).  No-op while
    telemetry is dormant."""
    from . import api as _tel

    if not _tel.is_active():
        return
    _tel.count("trace_merges_total")
    _tel.count("trace_spans_merged_total", len(merged))
    _tel.set_gauge("trace_ranks", len({s.rank for s in merged}))
    if clock is not None:
        _tel.set_gauge("trace_clock_residual_us", clock.residual_us)
    if bubble is None:
        bubble = bubble_fraction(merged)
    if bubble is not None:
        _tel.set_gauge("trace_pipe_bubble_fraction", bubble)
    if cp is None and merged:
        cp = critical_path(merged)
    if cp is not None:
        _tel.set_gauge("critical_path_ms", cp["total_ms"])
        _tel.set_gauge("critical_path_window_ms", cp["window_ms"])
        _tel.set_gauge("critical_path_coverage", cp["coverage"])
        _tel.set_gauge("critical_path_spans", cp["n_spans"])
