"""vescale_tpu.telemetry — unified runtime telemetry.

Three observability signals, one pipeline (docs/observability.md):

  1. **Metrics registry** (registry.py): counters / gauges / rolling-window
     histograms fed per-step by the train step, pipe engine,
     DistributedOptimizer and checkpoint layer.
  2. **Compile-time step reports** (step_report.py): one JSON per compiled
     program — FLOPs, peak HBM, argument/output/temp bytes, collective
     counts (shared counter with debug/comm_mode).
  3. **Exporters** (exporters.py): per-step JSONL stream, Prometheus text
     exposition, human-readable dashboard — plus a **straggler detector**
     (straggler.py) over the ndtimeline streamer's cross-rank spans.
  4. **Memory tracking** (memtrack.py + memory_report.py): live HBM gauges
     (host-RSS fallback), owner-tagged live-array census, leak detection,
     AOT-budget drift, and the OOM **flight recorder** (forensic JSON dump
     on RESOURCE_EXHAUSTED or via ``dump_now()``).
  5. **Distributed trace timeline + cost calibration** (trace.py +
     calibrate.py): cross-rank clock-offset estimation, merged Perfetto
     traces with per-step critical paths and pipeline bubble fraction, and
     the measured collective-cost table (``collective_calibration.json``)
     that re-prices the redistribution planner, the quant-edge competition
     and ``simulate_schedule`` from wall-clock data
     (``VESCALE_COST_CALIBRATION``).
  6. **Plan-vs-reality cost auditing** (costaudit.py): a bounded
     prediction ledger every priced plan records into, a per-step
     predicted-vs-measured join publishing ``cost_model_*`` divergence
     gauges + the ``cost-model-drift`` rule, online calibration harvest
     (measured spans fold back into the table, digest rotation re-plans),
     per-layer roofline attribution and the what-if mesh scorer
     (``VESCALE_COSTAUDIT``).

Gating contract (same as ndtimeline): a run that never calls
``telemetry.init()`` pays zero overhead — no registry, no locks, no files,
no tag registry (the memtrack hooks are no-op function references).
"""

from . import calibrate, costaudit, memtrack, ops_server, trace
from .api import (
    count,
    dashboard,
    get_registry,
    get_state,
    init,
    is_active,
    observe,
    prometheus_dump,
    record_event,
    record_step,
    set_gauge,
    shutdown,
    write_step_report,
)
from .exporters import JsonlExporter, parse_prometheus_text, prometheus_text
from .memory_report import compare_with_aot, device_memory_stats
from .memtrack import dump_now, flight_recorder, tagged
from .registry import Counter, Gauge, Histogram, MetricsRegistry
from .step_report import build_step_report, read_step_report
from .straggler import StragglerDetector

__all__ = [
    "init",
    "shutdown",
    "is_active",
    "get_state",
    "get_registry",
    "record_step",
    "record_event",
    "observe",
    "count",
    "set_gauge",
    "write_step_report",
    "prometheus_dump",
    "dashboard",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlExporter",
    "prometheus_text",
    "parse_prometheus_text",
    "build_step_report",
    "read_step_report",
    "StragglerDetector",
    "memtrack",
    "trace",
    "calibrate",
    "costaudit",
    "ops_server",
    "flight_recorder",
    "dump_now",
    "tagged",
    "compare_with_aot",
    "device_memory_stats",
]
