"""Compile-time step reports.

One JSON artifact per compiled program combining the two static views the
stack already half-produces: ``debug/comm_mode`` collective counts and XLA's
cost/memory analysis (``compiled.cost_analysis()`` / ``memory_analysis()``).
Generated ONCE per program (compile-time, not per-step): the report answers
"what does a step cost" — FLOPs, peak HBM, argument/output/temp bytes, and
how many of each collective the partitioner inserted — before any step runs.

The collective counts here and ``debug.comm_mode.comm_counts`` are computed
by the same counter over the same optimized-HLO text, so they agree by
construction on the same program (the acceptance contract the smoke test
asserts).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional

import jax

from ..debug.comm_mode import count_collectives

__all__ = ["build_step_report", "write_step_report", "read_step_report"]


def _cost_dict(compiled) -> Dict[str, Any]:
    """Normalize ``compiled.cost_analysis()`` across jax versions (dict on
    new, list-of-dict per partition on older)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


def build_step_report(
    fn: Callable,
    *args,
    static_argnums=(),
    name: str = "step",
    aot_report=None,
    donate_argnums=None,
    **kwargs,
) -> Dict[str, Any]:
    """Lower+compile ``fn(*args, **kwargs)`` (or reuse ``fn.lower`` when fn
    is already jitted — e.g. the step from ``make_train_step``) and distill
    the compiled program into a JSON-serializable report.

    Keys: ``flops``, ``bytes_accessed``, ``peak_bytes`` (argument + output +
    temp - aliased: the program's HBM high-water mark as XLA accounts it),
    ``argument_bytes``/``output_bytes``/``temp_bytes``/``alias_bytes``/
    ``generated_code_bytes``, and ``collectives`` (the comm_mode counter over
    the optimized HLO).  Fields XLA cannot provide on a backend come back
    None rather than raising — the report must degrade, not fail a run.

    ``aot_report`` (path or loaded AOT_*_REPORT.json dict): attaches an
    ``aot_drift`` section diffing the measured memory footprint against the
    AOT budget (memory_report.compare_with_aot; None when either side lacks
    a usable byte count).

    ``donate_argnums``: the donation the jit of ``fn`` uses — forwarded to
    the shardcheck section so donated steps are not falsely flagged VSC105;
    None (default) skips the donation check."""
    if hasattr(fn, "lower"):
        lowered = fn.lower(*args, **kwargs)
    else:
        lowered = jax.jit(fn, static_argnums=static_argnums).lower(*args, **kwargs)
    report: Dict[str, Any] = {
        "name": name,
        "platform": jax.devices()[0].platform,
        "num_devices": len(jax.devices()),
    }
    try:
        compiled = lowered.compile()
    except Exception as e:  # unpartitionable/abstract program: static views only
        report.update(
            flops=None,
            bytes_accessed=None,
            peak_bytes=None,
            collectives=count_collectives(lowered.as_text()),
            compile_error=repr(e),
        )
        return report
    cost = _cost_dict(compiled)
    report["flops"] = float(cost["flops"]) if "flops" in cost else None
    report["bytes_accessed"] = (
        float(cost["bytes accessed"]) if "bytes accessed" in cost else None
    )
    mem = None
    try:
        mem = compiled.memory_analysis()
    except Exception:
        pass
    for key, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("alias_bytes", "alias_size_in_bytes"),
        ("generated_code_bytes", "generated_code_size_in_bytes"),
    ):
        report[key] = getattr(mem, attr, None) if mem is not None else None
    peak = getattr(mem, "peak_memory_in_bytes", None) if mem is not None else None
    if peak is None and mem is not None:
        parts = [report["argument_bytes"], report["output_bytes"], report["temp_bytes"]]
        if all(p is not None for p in parts):
            peak = sum(parts) - (report["alias_bytes"] or 0)
    report["peak_bytes"] = peak
    try:
        text = compiled.as_text()
    except Exception:
        text = lowered.as_text()
    report["collectives"] = count_collectives(text)
    try:
        from .costaudit import layer_attribution

        # per-layer roofline attribution over the same optimized HLO the
        # collective counter reads: FLOPs/bytes per op_name scope,
        # compute- vs memory-bound against the device roofline
        report["layer_attribution"] = layer_attribution(text)
    except Exception as e:  # degrade, never fail a run for observability
        report["layer_attribution"] = {"error": repr(e)}
    if aot_report is not None:
        from .memory_report import compare_with_aot

        report["aot_drift"] = compare_with_aot(report, aot_report)
    _attach_shardcheck(report, fn, args, kwargs, name, donate_argnums, static_argnums)
    return report


def _attach_shardcheck(report, fn, args, kwargs, name, donate_argnums,
                       static_argnums=()) -> None:
    """Static placement findings for the SAME program the report describes
    (analysis/shardcheck.py), keyed ``shardcheck`` — input shardings read
    off the argument arrays' own NamedShardings.  Gated by
    ``VESCALE_SHARDCHECK`` (off -> no section); never fails the report.
    ``donate_argnums``: forwarded from the caller; ``None`` (the default —
    the report builder cannot see what the caller's jit donates) skips the
    VSC105 donation check rather than falsely flagging donated steps."""
    from .. import analysis

    if not analysis.enabled():
        return
    try:
        findings = analysis.shardcheck(
            fn, *args, name=name, check_source=False,
            donate_argnums=donate_argnums, static_argnums=static_argnums,
            **kwargs
        )
        report["shardcheck"] = findings.to_dict()
    except Exception as e:  # degrade, never fail a run for observability
        report["shardcheck"] = {"error": repr(e)}


def write_step_report(report: Dict[str, Any], path: str) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(report, f, indent=2, default=str)
    return path


def read_step_report(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
