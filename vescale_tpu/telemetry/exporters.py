"""Telemetry exporters: per-step JSONL, Prometheus text exposition, and a
human-readable dashboard string.

All three read the same ``MetricsRegistry`` snapshot; the JSONL exporter
additionally receives each per-step record as it is emitted (the stream a
dashboard tails), so offline analysis never has to reconstruct steps from
registry aggregates.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from typing import Dict, Optional, TextIO

from .registry import MetricsRegistry

__all__ = [
    "JsonlExporter",
    "prometheus_text",
    "parse_prometheus_text",
    "dashboard",
    "sanitize_metric_name",
]

# Prometheus metric-name charset: [a-zA-Z_:][a-zA-Z0-9_:]*
_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")
# one exposition line: name{labels}? value  (value: float/int/NaN/+-Inf)
_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(-?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|NaN|[+-]?Inf))$"
)


def sanitize_metric_name(name: str) -> str:
    """Map an internal metric name onto the Prometheus charset."""
    if _NAME_OK.match(name):
        return name
    fixed = _NAME_FIX.sub("_", name)
    if not re.match(r"[a-zA-Z_:]", fixed[:1] or "_"):
        fixed = "_" + fixed
    return fixed


class JsonlExporter:
    """Appends one JSON object per record to ``path``.  Opens lazily and
    flushes per line — a crashed run keeps every completed step, and a tail
    -f dashboard sees lines as they land."""

    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[TextIO] = None
        self._lock = threading.Lock()
        self.emitted = 0

    def emit(self, record: Dict) -> None:
        line = json.dumps(record, default=str)
        with self._lock:
            if self._fh is None:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write(line + "\n")
            self._fh.flush()
            self.emitted += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def prometheus_text(registry: MetricsRegistry) -> str:
    """Render the registry in the Prometheus text exposition format
    (version 0.0.4).  Histograms export as summaries: ``{quantile="..."}``
    series plus ``_count``/``_sum``."""
    snap = registry.snapshot()
    lines = []
    for name in sorted(snap["counters"]):
        pname = sanitize_metric_name(name)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {_fmt(snap['counters'][name])}")
    for name in sorted(snap["gauges"]):
        pname = sanitize_metric_name(name)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {_fmt(snap['gauges'][name])}")
    for name in sorted(snap["histograms"]):
        pname = sanitize_metric_name(name)
        h = snap["histograms"][name]
        lines.append(f"# TYPE {pname} summary")
        for label, q in (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99")):
            if label in h:
                lines.append(f'{pname}{{quantile="{q}"}} {_fmt(h[label])}')
        lines.append(f"{pname}_count {_fmt(h['count'])}")
        lines.append(f"{pname}_sum {_fmt(h['sum'])}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"  # canonical Prometheus spelling (repr gives 'nan')
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f.is_integer() else repr(f)


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Strict line-format parser for the exposition format this module
    emits (and any simple single-label exposition).  Returns
    ``{series: value}`` with the label set kept in the key.  Raises
    ``ValueError`` on any non-comment line it cannot parse — the validation
    half of the telemetry smoke test."""
    out: Dict[str, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            raise ValueError(f"prometheus text line {lineno} unparseable: {line!r}")
        name, labels, value = m.groups()
        out[name + (labels or "")] = float(value)
    return out


def _fmt_bytes(v: float) -> str:
    """Humanize a byte count for the dashboard memory section."""
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024.0
    return f"{v:.1f} TiB"


def dashboard(registry: MetricsRegistry, title: str = "telemetry") -> str:
    """Human-readable fixed-width dump of the registry (the quick-look
    answer to 'how is this run doing' without any external stack).  Memory
    gauges (the ``mem_`` namespace memtrack feeds) render as their own
    section with humanized byte values."""
    snap = registry.snapshot()
    width = 78
    lines = ["=" * width, f"{title:^{width}}", "=" * width]
    mem_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith("mem_")}
    # consistency_* (cross-rank desync checks) lives in the resilience
    # block: one recovery-story section, not two
    _res = ("resilience_", "consistency_")
    # quantized gradient collectives + planner quant hops: one
    # grad-compression story (collectives._compress_telemetry feed)
    _qc = ("grad_compress_", "redistribute.quant")
    # cross-rank trace timeline + per-step critical path (trace.py
    # record_trace_metrics feed): merge counts, clock residual, bubble
    # fraction in `trace:`; the extracted chain's numbers in `critical-path:`
    _tr = ("trace_",)
    _cp = ("critical_path_",)
    # serving block: the continuous-batching loop's request ledger +
    # in-flight/queue gauges (serve/scheduler.py + serve/loop.py feed)
    _sv = ("serve_",)
    # fleet block: the multi-replica router's dispatch/failover/breaker
    # totals + healthy-replica and pending gauges (serve/router.py +
    # serve/fleet.py feed)
    _fl = ("fleet_",)
    # fleet-timeline block: the FleetObservability rollups — aggregate
    # goodput/throughput, fleet MFU, fleet p99 TTFT, per-replica shed
    # rates and the SLO burn-rate gauge (serve/obs.py publish() feed;
    # carved out of the fleet_ prefix by its own fleet_timeline_ prefix)
    _ft = ("fleet_timeline_",)
    # pallas kernel layer: dispatch/fallback decision totals per kernel
    # (kernels/__init__.py feed, riding the same registry gate)
    _kn = ("kernel_",)
    # alert-engine block: lifecycle totals (fired/resolved/pending), the
    # live firing-count gauge and the per-rule 0/1/2 state gauges
    # (telemetry/alerts.py _emit feed)
    _al = ("alerts_",)
    # cost-model audit block: prediction/match totals, harvested-span and
    # digest-rotation counters, the divergence gauges the drift rule reads
    # (telemetry/costaudit.py audit_step feed)
    _cm = ("cost_model_",)
    cm_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_cm)}
    cm_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_cm)}
    al_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_al)}
    al_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_al)}
    kn_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_kn)}
    kn_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_kn)}
    res_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_res)}
    qc_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_qc)}
    tr_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_tr)}
    cp_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_cp)}
    sv_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_sv)}
    ft_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_ft)}
    ft_gauges = {n: v for n, v in snap["gauges"].items() if n.startswith(_ft)}
    fl_counters = {n: v for n, v in snap["counters"].items()
                   if n.startswith(_fl) and not n.startswith(_ft)}
    fl_gauges = {n: v for n, v in snap["gauges"].items()
                 if n.startswith(_fl) and not n.startswith(_ft)}
    other_gauges = {
        n: v
        for n, v in snap["gauges"].items()
        if not n.startswith(("mem_",) + _res + _qc + _tr + _cp + _sv + _kn + _fl + _al + _cm)
    }
    res_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_res)}
    qc_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_qc)}
    tr_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_tr)}
    cp_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_cp)}
    sv_counters = {n: v for n, v in snap["counters"].items() if n.startswith(_sv)}
    other_counters = {
        n: v
        for n, v in snap["counters"].items()
        if not n.startswith(_res + _qc + _tr + _cp + _sv + _kn + _fl + _al + _cm)
    }
    if other_counters:
        lines.append("counters:")
        for name in sorted(other_counters):
            lines.append(f"  {name:<48} {_fmt(other_counters[name]):>12}")
    if other_gauges:
        lines.append("gauges:")
        for name in sorted(other_gauges):
            lines.append(f"  {name:<48} {other_gauges[name]:>12.6g}")
    if qc_counters or qc_gauges:
        # byte-savings block of the quantized collectives: bytes-saved
        # totals humanize; the compress-ratio gauge stays numeric
        lines.append("grad-compression:")
        for name in sorted(qc_counters):
            v = qc_counters[name]
            shown = _fmt_bytes(v) if "bytes" in name else _fmt(v)
            lines.append(f"  {name:<48} {shown:>16}")
        for name in sorted(qc_gauges):
            lines.append(f"  {name:<48} {qc_gauges[name]:>12.6g}")
    if tr_counters or tr_gauges:
        # cross-rank trace block: merge totals + clock residual + bubble
        lines.append("trace:")
        for name in sorted(tr_counters):
            lines.append(f"  {name:<48} {_fmt(tr_counters[name]):>12}")
        for name in sorted(tr_gauges):
            lines.append(f"  {name:<48} {tr_gauges[name]:>12.6g}")
    if cp_counters or cp_gauges:
        # per-step critical path: chain length/coverage of the merged trace
        lines.append("critical-path:")
        for name in sorted(cp_counters):
            lines.append(f"  {name:<48} {_fmt(cp_counters[name]):>12}")
        for name in sorted(cp_gauges):
            lines.append(f"  {name:<48} {cp_gauges[name]:>12.6g}")
    if kn_counters or kn_gauges:
        # pallas kernel layer: dispatch-decision and fallback totals per
        # kernel (decisions are host-side — once per eager call, once per
        # trace for compiled programs; docs/kernels.md)
        lines.append("kernels:")
        for name in sorted(kn_counters):
            lines.append(f"  {name:<48} {_fmt(kn_counters[name]):>12}")
        for name in sorted(kn_gauges):
            lines.append(f"  {name:<48} {kn_gauges[name]:>12.6g}")
    if sv_counters or sv_gauges:
        # request ledger of the serve loop: admitted/completed/shed/
        # timed-out/evicted totals + in-flight and queue-depth gauges
        lines.append("serving:")
        for name in sorted(sv_counters):
            lines.append(f"  {name:<48} {_fmt(sv_counters[name]):>12}")
        for name in sorted(sv_gauges):
            lines.append(f"  {name:<48} {sv_gauges[name]:>12.6g}")
    if fl_counters or fl_gauges:
        # fleet-router block: dispatch/redispatch/failover/hedge/shed
        # totals, breaker transitions, healthy-replica + pending gauges
        lines.append("fleet:")
        for name in sorted(fl_counters):
            lines.append(f"  {name:<48} {_fmt(fl_counters[name]):>12}")
        for name in sorted(fl_gauges):
            lines.append(f"  {name:<48} {fl_gauges[name]:>12.6g}")
    if ft_counters or ft_gauges:
        # fleet-timeline block: the aggregated fleet health rollups the
        # /fleet endpoint serves (goodput, MFU, p99 TTFT, shed rates,
        # SLO burn rate) — the operator's "is a replica degrading" view
        lines.append("fleet-timeline:")
        for name in sorted(ft_counters):
            lines.append(f"  {name:<48} {_fmt(ft_counters[name]):>12}")
        for name in sorted(ft_gauges):
            lines.append(f"  {name:<48} {ft_gauges[name]:>12.6g}")
    if cm_counters or cm_gauges:
        # cost-model audit block: is the price list honest — divergence
        # ratio (max(m/p, p/m), 1.0 = perfect), match totals, and how much
        # measured reality the online harvest has folded back
        lines.append("cost-model:")
        for name in sorted(cm_counters):
            lines.append(f"  {name:<48} {_fmt(cm_counters[name]):>12}")
        for name in sorted(cm_gauges):
            lines.append(f"  {name:<48} {cm_gauges[name]:>12.6g}")
    if al_counters or al_gauges:
        # alert-engine block: the lifecycle totals + per-rule state gauges
        # (0=ok 1=pending 2=firing), then the live engine's firing/pending
        # rule names spelled out — the dashboard answer to "is anything
        # paging right now" without hitting /alerts
        lines.append("alerts:")
        for name in sorted(al_counters):
            lines.append(f"  {name:<48} {_fmt(al_counters[name]):>12}")
        for name in sorted(al_gauges):
            lines.append(f"  {name:<48} {al_gauges[name]:>12.6g}")
        from . import alerts as _alerts

        eng = _alerts.get_engine()
        if eng is not None:
            firing, pending = eng.firing(), eng.pending()
            lines.append(f"  firing:  {', '.join(firing) if firing else '(none)'}")
            if pending:
                lines.append(f"  pending: {', '.join(pending)}")
    from . import timeseries as _timeseries

    _store = _timeseries.get_store()
    if _store is not None:
        # time-series store block: what the alert rules are evaluated
        # over — series count, retained samples across all tiers, and the
        # sampling cadence/tiering shape
        s = _store.stats()
        lines.append("timeseries:")
        lines.append(f"  {'timeseries_series':<48} {_fmt(s['series']):>12}")
        lines.append(f"  {'timeseries_retained_samples':<48} {_fmt(s['retained_samples']):>12}")
        lines.append(f"  {'timeseries_samples_taken':<48} {_fmt(s['samples_taken']):>12}")
        lines.append(f"  {'timeseries_cadence_s':<48} {s['cadence_s']:>12.6g}")
        lines.append(
            f"  {'timeseries_tiers':<48} "
            f"{str(s['tiers']) + ' x ' + str(s['base_len']) + ' @ /' + str(s['tier_factor']):>12}"
        )
    if res_counters or res_gauges:
        # recovery-event block (resilience/loop.py feed, mirrors memory:):
        # a zero-fault run shows armed-but-quiet counters at 0
        lines.append("resilience:")
        for name in sorted(res_counters):
            lines.append(f"  {name:<48} {_fmt(res_counters[name]):>12}")
        for name in sorted(res_gauges):
            lines.append(f"  {name:<48} {res_gauges[name]:>12.6g}")
    if mem_gauges:
        lines.append("memory:")
        for name in sorted(mem_gauges):
            v = mem_gauges[name]
            # "bytes" anywhere in the name: covers mem_tag_*_bytes AND the
            # device gauges (mem_device<i>_bytes_in_use/peak_bytes_in_use/
            # bytes_limit), which don't END with the suffix
            shown = _fmt_bytes(v) if "bytes" in name else _fmt(v)
            lines.append(f"  {name:<48} {shown:>16}")
    if snap["histograms"]:
        lines.append("histograms (rolling window):")
        for name in sorted(snap["histograms"]):
            h = snap["histograms"][name]
            if h.get("count"):
                lines.append(
                    f"  {name:<38} n={h['count']:<7} p50={h.get('p50', 0):.6g} "
                    f"p95={h.get('p95', 0):.6g} p99={h.get('p99', 0):.6g}"
                )
            else:
                lines.append(f"  {name:<38} n=0")
    lines.append("=" * width)
    return "\n".join(lines)
