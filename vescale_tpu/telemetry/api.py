"""Telemetry global API — the zero-overhead gate.

Mirrors the ndtimeline activation contract (ndtimeline/api.py): the runtime
wiring (train step, pipe engine, optimizer, checkpoint) calls the helpers
here on every operation, and a run that never calls ``telemetry.init()``
must pay nothing — ``is_active()`` is a single module-global check, no
registry, no ring buffers, no locks, no files are ever created.

    from vescale_tpu import telemetry

    telemetry.init(out_dir="/tmp/run0")        # flip the gate
    ... train ...                              # steps stream to steps.jsonl
    print(telemetry.dashboard())               # human summary
    telemetry.prometheus_dump()                # prometheus text exposition
    telemetry.shutdown()

Per-step records land in ``<out_dir>/steps.jsonl`` (one JSON object per
step); ``write_step_report`` drops compile-time program reports next to
them.  All helpers are no-ops (returning None) while dormant.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

from . import alerts as _alerts
from . import costaudit as _costaudit
from . import memtrack as _memtrack
from . import timeseries as _timeseries
from .exporters import JsonlExporter, dashboard as _dashboard, prometheus_text
from .registry import MetricsRegistry

__all__ = [
    "init",
    "shutdown",
    "is_active",
    "get_state",
    "get_registry",
    "record_step",
    "record_event",
    "observe",
    "count",
    "set_gauge",
    "write_step_report",
    "prometheus_dump",
    "dashboard",
]


class TelemetryState:
    """Everything a live telemetry run owns.  Exists ONLY between ``init``
    and ``shutdown`` — its absence IS the off state."""

    def __init__(
        self,
        out_dir: Optional[str],
        rank: int,
        window: int,
        jsonl: bool,
    ):
        self.out_dir = out_dir
        self.rank = rank
        self.registry = MetricsRegistry(default_window=window)
        self.step = 0
        self.jsonl: Optional[JsonlExporter] = None
        self.memtrack = None  # set by init() when memory tracking is on
        self.timeseries = None  # set by init() when the history store is on
        self.alerts = None  # set by init() when the alert engine is on
        self.costaudit = None  # set by init() when cost auditing is on
        self.last_step_report: Optional[Dict] = None  # flight-recorder feed
        if jsonl and out_dir is not None:
            os.makedirs(out_dir, exist_ok=True)
            self.jsonl = JsonlExporter(os.path.join(out_dir, "steps.jsonl"))


_STATE: Optional[TelemetryState] = None


def init(
    out_dir: Optional[str] = None,
    rank: int = 0,
    window: int = 1024,
    jsonl: bool = True,
    memtrack: bool = True,
    memtrack_interval: int = 1,
    memtrack_history: int = 16,
    memtrack_leak_steps: int = 5,
    timeseries: Optional[bool] = None,
    timeseries_cadence_s: Optional[float] = None,
    alerts: Optional[bool] = None,
    costaudit: Optional[bool] = None,
) -> TelemetryState:
    """Activate telemetry.  ``out_dir=None`` keeps everything in-memory
    (registry only — no JSONL stream, no report files).  Re-initializing
    while active closes the previous state's stream first (its registry is
    discarded).

    ``memtrack`` (default on) also activates memory tracking (memtrack.py):
    live HBM gauges + tagged live-array census sampled every
    ``memtrack_interval`` steps, a ``memtrack_history``-deep sample ring for
    the OOM flight recorder, and a leak warning after
    ``memtrack_leak_steps`` consecutive steps of monotonic untagged
    growth.

    ``timeseries``/``alerts`` (default: the ``VESCALE_TIMESERIES`` /
    ``VESCALE_ALERTS`` knobs, both on) also activate the metric history
    store (timeseries.py) and the SLO alert engine (alerts.py) — the
    engine evaluates over the store, so ``alerts`` implies nothing
    without ``timeseries`` except manual (code-raised) alerts.

    ``costaudit`` (default: ``VESCALE_COSTAUDIT``, on) also activates the
    plan-vs-reality cost auditor (costaudit.py): a prediction ledger every
    priced plan records into, a per-step predicted-vs-measured join
    publishing ``cost_model_*`` divergence gauges and the
    ``cost-model-drift`` rule, and the online calibration harvest feeding
    measured spans back into the active CalibrationTable."""
    global _STATE
    if _STATE is not None:
        shutdown()
    from ..analysis import envreg

    _STATE = TelemetryState(out_dir, rank, window, jsonl)
    if memtrack:
        _STATE.memtrack = _memtrack.activate(
            history=memtrack_history,
            leak_steps=memtrack_leak_steps,
            census_interval=memtrack_interval,
        )
    if timeseries is None:
        timeseries = envreg.get_bool("VESCALE_TIMESERIES")
    if alerts is None:
        alerts = envreg.get_bool("VESCALE_ALERTS")
    if timeseries:
        _STATE.timeseries = _timeseries.activate(
            _STATE.registry,
            cadence_s=(
                timeseries_cadence_s
                if timeseries_cadence_s is not None
                else envreg.get_float("VESCALE_TIMESERIES_CADENCE_S")
            ),
            base_len=envreg.get_int("VESCALE_TIMESERIES_BASE_LEN"),
            tier_factor=envreg.get_int("VESCALE_TIMESERIES_TIER_FACTOR"),
            tiers=envreg.get_int("VESCALE_TIMESERIES_TIERS"),
        )
    if alerts:
        _STATE.alerts = _alerts.activate(
            store=_STATE.timeseries,
            history=envreg.get_int("VESCALE_ALERTS_HISTORY"),
            min_eval_interval_s=envreg.get_float("VESCALE_ALERTS_EVAL_INTERVAL_S"),
        )
    if costaudit is None:
        costaudit = envreg.get_bool("VESCALE_COSTAUDIT")
    if costaudit:
        # after alerts: activation arms the cost-model-drift rule on the
        # live engine when there is one
        _STATE.costaudit = _costaudit.activate(_STATE.registry)
    return _STATE


def shutdown() -> None:
    """Deactivate and release the gate; flushes/closes the JSONL stream
    and restores the memtrack no-op hooks."""
    global _STATE
    if _STATE is not None and _STATE.jsonl is not None:
        _STATE.jsonl.close()
    _costaudit.deactivate()
    _memtrack.deactivate()
    _alerts.deactivate()
    _timeseries.deactivate()
    _STATE = None


def is_active() -> bool:
    return _STATE is not None


def get_state() -> Optional[TelemetryState]:
    return _STATE


def get_registry() -> Optional[MetricsRegistry]:
    return _STATE.registry if _STATE is not None else None


# ------------------------------------------------------------- hot helpers
# Each is a one-branch no-op while dormant: the runtime wiring calls these
# unconditionally and un-instrumented runs must not allocate or lock.

def record_step(metrics: Dict[str, Any], kind: str = "train") -> None:
    """Ingest one step's metrics (the train.py feed; the serve loop's
    decode steps pass ``kind="serve"``).

    Train conventions: ``step_time_s`` feeds the step-time histogram,
    ``tokens`` the throughput counters, scalar floats become gauges.  The
    full record (plus ``step``/``rank``/``ts``) appends to steps.jsonl.

    ``kind="serve"`` skips the train_* registry conventions (the serve
    loop feeds its own ``serve_*`` metrics directly) but keeps everything
    structural: the step counter, the memory sample and the per-step
    ``spans`` rollup — so a decode step's spans land on a steps.jsonl line
    of their OWN step instead of smearing onto a stale training step."""
    st = _STATE
    if st is None:
        return
    st.step = int(metrics.get("step", st.step + 1))
    reg = st.registry
    if kind == "train":
        reg.counter("train_steps_total").inc()
        if "step_time_s" in metrics:
            reg.histogram("train_step_time_seconds").observe(metrics["step_time_s"])
        if "tokens" in metrics:
            reg.counter("train_tokens_total").inc(metrics["tokens"])
        if "tokens_per_sec" in metrics:
            reg.gauge("train_tokens_per_sec").set(metrics["tokens_per_sec"])
        for key, gname in (
            ("loss", "train_loss"),
            ("grad_norm", "train_grad_norm"),
            ("loss_scale", "train_loss_scale"),
            ("skip_count", "train_skipped_steps"),
        ):
            if key in metrics and metrics[key] is not None:
                reg.gauge(gname).set(float(metrics[key]))
        if metrics.get("overflow"):
            reg.counter("train_overflow_steps_total").inc()
    mem = None
    if st.memtrack is not None:
        # per-step memory sample: device gauges, tagged census, leak check
        # (None on census-interval skip steps — the jsonl line just omits it)
        mem = st.memtrack.on_step(st.step, reg)
    # the step boundary IS the sampling/evaluation boundary: the cost
    # auditor joins predicted-vs-measured and publishes its divergence
    # gauges FIRST so the history sample taken right after (and the
    # cost-model-drift rule evaluating over it) sees this step's numbers;
    # the store keeps at most one sample per cadence and the engine
    # rate-limits itself, so a kHz decode loop pays three no-op-ish calls
    # per step (dormant runs pay the no-op hook references — the memtrack
    # contract)
    audit = _costaudit.audit_step(kind)
    _timeseries.sample(kind)
    _alerts.evaluate()
    if st.jsonl is not None:
        rec = {"step": st.step, "rank": st.rank, "ts": time.time(), **metrics}
        if kind != "train":
            rec["kind"] = kind
        if mem is not None:
            rec["memory"] = mem
        spans = _step_spans()
        if spans is not None:
            rec["spans"] = spans
        if audit is not None:
            rec["cost_audit"] = audit
        st.jsonl.emit(rec)


def _step_spans():
    """Per-metric span rollup of the step being recorded, when the
    ndtimeline profiler is live — the ``spans`` object of a steps.jsonl
    line (``{metric: {count, total_ms}}``).  None (and zero cost) when the
    profiler is dormant; the manager's ring is PEEKED, never drained, so
    the flush a handler expects still sees every span."""
    from ..ndtimeline.api import is_active as _nd_active

    if not _nd_active():
        return None
    from .trace import step_span_summary

    return step_span_summary()


def record_event(kind: str, **fields) -> None:
    """Append a non-step EVENT line to steps.jsonl (recovery events:
    restarts, rollbacks, preemptions, quarantines — the resilience loop's
    feed).  Events carry ``{"event": kind, "step": <current>, ...fields}``
    so a dashboard tailing the stream can interleave them with step
    records.  No-op while dormant or without an out_dir stream."""
    st = _STATE
    if st is None or st.jsonl is None:
        return
    st.jsonl.emit(
        {"event": kind, "step": st.step, "rank": st.rank, "ts": time.time(), **fields}
    )


def observe(name: str, value: float) -> None:
    if _STATE is not None:
        _STATE.registry.histogram(name).observe(value)


def count(name: str, n: float = 1) -> None:
    if _STATE is not None:
        _STATE.registry.counter(name).inc(n)


def set_gauge(name: str, value: float) -> None:
    if _STATE is not None:
        _STATE.registry.gauge(name).set(value)


# ----------------------------------------------------------------- outputs
def write_step_report(
    name: str, fn: Callable, *args, aot_report=None, **kwargs
) -> Optional[Dict]:
    """Build a compile-time step report (see step_report.py) and — when an
    ``out_dir`` is configured — persist it as ``<out_dir>/<name>_report.json``.
    No-op while dormant.

    ``aot_report``: path to (or loaded dict of) a matching
    ``AOT_*_REPORT.json`` — the report gains an ``aot_drift`` section
    diffing the compiled step's memory footprint against the AOT budget,
    and drift beyond 10% warns (see memory_report.compare_with_aot)."""
    st = _STATE
    if st is None:
        return None
    from .step_report import build_step_report, write_step_report as _write

    report = build_step_report(fn, *args, name=name, aot_report=aot_report, **kwargs)
    st.last_step_report = report  # flight-recorder forensics feed
    if st.out_dir is not None:
        _write(report, os.path.join(st.out_dir, f"{name}_report.json"))
    if report.get("flops") is not None:
        st.registry.gauge(f"step_report_{name}_flops").set(report["flops"])
    if report.get("peak_bytes") is not None:
        st.registry.gauge(f"step_report_{name}_peak_bytes").set(report["peak_bytes"])
    drift = report.get("aot_drift")
    if drift is not None:
        # the AOT memory budget is a priced plan too: ledger it so the
        # train path always has joined predictions (instant join — both
        # sides are known at compile time)
        pid = _costaudit.record_prediction(
            "aot_memory", predicted_bytes=drift["aot_bytes"], unit="bytes",
            detail={"name": name, "source": drift["aot_source"]},
        )
        _costaudit.record_measurement(pid, measured_bytes=drift["measured_bytes"])
        st.registry.gauge(f"step_report_{name}_aot_drift_frac").set(drift["drift_frac"])
        if drift["exceeds_tolerance"]:
            # the AOT-drift watcher routes through the alert engine (ONE
            # lifecycle for every watcher); with the engine off this
            # degrades to the legacy one-shot warning
            _alerts.raise_alert(
                f"aot-drift-{name}",
                message=(
                    f"step report {name!r}: compiled memory footprint "
                    f"{drift['measured_bytes']:.3e} B drifts "
                    f"{drift['drift_frac'] * 100:+.1f}% from the AOT budget "
                    f"{drift['aot_bytes']:.3e} B ({drift['aot_source']}) — "
                    "beyond the 10% tolerance; re-derive the AOT report or "
                    "find the regression."
                ),
                severity="warning",
                value=drift["drift_frac"],
            )
        else:
            _alerts.resolve(f"aot-drift-{name}")
    return report


def prometheus_dump(path: Optional[str] = None) -> Optional[str]:
    """Prometheus text exposition of the live registry; writes to ``path``
    (default ``<out_dir>/metrics.prom``) when an out_dir is configured.
    Returns the text, or None while dormant."""
    st = _STATE
    if st is None:
        return None
    text = prometheus_text(st.registry)
    target = path or (os.path.join(st.out_dir, "metrics.prom") if st.out_dir else None)
    if target is not None:
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
        with open(target, "w") as f:
            f.write(text)
    return text


def dashboard(title: str = "vescale_tpu telemetry") -> Optional[str]:
    return _dashboard(_STATE.registry, title) if _STATE is not None else None
