"""Live HBM attribution + OOM flight recorder.

The runtime half of memory observability (memory_report.py holds the pure
measurement functions):

  1. **Tag registry** — the runtime wiring (darray factories, optimizer
     state init, pipe-engine activation stash, checkpoint load buffers,
     train-step outputs) tags the arrays it creates, so
     ``jax.live_arrays()`` can be bucketed by OWNER (``params`` /
     ``optimizer_state`` / ``grads`` / ``activation_stash`` /
     ``checkpoint_buffers`` / ``untagged``) instead of being an anonymous
     byte pile.  Registration is weakref-based: tagging never extends an
     array's lifetime.
  2. **Per-step sampling** — ``telemetry.record_step`` drives ``on_step``:
     device memory gauges (host-RSS fallback on CPU), per-tag byte gauges,
     a bounded history ring, and **leak detection** (N consecutive steps of
     monotonic ``untagged`` growth warns once per run of growth).
  3. **Flight recorder** — ``flight_recorder(step_fn)`` dumps a forensic
     JSON bundle on RESOURCE_EXHAUSTED (census, device stats, last step
     report, history, registry snapshot, ndtimeline tail) so an OOM at
     step 40k is a file, not a bare stack trace.  ``dump_now()`` is the
     on-demand path.

Gating contract (same as the rest of telemetry): while dormant the module
hooks ``tag_array`` / ``tag_tree`` ARE the no-op functions (``_noop_tag_array``
/ ``_noop_tag_tree`` — tests assert identity), there is no tracker, no
registry dict, no lock.  Callers must use ``memtrack.tag_array(...)``
attribute access, never ``from memtrack import tag_array`` (which would
freeze the dormant binding).
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

import jax

from . import alerts as _alerts

from .memory_report import device_memory_stats, live_array_census

__all__ = [
    "TAGS",
    "MemoryTracker",
    "activate",
    "deactivate",
    "is_active",
    "get_tracker",
    "tagged",
    "tag_array",
    "tag_tree",
    "flight_recorder",
    "dump_now",
    "maybe_dump_oom",
]

# the tag taxonomy (docs/observability.md) — anything else is user-defined
TAGS = (
    "params",
    "optimizer_state",
    "grads",
    "activation_stash",
    "checkpoint_buffers",
    "untagged",
)

_TRACKER: Optional["MemoryTracker"] = None
_TAG_STACK: List[str] = []  # ambient tag for factory hooks (tagged())


# ------------------------------------------------------------ dormant hooks
# These ARE the module's public hooks while dormant: a single no-op call per
# factory/init site.  activate() rebinds the module attributes to the live
# tracker's methods; deactivate() restores these exact references (the
# gating test asserts identity against them).
def _noop_tag_array(x, tag: Optional[str] = None):
    return x


def _noop_tag_tree(tree, tag: Optional[str] = None):
    return tree


tag_array = _noop_tag_array
tag_tree = _noop_tag_tree


@contextlib.contextmanager
def tagged(tag: str):
    """Ambient-tag scope: darray factory calls inside the block register
    their results under ``tag``.  Harmless while dormant (one list append)."""
    _TAG_STACK.append(tag)
    try:
        yield
    finally:
        _TAG_STACK.pop()


def is_active() -> bool:
    return _TRACKER is not None


def get_tracker() -> Optional["MemoryTracker"]:
    return _TRACKER


# ----------------------------------------------------------------- tracker
class MemoryTracker:
    """Everything a live memory-tracking run owns (created ONLY by
    ``telemetry.init(memtrack=True)``; its absence IS the off state)."""

    def __init__(
        self,
        history: int = 16,
        leak_steps: int = 5,
        census_interval: int = 1,
        top_k: int = 10,
    ):
        if census_interval < 1:
            raise ValueError(f"census_interval must be >= 1, got {census_interval}")
        self.history_len = history
        self.leak_steps = leak_steps
        self.census_interval = census_interval
        self.top_k = top_k
        # id(arr) -> (weakref, tag); the weakref callback evicts the entry,
        # so the registry tracks LIVE arrays only and never extends lifetimes.
        # RLock, not Lock: a GC cycle collection triggered by the insert
        # allocation can run an eviction callback SYNCHRONOUSLY on the same
        # thread while tag_array holds the lock — a plain Lock would deadlock
        self._entries: Dict[int, tuple] = {}
        self._lock = threading.RLock()
        self.history: List[Dict[str, Any]] = []
        self._last_untagged: Optional[int] = None
        self._growth_run = 0
        self._leak_warned = False
        self.dumps_written = 0

    # ------------------------------------------------------------ tagging
    def tag_array(self, x, tag: Optional[str] = None):
        """Register one array (or DArray — its physical leaf) under ``tag``
        (or the ambient ``tagged()`` scope).  Tracers, non-weakrefable and
        tagless arrays are skipped silently: tagging is advisory."""
        tag = tag or (_TAG_STACK[-1] if _TAG_STACK else None)
        if tag is None:
            return x
        arr = getattr(x, "_data", x)  # DArray -> physical jax.Array
        if isinstance(arr, jax.core.Tracer):
            return x
        if not hasattr(arr, "nbytes"):
            return x
        key = id(arr)
        try:
            ref = weakref.ref(arr, lambda _r, k=key, s=self: s._evict(k))
        except TypeError:
            return x
        with self._lock:
            self._entries[key] = (ref, tag)
        return x

    def _evict(self, key: int) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def tag_tree(self, tree, tag: Optional[str] = None):
        """Register every array leaf of a pytree (DArray leaves register
        their physical arrays — DArray is a pytree node)."""
        for leaf in jax.tree_util.tree_leaves(tree):
            self.tag_array(leaf, tag)
        return tree

    def tag_of(self, arr) -> Optional[str]:
        entry = self._entries.get(id(arr))
        if entry is None:
            return None
        ref, tag = entry
        return tag if ref() is arr else None  # guard id() reuse

    @property
    def num_tagged(self) -> int:
        return len(self._entries)

    # ----------------------------------------------------------- sampling
    def census(self) -> Dict[str, Any]:
        return live_array_census(self.tag_of, top_k=self.top_k)

    def on_step(self, step: int, registry) -> Optional[Dict[str, Any]]:
        """Per-step sample (driven by ``telemetry.record_step``): gauges
        into the registry, a history entry, leak detection.  Returns the
        compact memory record merged into the steps.jsonl line (None on
        skipped census-interval steps)."""
        if step % self.census_interval != 0:
            return None
        devices = device_memory_stats()
        census = self.census()
        tag_bytes = {t: b["bytes"] for t, b in census["tags"].items()}

        for i, d in enumerate(devices):
            if d["source"] == "host_rss":
                if d["bytes_in_use"] is not None:
                    registry.gauge("mem_host_rss_bytes").set(d["bytes_in_use"])
                if d["peak_bytes_in_use"] is not None:
                    registry.gauge("mem_host_peak_rss_bytes").set(d["peak_bytes_in_use"])
                continue
            # keyed by DEVICE ID, not list position: a device whose stats
            # transiently fail is skipped by device_memory_stats, and a
            # positional key would shift every later device's gauge onto the
            # wrong chip (the exact misattribution this layer exists to avoid)
            dev = d.get("id", i)
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if d.get(key) is not None:
                    registry.gauge(f"mem_device{dev}_{key}").set(d[key])
        for tag, nbytes in tag_bytes.items():
            registry.gauge(f"mem_tag_{tag}_bytes").set(nbytes)
        registry.gauge("mem_live_arrays").set(census["live_arrays"])

        # leak detection: N consecutive steps of strictly monotonic untagged
        # growth.  Warn once per run of growth — a real leak keeps growing,
        # and re-warning every step would bury the signal it carries.
        untagged = int(tag_bytes.get("untagged", 0))
        if self._last_untagged is not None and untagged > self._last_untagged:
            self._growth_run += 1
        else:
            if self._leak_warned:
                # growth broke: the leak episode is over — resolve the
                # alert (a no-op while the engine is dormant)
                _alerts.resolve("mem-leak")
            self._growth_run = 0
            self._leak_warned = False
        self._last_untagged = untagged
        registry.gauge("mem_untagged_growth_steps").set(self._growth_run)
        if self._growth_run >= self.leak_steps and not self._leak_warned:
            self._leak_warned = True
            registry.counter("mem_leak_warnings_total").inc()
            # the leak watcher routes through the alert engine (one
            # lifecycle, /alerts visibility, ALERT timeline span); with the
            # engine off this degrades to the legacy one-shot warning
            _alerts.raise_alert(
                "mem-leak",
                message=(
                    f"memtrack: untagged live-array bytes grew monotonically "
                    f"for {self._growth_run} consecutive steps (now "
                    f"{untagged} B) — possible leak.  telemetry.dump_now() "
                    "writes a tagged census to identify the owner."
                ),
                severity="warning",
                value=float(untagged),
            )

        sample = {
            "step": step,
            "ts": time.time(),
            "devices": devices,
            "tags": tag_bytes,
            "live_arrays": census["live_arrays"],
            "untagged_growth_steps": self._growth_run,
        }
        self.history.append(sample)
        if len(self.history) > self.history_len:
            del self.history[: len(self.history) - self.history_len]
        return {
            "tags": tag_bytes,
            "devices": [
                {k: d.get(k) for k in ("source", "bytes_in_use", "peak_bytes_in_use")}
                for d in devices
            ],
            "untagged_growth_steps": self._growth_run,
        }

    # ----------------------------------------------------- flight recorder
    def flight_record(self, reason: str, exception: Optional[str] = None) -> Dict[str, Any]:
        """Build the forensic bundle (the OOM dump / dump_now payload)."""
        from . import api as _api  # late: api imports this module at top

        st = _api.get_state()
        bundle: Dict[str, Any] = {
            "reason": reason,
            "ts": time.time(),
            "step": st.step if st is not None else None,
            "rank": st.rank if st is not None else None,
            "exception": exception,
            "device_memory": device_memory_stats(),
            "census": self.census(),
            "history": list(self.history),
            "last_step_report": getattr(st, "last_step_report", None),
            "registry": st.registry.snapshot() if st is not None else None,
            "ndtimeline_tail": _ndtimeline_tail(),
        }
        return bundle


def _ndtimeline_tail(n: int = 200) -> Optional[List[Dict[str, Any]]]:
    """Last ``n`` buffered (un-flushed) profiler spans, when the profiler is
    live — the 'what was the run doing' context of an OOM dump."""
    from ..ndtimeline import api as _nd

    if not _nd.is_active():
        return None
    return [
        {
            "metric": s.metric,
            "start": s.start,
            "duration": s.duration,
            "step": s.step,
            "rank": s.rank,
            "tags": s.tags,
        }
        for s in _nd.get_manager().tail(n)
    ]


# --------------------------------------------------------------- gate flips
def activate(
    history: int = 16,
    leak_steps: int = 5,
    census_interval: int = 1,
    top_k: int = 10,
) -> MemoryTracker:
    """Create the tracker and bind the live hooks (called by
    ``telemetry.init``; do not call directly unless you know why)."""
    global _TRACKER, tag_array, tag_tree
    _TRACKER = MemoryTracker(
        history=history,
        leak_steps=leak_steps,
        census_interval=census_interval,
        top_k=top_k,
    )
    tag_array = _TRACKER.tag_array
    tag_tree = _TRACKER.tag_tree
    return _TRACKER


def deactivate() -> None:
    """Drop the tracker and restore the no-op hook references."""
    global _TRACKER, tag_array, tag_tree
    _TRACKER = None
    tag_array = _noop_tag_array
    tag_tree = _noop_tag_tree


# ------------------------------------------------------------------- dumps
def _is_oom(exc: BaseException) -> bool:
    """Does this exception look like a device-memory exhaustion?  String
    match on purpose: jax surfaces XLA's RESOURCE_EXHAUSTED through several
    exception types (XlaRuntimeError is a plain RuntimeError subclass)."""
    s = str(exc)
    return (
        "RESOURCE_EXHAUSTED" in s
        or "resource exhausted" in s.lower()
        or "out of memory" in s.lower()
    )


def dump_now(
    path: Optional[str] = None,
    reason: str = "manual",
    exception: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """Write a flight-recorder bundle on demand.  Default path is
    ``<out_dir>/flight_record_<step>_<seq>.json`` when telemetry has an
    out_dir (in-memory runs just get the dict back).  None while dormant."""
    tracker = _TRACKER
    if tracker is None:
        return None
    from . import api as _api

    bundle = tracker.flight_record(reason, exception=exception)
    st = _api.get_state()
    if path is None and st is not None and st.out_dir is not None:
        tracker.dumps_written += 1
        path = os.path.join(
            st.out_dir, f"flight_record_{bundle['step']}_{tracker.dumps_written}.json"
        )
    if path is not None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(bundle, f, indent=2, default=str)
        bundle["path"] = path
    if st is not None:
        st.registry.counter("mem_flight_records_total").inc()
    return bundle


def maybe_dump_oom(exc: BaseException) -> Optional[Dict[str, Any]]:
    """The shared OOM-dump handler: if tracking is live and ``exc`` looks
    like memory exhaustion, write a flight record.  Never raises — the dump
    must not mask the OOM the caller is about to re-raise.  Call this from
    any step-shaped wrapper's except block (train.py and pipe/engine.py
    do); ``flight_recorder`` is the decorator form."""
    if _TRACKER is None or not _is_oom(exc):
        return None
    try:
        return dump_now(reason=f"oom:{type(exc).__name__}", exception=repr(exc))
    except Exception:
        return None


def flight_recorder(fn: Callable) -> Callable:
    """Wrap a train/pipe step so RESOURCE_EXHAUSTED writes a forensic dump
    before propagating.  Dormant runs pay one try/except frame; the dump
    itself never masks the original exception (a failing dump is swallowed
    — the OOM is the signal that must reach the caller)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            maybe_dump_oom(e)
            raise

    return wrapped
