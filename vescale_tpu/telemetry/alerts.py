"""Declarative SLO alerting over the time-series store — ONE lifecycle for
every watcher in the repo.

Before this module each watcher invented its own one-shot warn path
(memtrack's leak ``warnings.warn``, the AOT-drift warning, the calibration
staleness ``_warn_once``, the watchdog's stderr print, the straggler
detector's silent report).  Now there is one engine with one lifecycle —

    ok -> pending -> firing -> resolved (-> ok)

— and every transition emits the SAME three signals: a ``record_event``
line on steps.jsonl, an ndtimeline ``alert`` span (firings render on the
merged Perfetto fleet timeline next to the step/request spans that caused
them), and registry counters (``alerts_fired_total`` + per-rule).  The
``/alerts`` ops endpoint serves :func:`payload` (FROZEN schema v1 —
``ALERTS_FIELDS``, the ROUTER_FIELDS contract: fields only ever added).

Rule grammar (docs/observability.md "Alerting"):

  * :class:`ThresholdRule` — ``reduce(metric, window_s, reducer) OP
    threshold``, held ``for_s`` seconds before firing (pending in
    between).
  * :class:`BurnRateRule` — the SRE multi-window multi-burn-rate
    formulation over an error-budget spec: burn(window) =
    avg(metric over window) / slo; the rule fires when BOTH the long and
    the short window of any configured (long_s, short_s, factor) pair
    burn faster than ``factor`` (the short window gates alert RESET —
    a long window alone would keep paging hours after recovery).
  * :class:`TrendRule` — least-squares slope per second over a window
    crosses a limit (queue-depth growth, page-pool drain, mem growth).
  * :class:`ZScoreRule` — |latest - window mean| / window std exceeds z
    (loss anomalies, grad-norm spikes) with a ``min_samples`` floor.
  * :class:`ManualRule` — code-driven: :func:`raise_alert` /
    :func:`resolve` walk the same lifecycle for watchers whose condition
    lives outside the store (watchdog stall, stale calibration table,
    AOT drift, bench staleness).

Gating contract (memtrack precedent): dormant hooks ``evaluate`` /
``raise_alert`` / ``resolve`` ARE the module no-op references (identity-
asserted).  The dormant ``raise_alert`` degrades to the legacy one-shot
``warnings.warn`` (once per rule name per process) so un-instrumented
runs still surface watcher signals — that latch is THE sanctioned
warn-once path (lint VSC207 flags any other).
"""

from __future__ import annotations

import collections
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ALERTS_SCHEMA_VERSION",
    "ALERTS_FIELDS",
    "SEVERITIES",
    "Rule",
    "ThresholdRule",
    "BurnRateRule",
    "TrendRule",
    "ZScoreRule",
    "ManualRule",
    "AlertEngine",
    "activate",
    "deactivate",
    "is_active",
    "get_engine",
    "evaluate",
    "raise_alert",
    "resolve",
    "payload",
    "digest",
    "serve_rule_pack",
    "train_rule_pack",
    "fleet_rule_pack",
    "bench_rule_pack",
    "burn_windows_from_env",
    "clear_fallback_warned",
]

ALERTS_SCHEMA_VERSION = 1
# the frozen /alerts v1 field set (ROUTER_FIELDS contract: only ever ADD)
ALERTS_FIELDS = frozenset(
    (
        "schema_version",
        "active",
        "rules",
        "firing",
        "pending",
        "history",
        "counts",
        "uptime_s",
    )
)
# per-rule row of the /alerts feed (frozen with the outer schema)
ALERTS_RULE_FIELDS = frozenset(
    (
        "kind",
        "severity",
        "state",
        "since_s",
        "value",
        "message",
        "fired_count",
    )
)

SEVERITIES = ("info", "warning", "critical")

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


# -------------------------------------------------------------------- rules
class Rule:
    """Base declarative rule: subclasses implement :meth:`condition`
    returning ``(condition_holds, observed_value)`` over the store.

    ``for_s`` holds the rule PENDING that long before firing;
    ``resolve_for_s`` is the symmetric hysteresis on the way down — a
    firing rule must stay below threshold that long before ok-ing (the
    anti-flap hold consumers like the autoscaler key off: a noisy burn
    signal that dips for one sample must not read as recovered)."""

    kind = "rule"

    def __init__(self, name: str, severity: str = "warning",
                 message: str = "", for_s: float = 0.0,
                 resolve_for_s: float = 0.0):
        if severity not in SEVERITIES:
            raise ValueError(f"rule {name!r}: severity must be one of {SEVERITIES}")
        if for_s < 0:
            raise ValueError(f"rule {name!r}: for_s must be >= 0")
        if resolve_for_s < 0:
            raise ValueError(f"rule {name!r}: resolve_for_s must be >= 0")
        self.name = name
        self.severity = severity
        self.message = message
        self.for_s = float(for_s)
        self.resolve_for_s = float(resolve_for_s)

    def condition(self, store, now: float) -> Tuple[bool, Optional[float]]:
        raise NotImplementedError


class ThresholdRule(Rule):
    """``reduce(metric, window_s, reducer) OP threshold`` held ``for_s``."""

    kind = "threshold"

    def __init__(self, name: str, metric: str, op: str, threshold: float,
                 window_s: float = 60.0, reducer: str = "last",
                 for_s: float = 0.0, severity: str = "warning",
                 message: str = "", resolve_for_s: float = 0.0):
        super().__init__(name, severity=severity, message=message, for_s=for_s,
                         resolve_for_s=resolve_for_s)
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: op must be one of {sorted(_OPS)}")
        self.metric = metric
        self.op = op
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.reducer = reducer

    def condition(self, store, now: float) -> Tuple[bool, Optional[float]]:
        v = store.reduce(self.metric, self.window_s, self.reducer, now=now)
        if v is None:
            return False, None
        return _OPS[self.op](v, self.threshold), v


class BurnRateRule(Rule):
    """Multi-window multi-burn-rate SLO rule (the SRE formulation).

    ``burn(window) = avg(metric over window) / slo`` — for a latency SLO
    the metric is a percentile series (``serve_ttft_seconds:p99``) and the
    slo is the budget in the same unit; burn 1.0 means exactly spending
    budget, burn N means exhausting it N times faster.  ``windows`` is a
    sequence of ``(long_s, short_s, factor)`` pairs; the rule's condition
    holds when ANY pair has BOTH windows burning above its factor (the
    short window makes the alert reset promptly after recovery)."""

    kind = "burn_rate"

    def __init__(self, name: str, metric: str, slo: float,
                 windows: Sequence[Tuple[float, float, float]] = (
                     (3600.0, 300.0, 14.4),
                     (21600.0, 1800.0, 6.0),
                 ),
                 for_s: float = 0.0, severity: str = "critical",
                 message: str = "", resolve_for_s: float = 0.0):
        super().__init__(name, severity=severity, message=message, for_s=for_s,
                         resolve_for_s=resolve_for_s)
        if slo <= 0:
            raise ValueError(f"rule {name!r}: slo must be > 0, got {slo}")
        if not windows:
            raise ValueError(f"rule {name!r}: need at least one window pair")
        self.metric = metric
        self.slo = float(slo)
        self.windows = tuple((float(l), float(s), float(f)) for l, s, f in windows)

    def burn(self, store, span_s: float, now: float) -> Optional[float]:
        v = store.reduce(self.metric, span_s, "avg", now=now)
        return None if v is None else v / self.slo

    def condition(self, store, now: float) -> Tuple[bool, Optional[float]]:
        worst: Optional[float] = None
        hold = False
        for long_s, short_s, factor in self.windows:
            bl = self.burn(store, long_s, now)
            bs = self.burn(store, short_s, now)
            for b in (bl, bs):
                if b is not None and (worst is None or b > worst):
                    worst = b
            if bl is not None and bs is not None and bl > factor and bs > factor:
                hold = True
        return hold, worst


class TrendRule(Rule):
    """Least-squares slope per second over ``window_s`` beyond a limit.
    ``direction="up"`` fires on slope > ``slope_per_s``; ``"down"`` on
    slope < ``-slope_per_s`` (pass the magnitude, not a signed value)."""

    kind = "trend"

    def __init__(self, name: str, metric: str, slope_per_s: float,
                 window_s: float = 120.0, direction: str = "up",
                 min_samples: int = 4, for_s: float = 0.0,
                 severity: str = "warning", message: str = "",
                 resolve_for_s: float = 0.0):
        super().__init__(name, severity=severity, message=message, for_s=for_s,
                         resolve_for_s=resolve_for_s)
        if direction not in ("up", "down"):
            raise ValueError(f"rule {name!r}: direction must be 'up' or 'down'")
        if slope_per_s <= 0:
            raise ValueError(f"rule {name!r}: slope_per_s is a magnitude, > 0")
        self.metric = metric
        self.slope_per_s = float(slope_per_s)
        self.window_s = float(window_s)
        self.direction = direction
        self.min_samples = int(min_samples)

    def condition(self, store, now: float) -> Tuple[bool, Optional[float]]:
        win = store.window(self.metric, self.window_s, now=now)
        if len(win) < self.min_samples:
            return False, None
        from .timeseries import _reduce_samples

        slope = _reduce_samples(win, "slope")
        if slope is None:
            return False, None
        if self.direction == "up":
            return slope > self.slope_per_s, slope
        return slope < -self.slope_per_s, slope


class ZScoreRule(Rule):
    """|latest - window mean| / window std exceeds ``z`` — the anomaly
    shape (loss spikes, grad-norm blowups).  Needs ``min_samples`` in the
    window and a non-degenerate std; ``direction`` limits which side
    counts (``"up"``/``"down"``/``"both"``)."""

    kind = "zscore"

    def __init__(self, name: str, metric: str, z: float = 4.0,
                 window_s: float = 300.0, min_samples: int = 8,
                 direction: str = "both", for_s: float = 0.0,
                 severity: str = "warning", message: str = "",
                 resolve_for_s: float = 0.0):
        super().__init__(name, severity=severity, message=message, for_s=for_s,
                         resolve_for_s=resolve_for_s)
        if direction not in ("up", "down", "both"):
            raise ValueError(f"rule {name!r}: bad direction {direction!r}")
        self.metric = metric
        self.z = float(z)
        self.window_s = float(window_s)
        self.min_samples = int(min_samples)
        self.direction = direction

    def condition(self, store, now: float) -> Tuple[bool, Optional[float]]:
        win = store.window(self.metric, self.window_s, now=now)
        if len(win) < self.min_samples:
            return False, None
        vals = [v for _, v in win]
        latest = vals[-1]
        base = vals[:-1]  # the latest sample must not dilute its own baseline
        mean = sum(base) / len(base)
        var = sum((v - mean) ** 2 for v in base) / len(base)
        std = var ** 0.5
        if std <= 1e-12:
            return False, 0.0
        score = (latest - mean) / std
        if self.direction == "up":
            return score > self.z, score
        if self.direction == "down":
            return score < -self.z, score
        return abs(score) > self.z, score


class ManualRule(Rule):
    """Code-driven rule: :func:`raise_alert`/:func:`resolve` flip it.  The
    migration target for watchers whose condition lives outside the store
    (watchdog stall, stale calibration table, AOT drift, bench-TPU
    staleness)."""

    kind = "manual"

    def __init__(self, name: str, severity: str = "warning", message: str = ""):
        super().__init__(name, severity=severity, message=message, for_s=0.0)
        self.raised = False
        self.raised_value: Optional[float] = None

    def condition(self, store, now: float) -> Tuple[bool, Optional[float]]:
        return self.raised, self.raised_value


# ------------------------------------------------------------------- engine
class AlertEngine:
    """Rules + lifecycle states + the bounded transition-history ring
    (created ONLY by ``telemetry.init(alerts=True)``; its absence IS the
    off state)."""

    def __init__(self, store=None, history: int = 256,
                 min_eval_interval_s: float = 0.0):
        self.store = store
        self.history: "collections.deque" = collections.deque(maxlen=history)
        self.rules: Dict[str, Rule] = {}
        self._states: Dict[str, Dict] = {}
        self._packs: set = set()
        self._lock = threading.RLock()
        self.min_eval_interval_s = float(min_eval_interval_s)
        self._last_eval = 0.0
        self._start = time.time()
        self.counts = {"fired": 0, "resolved": 0, "pending": 0, "evaluations": 0}

    # ------------------------------------------------------------ rule mgmt
    def add_rule(self, rule: Rule) -> Rule:
        """Register (or replace — same name) one rule; its lifecycle state
        starts at ``ok``."""
        with self._lock:
            self.rules[rule.name] = rule
            self._states.setdefault(
                rule.name,
                {"state": "ok", "since": time.time(), "value": None,
                 "message": rule.message, "fired_count": 0},
            )
        return rule

    def arm_pack(self, pack: str, rules: Sequence[Rule]) -> bool:
        """Idempotently install a named rule pack (the serve loop re-arms
        on every construction; only the first arm installs)."""
        with self._lock:
            if pack in self._packs:
                return False
            self._packs.add(pack)
            for r in rules:
                self.add_rule(r)
            return True

    def state_of(self, name: str) -> Optional[Dict]:
        with self._lock:
            st = self._states.get(name)
            return dict(st) if st is not None else None

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(n for n, s in self._states.items()
                          if s["state"] == "firing")

    def pending(self) -> List[str]:
        with self._lock:
            return sorted(n for n, s in self._states.items()
                          if s["state"] == "pending")

    # ------------------------------------------------------------ lifecycle
    def _transition(self, rule: Rule, st: Dict, new_state: str, now: float,
                    value: Optional[float], message: str) -> Dict:
        """One lifecycle edge: dedup is the caller's job (same-state calls
        never reach here).  Emits the event line, the span, the counters,
        and appends the bounded history entry."""
        old = st["state"]
        fired_at = st.get("fired_at")
        st["state"] = new_state
        st["since"] = now
        st["value"] = value
        st["message"] = message or rule.message
        if new_state == "firing":
            st["fired_at"] = now
            st["fired_count"] += 1
        rec = {
            "rule": rule.name,
            "kind": rule.kind,
            "severity": rule.severity,
            "from": old,
            "to": new_state,
            "ts": now,
            "value": value,
            "message": st["message"],
        }
        self.history.append(rec)
        self._emit(rule, rec, fired_at, now)
        return rec

    def _emit(self, rule: Rule, rec: Dict, fired_at: Optional[float],
              now: float) -> None:
        from . import api as _tel

        to = rec["to"]
        if to == "pending":
            self.counts["pending"] += 1
            _tel.count("alerts_pending_total")
        elif to == "firing":
            self.counts["fired"] += 1
            _tel.count("alerts_fired_total")
            _tel.count(f"alerts_fired_total_{_safe(rule.name)}")
        elif rec["from"] == "firing":  # firing -> ok IS the resolve edge
            self.counts["resolved"] += 1
            _tel.count("alerts_resolved_total")
            _tel.count(f"alerts_resolved_total_{_safe(rule.name)}")
        _tel.set_gauge("alerts_firing", float(len(self.firing())))
        # per-rule state gauge for the prom export: 0 ok / 1 pending /
        # 2 firing — a scraper's view of the lifecycle without JSON
        _tel.set_gauge(f"alerts_state_{_safe(rule.name)}",
                       {"ok": 0.0, "pending": 1.0, "firing": 2.0}[to])
        _tel.record_event(
            "alert",
            rule=rec["rule"],
            severity=rec["severity"],
            transition=f"{rec['from']}->{to}",
            value=rec["value"],
            message=rec["message"],
        )
        self._emit_span(rule, rec, fired_at, now)

    def _emit_span(self, rule: Rule, rec: Dict, fired_at: Optional[float],
                   now: float) -> None:
        """The timeline presence: a point span at each transition, plus —
        on resolve — one span COVERING the firing episode, so Perfetto
        shows the alert as a bar spanning exactly the degraded region of
        the step/request lanes under it."""
        from ..ndtimeline import api as _nd

        if not _nd.is_active():
            return
        from ..ndtimeline.predefined import ALERT

        mgr = _nd.get_manager()
        tags = {
            "rule": rec["rule"],
            "severity": rec["severity"],
            "transition": f"{rec['from']}->{rec['to']}",
            "value": rec["value"],
        }
        # stamp with the step that JUST finished — the loops advance the
        # profiler counter before record_step() evaluates us, so the
        # counter already names the (empty) next step; the newest buffered
        # span's step is the finished step (step_span_summary's own rule)
        tail = mgr.tail(1)
        step = tail[-1].step if tail else mgr.step
        mgr.record(ALERT, now, 0.0, tags=tags, step=step)
        if rec["from"] == "firing" and fired_at is not None:
            mgr.record(
                ALERT,
                fired_at,
                max(0.0, now - fired_at),
                tags={**tags, "episode": rec["rule"]},
                step=step,
            )

    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """Walk every rule's condition over the store and advance the
        lifecycles.  Returns the transitions this call produced (empty on
        quiet evaluations and rate-limited calls)."""
        now = time.time() if now is None else now
        out: List[Dict] = []
        with self._lock:
            if self.min_eval_interval_s > 0 and \
                    (now - self._last_eval) < self.min_eval_interval_s:
                return out
            self._last_eval = now
            self.counts["evaluations"] += 1
            for name, rule in list(self.rules.items()):
                st = self._states[name]
                try:
                    hold, value = (
                        rule.condition(self.store, now)
                        if self.store is not None or rule.kind == "manual"
                        else (False, None)
                    )
                except Exception:  # a broken rule must not kill the loop
                    hold, value = False, None
                cur = st["state"]
                if hold:
                    if cur == "ok":
                        if rule.for_s > 0:
                            st["pending_since"] = now
                            out.append(self._transition(
                                rule, st, "pending", now, value, rule.message))
                        else:
                            out.append(self._transition(
                                rule, st, "firing", now, value, rule.message))
                    elif cur == "pending":
                        if (now - st.get("pending_since", now)) >= rule.for_s:
                            out.append(self._transition(
                                rule, st, "firing", now, value, rule.message))
                        else:
                            st["value"] = value
                    else:  # already firing: dedup, just refresh the value
                        # re-holding resets the resolve hysteresis clock
                        st.pop("below_since", None)
                        st["value"] = value
                else:
                    if cur == "pending":
                        out.append(self._transition(
                            rule, st, "ok", now, value, rule.message))
                    elif cur == "firing":
                        # resolve_for_s hysteresis: the rule must stay
                        # below threshold that long before the resolve
                        # edge — one quiet sample must not un-page
                        if rule.resolve_for_s > 0:
                            below = st.setdefault("below_since", now)
                            if (now - below) < rule.resolve_for_s:
                                st["value"] = value
                                continue
                        st.pop("below_since", None)
                        out.append(self._transition(
                            rule, st, "ok", now, value, rule.message))
        return out

    # ------------------------------------------------------- manual alerts
    def raise_alert(self, name: str, message: str = "",
                    severity: str = "warning",
                    value: Optional[float] = None) -> Optional[Dict]:
        """Fire (or refresh) a :class:`ManualRule` NOW — no store, no
        evaluate() round trip; the watchdog's stall must not wait for the
        next poll.  Deduped: raising an already-firing alert only updates
        its value/message."""
        now = time.time()
        with self._lock:
            rule = self.rules.get(name)
            if rule is None:
                rule = self.add_rule(ManualRule(name, severity=severity,
                                                message=message))
            if not isinstance(rule, ManualRule):
                raise TypeError(
                    f"alert {name!r} is a declarative {rule.kind} rule; "
                    "raise_alert only drives manual rules"
                )
            rule.raised = True
            rule.raised_value = value
            st = self._states[name]
            if st["state"] == "firing":
                st["value"] = value
                if message:
                    st["message"] = message
                return None
            return self._transition(rule, st, "firing", now, value,
                                    message or rule.message)

    def resolve(self, name: str, message: str = "") -> Optional[Dict]:
        """Resolve a manual alert (no-op when unknown or not firing)."""
        now = time.time()
        with self._lock:
            rule = self.rules.get(name)
            if rule is None or not isinstance(rule, ManualRule):
                return None
            rule.raised = False
            st = self._states[name]
            if st["state"] not in ("pending", "firing"):
                return None
            return self._transition(rule, st, "ok", now, rule.raised_value,
                                    message or rule.message)

    # ------------------------------------------------------------- payload
    def snapshot(self) -> Dict:
        """The `/alerts` body — FROZEN schema v1 (``ALERTS_FIELDS``)."""
        now = time.time()
        with self._lock:
            rules = {}
            for name, rule in self.rules.items():
                st = self._states[name]
                row = {
                    "kind": rule.kind,
                    "severity": rule.severity,
                    "state": st["state"],
                    "since_s": round(now - st["since"], 6),
                    "value": st["value"],
                    "message": st["message"],
                    "fired_count": st["fired_count"],
                }
                assert set(row) == ALERTS_RULE_FIELDS  # frozen at source
                rules[name] = row
            out = {
                "schema_version": ALERTS_SCHEMA_VERSION,
                "active": True,
                "rules": rules,
                "firing": sorted(n for n, s in self._states.items()
                                 if s["state"] == "firing"),
                "pending": sorted(n for n, s in self._states.items()
                                  if s["state"] == "pending"),
                "history": list(self.history)[-64:],
                "counts": dict(self.counts),
                "uptime_s": round(now - self._start, 6),
            }
        assert set(out) == ALERTS_FIELDS  # the freeze, enforced at source
        return out


def _safe(name: str) -> str:
    return "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


# --------------------------------------------------------------- gate flips
_ENGINE: Optional[AlertEngine] = None

# legacy fallback latch — THE one sanctioned warn-once path (VSC207 exempts
# this module); keyed by rule name, cleared by clear_fallback_warned()
_FALLBACK_WARNED: set = set()
_FALLBACK_LOCK = threading.Lock()


def clear_fallback_warned() -> None:
    """Reset the dormant-mode warn-once latch (tests)."""
    with _FALLBACK_LOCK:
        _FALLBACK_WARNED.clear()


# These ARE the module's public hooks while dormant (identity-asserted).
# The dormant raise_alert keeps the legacy operator signal: one
# warnings.warn per rule name per process, so a watcher tripping without
# telemetry still prints SOMETHING.
def _noop_evaluate(now: Optional[float] = None) -> List[Dict]:
    return []


def _fallback_raise_alert(name: str, message: str = "",
                          severity: str = "warning",
                          value: Optional[float] = None) -> None:
    with _FALLBACK_LOCK:
        if name in _FALLBACK_WARNED:
            return None
        _FALLBACK_WARNED.add(name)
    warnings.warn(f"[alert:{name}] {message}" if message else f"[alert:{name}]",
                  stacklevel=3)
    return None


def _noop_resolve(name: str, message: str = "") -> None:
    return None


evaluate = _noop_evaluate
raise_alert = _fallback_raise_alert
resolve = _noop_resolve


def is_active() -> bool:
    return _ENGINE is not None


def get_engine() -> Optional[AlertEngine]:
    return _ENGINE


def activate(store=None, history: int = 256,
             min_eval_interval_s: float = 0.0) -> AlertEngine:
    """Create the engine and bind the live hooks (called by
    ``telemetry.init``; do not call directly unless you know why)."""
    global _ENGINE, evaluate, raise_alert, resolve
    _ENGINE = AlertEngine(store=store, history=history,
                          min_eval_interval_s=min_eval_interval_s)
    evaluate = _ENGINE.evaluate
    raise_alert = _ENGINE.raise_alert
    resolve = _ENGINE.resolve
    return _ENGINE


def deactivate() -> None:
    """Drop the engine and restore the dormant hook references."""
    global _ENGINE, evaluate, raise_alert, resolve
    _ENGINE = None
    evaluate = _noop_evaluate
    raise_alert = _fallback_raise_alert
    resolve = _noop_resolve


def payload() -> Dict:
    """The `/alerts` endpoint provider — works DORMANT (a probe must not
    require a metrics pipeline): same frozen schema, ``active: false``."""
    eng = _ENGINE
    if eng is not None:
        return eng.snapshot()
    out = {
        "schema_version": ALERTS_SCHEMA_VERSION,
        "active": False,
        "rules": {},
        "firing": [],
        "pending": [],
        "history": [],
        "counts": {"fired": 0, "resolved": 0, "pending": 0, "evaluations": 0},
        "uptime_s": 0.0,
    }
    assert set(out) == ALERTS_FIELDS
    return out


def digest() -> Dict:
    """The inline alert summary the `/router` (v4) and `/fleet` (v3)
    feeds carry: ``{"active", "firing", "pending"}`` — sorted rule names
    only, no states/history (that is `/alerts`).  Dormant-safe."""
    eng = _ENGINE
    if eng is None:
        return {"active": False, "firing": [], "pending": []}
    return {"active": True, "firing": eng.firing(), "pending": eng.pending()}


# --------------------------------------------------------------- rule packs
def burn_windows_from_env() -> Optional[Sequence[Tuple[float, float, float]]]:
    """Parse ``VESCALE_ALERTS_BURN_WINDOWS`` — ``"long:short:factor"``
    triples, comma-separated (seconds, seconds, burn multiple), e.g.
    ``"3600:300:14.4,21600:1800:6"``.  None when unset; a malformed value
    raises (a silently-dropped paging rule is worse than a crash at
    arm time)."""
    from ..analysis import envreg

    raw = envreg.get_str("VESCALE_ALERTS_BURN_WINDOWS")
    if not raw:
        return None
    out = []
    for part in raw.split(","):
        pieces = part.strip().split(":")
        if len(pieces) != 3:
            raise ValueError(
                f"VESCALE_ALERTS_BURN_WINDOWS: expected long:short:factor, got {part!r}"
            )
        long_s, short_s, factor = (float(p) for p in pieces)
        out.append((long_s, short_s, factor))
    return tuple(out)


def _burn_for_s_from_env() -> float:
    from ..analysis import envreg

    return envreg.get_float("VESCALE_ALERTS_BURN_FOR_S") or 0.0


def serve_rule_pack(slo_ttft_s: Optional[float] = None,
                    burn_windows: Optional[Sequence[Tuple[float, float, float]]] = None,
                    burn_for_s: Optional[float] = None,
                    ) -> List[Rule]:
    """The default serve-replica pack (armed by ``run_serve_resilient``
    when the engine is live).  The burn-rate rule needs a TTFT SLO — with
    ``slo_ttft_s`` unset/0 it is omitted (the rest still arm).
    ``burn_windows``/``burn_for_s`` default from the
    ``VESCALE_ALERTS_BURN_WINDOWS`` / ``VESCALE_ALERTS_BURN_FOR_S`` knobs
    (then the Google-SRE pairs / 0)."""
    rules: List[Rule] = [
        ThresholdRule(
            "serve-shed-rate", "serve_shed_rate", ">", 0.1,
            window_s=60.0, reducer="avg", for_s=0.0, severity="warning",
            message="admission control is shedding >10% of submissions",
        ),
        TrendRule(
            "serve-queue-depth-trend", "serve_queue_depth", slope_per_s=0.5,
            window_s=120.0, direction="up", severity="warning",
            message="request queue depth growing — demand exceeds decode capacity",
        ),
        ThresholdRule(
            "serve-goodput-collapse", "serve_goodput_fraction", "<", 0.5,
            window_s=120.0, reducer="avg", for_s=0.0, severity="critical",
            message="less than half of sampled tokens reach completed requests",
        ),
        TrendRule(
            "serve-page-pool-drain", "serve_free_pages", slope_per_s=0.2,
            window_s=120.0, direction="down", severity="warning",
            message="KV page pool draining — exhaustion (and eviction storms) ahead",
        ),
    ]
    if slo_ttft_s:
        rules.insert(0, BurnRateRule(
            "serve-ttft-slo-burn", "serve_ttft_seconds:p99", float(slo_ttft_s),
            windows=(burn_windows or burn_windows_from_env()
                     or ((3600.0, 300.0, 14.4), (21600.0, 1800.0, 6.0))),
            for_s=burn_for_s if burn_for_s is not None else _burn_for_s_from_env(),
            severity="critical",
            message="p99 TTFT burning the SLO error budget across both windows",
        ))
    return rules


def train_rule_pack() -> List[Rule]:
    """The default train-loop pack (armed by ``train.py`` when the engine
    is live)."""
    return [
        ZScoreRule(
            "train-loss-anomaly", "train_loss", z=6.0, window_s=600.0,
            min_samples=16, direction="up", severity="critical",
            message="loss spiked beyond 6 sigma of its recent window",
        ),
        ZScoreRule(
            "train-grad-norm-spike", "train_grad_norm", z=6.0, window_s=600.0,
            min_samples=16, direction="up", severity="warning",
            message="gradient norm spiked beyond 6 sigma of its recent window",
        ),
        TrendRule(
            "train-step-time-regression", "train_step_time_seconds:p50",
            slope_per_s=0.001, window_s=600.0, direction="up",
            severity="warning",
            message="median step time trending up — throughput regression",
        ),
        TrendRule(
            "train-mem-growth", "mem_tag_untagged_bytes", slope_per_s=1024.0,
            window_s=600.0, direction="up", severity="warning",
            message="untagged live-array bytes trending up — possible leak",
        ),
    ]


def bench_rule_pack() -> List[Rule]:
    """The bench orchestrator's pack (armed by bench.py's CPU-fallback
    child): ``bench_tpu_record_age_days`` is set ONLY when a run emits a
    stale last-known-TPU record, so any sample at all fires the rule —
    the down-since-round-N TPU tunnel shows up next to every other
    alert instead of only inside a JSON line."""
    return [
        ThresholdRule(
            "bench-tpu-stale", "bench_tpu_record_age_days", ">=", 0.0,
            window_s=3600.0, reducer="last", severity="warning",
            message="bench ran on the CPU fallback rung; TPU perf record is stale",
        ),
    ]


def fleet_rule_pack(slo_ttft_s: Optional[float] = None,
                    burn_windows: Optional[Sequence[Tuple[float, float, float]]] = None,
                    burn_for_s: Optional[float] = None,
                    ) -> List[Rule]:
    """The router-side pack: fleet-scope rules over the AGGREGATED
    ``fleet_timeline_*`` gauges FleetObservability publishes — a
    fleet-wide SLO burn fires here even when every replica looks healthy
    alone."""
    rules: List[Rule] = [
        ThresholdRule(
            "fleet-shed-rate", "fleet_timeline_shed_rate", ">", 0.1,
            window_s=60.0, reducer="avg", severity="warning",
            message="fleet-wide shed rate above 10%",
        ),
        ThresholdRule(
            "fleet-no-healthy-replicas", "fleet_timeline_healthy_replicas",
            "<", 1.0, window_s=30.0, reducer="last", severity="critical",
            message="no dispatchable replica left in the fleet",
        ),
    ]
    if slo_ttft_s:
        rules.insert(0, BurnRateRule(
            "fleet-ttft-slo-burn", "fleet_timeline_ttft_p99_s",
            float(slo_ttft_s),
            windows=(burn_windows or burn_windows_from_env()
                     or ((3600.0, 300.0, 14.4), (21600.0, 1800.0, 6.0))),
            for_s=burn_for_s if burn_for_s is not None else _burn_for_s_from_env(),
            severity="critical",
            message="fleet p99 TTFT burning the SLO error budget across both windows",
        ))
    return rules
