"""Memory measurement primitives — device stats, host fallback, live-array
census, AOT-budget drift.

Pure functions only: the stateful half (tag registry, per-step sampling,
leak detection, the OOM flight recorder) lives in memtrack.py.  Everything
here degrades instead of raising — memory observability must never be the
thing that kills a run.

Byte accounting convention: a sharded ``jax.Array``'s ``nbytes`` is the
LOGICAL global size, so census buckets report logical bytes (what the
training code owns), while ``device_memory_stats`` reports physical
per-device HBM (what the allocator sees).  The two agree only on a
single-device run; both are in the flight-recorder bundle on purpose.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

import jax

__all__ = [
    "host_rss_bytes",
    "host_peak_rss_bytes",
    "device_memory_stats",
    "live_array_census",
    "aot_memory_budget",
    "compare_with_aot",
]


def host_rss_bytes() -> Optional[int]:
    """Current resident-set size of this process (Linux /proc; None where
    unavailable) — the degradation target when ``memory_stats()`` has
    nothing (CPU backend, old jax)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        return None


def host_peak_rss_bytes() -> Optional[int]:
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device allocator stats (bytes_in_use / peak / limit).  On
    backends where ``memory_stats()`` returns None or raises (CPU, older
    jax), degrades to ONE host-RSS entry (``source: "host_rss"``) rather
    than zero entries — the gauges must always have something to say."""
    out: List[Dict[str, Any]] = []
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        out.append(
            {
                "device": str(d),
                "id": d.id,
                "platform": d.platform,
                "source": "device",
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
        )
    if not out:
        out.append(
            {
                "device": "host",
                "platform": jax.devices()[0].platform,
                "source": "host_rss",
                "bytes_in_use": host_rss_bytes(),
                "peak_bytes_in_use": host_peak_rss_bytes(),
                "bytes_limit": None,
            }
        )
    return out


def live_array_census(
    tag_of: Callable[[Any], Optional[str]], top_k: int = 10
) -> Dict[str, Any]:
    """Bucket ``jax.live_arrays()`` by owner tag.

    ``tag_of(arr)`` maps one live array to its registered tag or None
    (-> ``untagged``).  Returns per-tag ``{count, bytes}`` buckets plus the
    ``top_k`` largest arrays — the first thing to read in an OOM dump."""
    buckets: Dict[str, Dict[str, int]] = {}
    largest: List[Dict[str, Any]] = []
    n = 0
    for arr in jax.live_arrays():
        try:
            if arr.is_deleted():
                continue
            nbytes = int(arr.nbytes)
            shape, dtype = tuple(arr.shape), str(arr.dtype)
        except Exception:
            continue
        n += 1
        tag = tag_of(arr) or "untagged"
        b = buckets.setdefault(tag, {"count": 0, "bytes": 0})
        b["count"] += 1
        b["bytes"] += nbytes
        largest.append({"shape": shape, "dtype": dtype, "bytes": nbytes, "tag": tag})
    largest.sort(key=lambda e: e["bytes"], reverse=True)
    return {"live_arrays": n, "tags": buckets, "top_arrays": largest[:top_k]}


# ----------------------------------------------------------- AOT drift
def aot_memory_budget(aot: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Extract the per-device memory budget from an ``AOT_*_REPORT.json``
    document.  Prefers the measured fp32-compile bytes (same basis as a
    fresh CPU/AOT compile of the step); falls back to the bf16-basis total.
    None when the document carries neither."""
    measured = (aot.get("measured") or {}).get("per_device_bytes_fp32_compile")
    if measured:
        return {"bytes": float(measured), "source": "measured.per_device_bytes_fp32_compile"}
    bf16 = (aot.get("bf16_basis_memory") or {}).get("total_bytes")
    if bf16:
        return {"bytes": float(bf16), "source": "bf16_basis_memory.total_bytes"}
    return None


def compare_with_aot(
    report: Dict[str, Any],
    aot: Any,
    tolerance: float = 0.10,
) -> Optional[Dict[str, Any]]:
    """Diff a compiled step report's memory footprint against the matching
    AOT report's budget; ``exceeds_tolerance`` flags drift beyond
    ``tolerance`` (default 10%) in either direction — a regression OR a
    budget that is no longer honest.

    ``aot`` may be a loaded AOT document (dict) or a path to one.  Returns
    None (never raises) when either side lacks a usable byte count."""
    if isinstance(aot, str):
        try:
            with open(aot) as f:
                aot = json.load(f)
        except Exception:
            return None
    if not isinstance(aot, dict):
        return None
    budget = aot_memory_budget(aot)
    measured = report.get("peak_bytes")
    if budget is None or not measured:
        return None
    drift = (float(measured) - budget["bytes"]) / budget["bytes"]
    return {
        "aot_bytes": budget["bytes"],
        "aot_source": budget["source"],
        "measured_bytes": float(measured),
        "drift_frac": drift,
        "tolerance": tolerance,
        "exceeds_tolerance": abs(drift) > tolerance,
        "components": {
            k: report.get(k)
            for k in ("argument_bytes", "output_bytes", "temp_bytes",
                      "alias_bytes", "generated_code_bytes")
        },
    }
