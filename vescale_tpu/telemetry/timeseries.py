"""Metric time-series store — bounded ring history with tiered downsampling.

The registry (registry.py) answers "what is the value NOW"; this module
answers "what was it over the last N seconds" — the substrate the alert
engine (alerts.py) evaluates burn rates, trends and anomalies over, and
the history feed ROADMAP item 6's autoscaler consumes next.

Design (docs/observability.md "Time-series store"):

  * **Fixed-cadence sampling.**  The train loop, the serve loop and the
    FleetRouter call :func:`sample` at their natural boundaries (train
    step, decode step, poll); the store accepts at most one sample per
    ``cadence_s`` regardless of call rate, so a 2 kHz decode loop and a
    1 Hz poll loop produce the same densities.  One accepted sample
    snapshots EVERY registry metric: counters and gauges verbatim, each
    histogram as ``name:p50/p95/p99`` value series plus ``name:count`` /
    ``name:sum`` cumulative series.
  * **Tiered downsampling.**  Each series keeps ``tiers`` rings of
    ``base_len`` (ts, value) pairs.  Tier 0 holds raw samples; every
    ``tier_factor`` tier-k samples collapse into ONE tier-(k+1) sample
    (mean for value series, last for cumulative series — a counter's
    bucket endpoint is what rate math needs).  With the defaults
    (1 s cadence, 512 samples, factor 8, 3 tiers) tier 2 retains ~9 h of
    history in a few KiB per metric; appends stay O(1).
  * **Windowed reducers.**  :meth:`TimeSeriesStore.reduce` evaluates
    ``last/min/max/avg/delta/rate/slope/count/std/pNN`` over the finest
    tier covering the requested span — ``rate`` and ``delta`` are
    endpoint-exact on cumulative series because of the last-value bucket
    aggregation above.

Gating contract (memtrack.py precedent): while dormant the module hook
``sample`` IS ``_noop_sample`` (tests assert identity) — no store, no
rings, no clock reads beyond the caller's.  ``telemetry.init()`` activates
(``timeseries=True`` default; ``VESCALE_TIMESERIES`` gates the loops'
arming), ``telemetry.shutdown()`` restores the no-op reference.  Callers
must use ``timeseries.sample(...)`` attribute access, never
``from timeseries import sample``.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Series",
    "TimeSeriesStore",
    "REDUCERS",
    "activate",
    "deactivate",
    "is_active",
    "get_store",
    "sample",
]

REDUCERS = (
    "last", "min", "max", "avg", "delta", "rate", "slope", "count", "std",
)  # plus "pNN" percentiles, e.g. "p99"

_CUMULATIVE = "cumulative"  # counter-shaped: bucket-aggregate = last value
_VALUE = "value"            # gauge/percentile-shaped: bucket-aggregate = mean


class _Ring:
    """Preallocated (ts, value) ring — O(1) append, chronological read."""

    __slots__ = ("_ts", "_val", "_pos", "_filled", "cap")

    def __init__(self, cap: int):
        self.cap = cap
        self._ts = [0.0] * cap
        self._val = [0.0] * cap
        self._pos = 0
        self._filled = 0

    def append(self, ts: float, val: float) -> None:
        self._ts[self._pos] = ts
        self._val[self._pos] = val
        self._pos = (self._pos + 1) % self.cap
        self._filled = min(self._filled + 1, self.cap)

    def __len__(self) -> int:
        return self._filled

    def items(self) -> List[Tuple[float, float]]:
        """Chronological (ts, value) pairs."""
        n, p, cap = self._filled, self._pos, self.cap
        if n < cap:
            idx = range(n)
        else:
            idx = [(p + i) % cap for i in range(cap)]
        return [(self._ts[i], self._val[i]) for i in idx]

    def earliest_ts(self) -> Optional[float]:
        if self._filled == 0:
            return None
        i = 0 if self._filled < self.cap else self._pos
        return self._ts[i]


class Series:
    """One metric's tiered history.  ``kind`` decides bucket aggregation:
    ``cumulative`` keeps the bucket's LAST value (endpoint-exact rates),
    ``value`` keeps the bucket mean."""

    __slots__ = ("name", "kind", "tiers", "tier_factor", "_buckets")

    def __init__(self, name: str, kind: str, base_len: int, tier_factor: int,
                 tiers: int):
        self.name = name
        self.kind = kind
        self.tier_factor = tier_factor
        self.tiers = [_Ring(base_len) for _ in range(tiers)]
        # per-tier open bucket: [n, sum, last_ts, last_val]
        self._buckets = [[0, 0.0, 0.0, 0.0] for _ in range(tiers)]

    def append(self, ts: float, val: float) -> None:
        self._append_tier(0, ts, val)

    def _append_tier(self, k: int, ts: float, val: float) -> None:
        self.tiers[k].append(ts, val)
        if k + 1 >= len(self.tiers):
            return
        b = self._buckets[k]
        b[0] += 1
        b[1] += val
        b[2], b[3] = ts, val
        if b[0] >= self.tier_factor:
            agg = b[3] if self.kind == _CUMULATIVE else b[1] / b[0]
            n_ts = b[2]
            b[0], b[1] = 0, 0.0
            self._append_tier(k + 1, n_ts, agg)

    def window(self, span_s: float, now: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """Chronological samples within the last ``span_s`` seconds, read
        from the FINEST tier whose retained history covers the span (the
        coarsest tier answers spans beyond every ring's reach)."""
        now = time.time() if now is None else now
        cut = now - span_s
        chosen = None
        for ring in self.tiers:
            e = ring.earliest_ts()
            if e is not None and e <= cut:
                chosen = ring
                break
        if chosen is None:
            # no tier's history covers the span (short run, or a span
            # beyond every ring's reach): answer from the tier reaching
            # furthest back — finest wins ties, so a young series serves
            # ALL its samples instead of an empty coarse ring
            best = None
            for ring in self.tiers:
                e = ring.earliest_ts()
                if e is not None and (best is None or e < best):
                    best, chosen = e, ring
            if chosen is None:
                return []
        return [(t, v) for t, v in chosen.items() if t >= cut]

    def retained_samples(self) -> int:
        return sum(len(r) for r in self.tiers)


def _reduce_samples(samples: List[Tuple[float, float]], reducer: str
                    ) -> Optional[float]:
    """Apply one named reducer to chronological (ts, value) samples."""
    if not samples:
        return None
    vals = [v for _, v in samples]
    if reducer == "last":
        return vals[-1]
    if reducer == "min":
        return min(vals)
    if reducer == "max":
        return max(vals)
    if reducer == "avg":
        return sum(vals) / len(vals)
    if reducer == "count":
        return float(len(vals))
    if reducer == "delta":
        return vals[-1] - vals[0]
    if reducer == "rate":
        if len(samples) < 2:
            return None
        dt = samples[-1][0] - samples[0][0]
        return (vals[-1] - vals[0]) / dt if dt > 0 else None
    if reducer == "slope":
        # least-squares slope per second over the window
        if len(samples) < 2:
            return None
        t0 = samples[0][0]
        ts = [t - t0 for t, _ in samples]
        mt = sum(ts) / len(ts)
        mv = sum(vals) / len(vals)
        den = sum((t - mt) ** 2 for t in ts)
        if den <= 0:
            return None
        return sum((t - mt) * (v - mv) for t, v in zip(ts, vals)) / den
    if reducer == "std":
        mv = sum(vals) / len(vals)
        return math.sqrt(sum((v - mv) ** 2 for v in vals) / len(vals))
    if reducer.startswith("p") and reducer[1:].isdigit():
        q = int(reducer[1:]) / 100.0
        xs = sorted(vals)
        return xs[max(0, math.ceil(len(xs) * q) - 1)]
    raise ValueError(f"unknown reducer {reducer!r} (choose from {REDUCERS} or pNN)")


class TimeSeriesStore:
    """Everything a live time-series run owns (created ONLY by
    ``telemetry.init(timeseries=True)``; its absence IS the off state)."""

    def __init__(
        self,
        registry,
        cadence_s: float = 1.0,
        base_len: int = 512,
        tier_factor: int = 8,
        tiers: int = 3,
    ):
        if cadence_s < 0:
            raise ValueError(f"cadence_s must be >= 0, got {cadence_s}")
        if base_len < 2 or tier_factor < 2 or tiers < 1:
            raise ValueError(
                f"bad store shape: base_len={base_len} tier_factor={tier_factor} "
                f"tiers={tiers}"
            )
        self.registry = registry
        self.cadence_s = float(cadence_s)
        self.base_len = int(base_len)
        self.tier_factor = int(tier_factor)
        self.num_tiers = int(tiers)
        self._series: Dict[str, Series] = {}
        self._last_sample = 0.0
        self.samples_taken = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- append
    def _get(self, name: str, kind: str) -> Series:
        s = self._series.get(name)
        if s is None:
            s = self._series[name] = Series(
                name, kind, self.base_len, self.tier_factor, self.num_tiers
            )
        return s

    def sample(self, kind: Optional[str] = None, now: Optional[float] = None,
               force: bool = False) -> bool:
        """Snapshot the registry into the rings; at most one accepted
        sample per ``cadence_s`` (``force`` bypasses — tests and the
        router's explicit poll cadence).  Returns whether a sample was
        taken.  ``kind`` is advisory (the caller's boundary name); the
        cadence limiter is global so overlapping loops do not double the
        density."""
        now = time.time() if now is None else now
        with self._lock:
            if not force and (now - self._last_sample) < self.cadence_s:
                return False
            self._last_sample = now
            snap = self.registry.snapshot()
            for name, v in snap["counters"].items():
                self._get(name, _CUMULATIVE).append(now, float(v))
            for name, v in snap["gauges"].items():
                self._get(name, _VALUE).append(now, float(v))
            for name, h in snap["histograms"].items():
                for q in ("p50", "p95", "p99"):
                    if q in h:
                        self._get(f"{name}:{q}", _VALUE).append(now, float(h[q]))
                self._get(f"{name}:count", _CUMULATIVE).append(now, float(h["count"]))
                self._get(f"{name}:sum", _CUMULATIVE).append(now, float(h["sum"]))
            self.samples_taken += 1
            return True

    # ------------------------------------------------------------- queries
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, metric: str) -> Optional[Series]:
        with self._lock:
            return self._series.get(metric)

    def window(self, metric: str, span_s: float, now: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        s = self.series(metric)
        return s.window(span_s, now) if s is not None else []

    def reduce(self, metric: str, span_s: float, reducer: str = "last",
               now: Optional[float] = None) -> Optional[float]:
        """One reduced number over the window; None when the series is
        absent or too thin for the reducer."""
        return _reduce_samples(self.window(metric, span_s, now), reducer)

    def retained_samples(self) -> int:
        with self._lock:
            return sum(s.retained_samples() for s in self._series.values())

    def stats(self) -> Dict[str, float]:
        """The ``timeseries:`` dashboard block feed."""
        with self._lock:
            return {
                "series": len(self._series),
                "samples_taken": self.samples_taken,
                "retained_samples": sum(
                    s.retained_samples() for s in self._series.values()
                ),
                "cadence_s": self.cadence_s,
                "tiers": self.num_tiers,
                "base_len": self.base_len,
                "tier_factor": self.tier_factor,
            }


# --------------------------------------------------------------- gate flips
_STORE: Optional[TimeSeriesStore] = None


# This IS the module's public hook while dormant (memtrack contract): the
# loops call it per step/poll and an un-instrumented run must pay one
# no-op frame, nothing else.  activate() rebinds; deactivate() restores
# this exact reference (the gating test asserts identity).
def _noop_sample(kind: Optional[str] = None, now: Optional[float] = None,
                 force: bool = False) -> bool:
    return False


sample = _noop_sample


def is_active() -> bool:
    return _STORE is not None


def get_store() -> Optional[TimeSeriesStore]:
    return _STORE


def activate(
    registry,
    cadence_s: float = 1.0,
    base_len: int = 512,
    tier_factor: int = 8,
    tiers: int = 3,
) -> TimeSeriesStore:
    """Create the store and bind the live hook (called by
    ``telemetry.init``; do not call directly unless you know why)."""
    global _STORE, sample
    _STORE = TimeSeriesStore(
        registry,
        cadence_s=cadence_s,
        base_len=base_len,
        tier_factor=tier_factor,
        tiers=tiers,
    )
    sample = _STORE.sample
    return _STORE


def deactivate() -> None:
    """Drop the store and restore the no-op hook reference."""
    global _STORE, sample
    _STORE = None
    sample = _noop_sample
