"""Metrics registry — counters, gauges, rolling-window histograms.

The runtime-facing half of the telemetry subsystem: ``train.py``, the pipe
engine, the DistributedOptimizer and the checkpoint layer feed one
process-global ``MetricsRegistry`` (created ONLY by ``telemetry.init()`` —
see api.py for the zero-overhead gate).  Exporters (exporters.py) read a
consistent ``snapshot()``.

Design notes:
  - Histograms keep a bounded rolling window (deque) for percentiles plus
    monotonic count/sum totals, so a long run's p50/p95/p99 track RECENT
    behavior (a warmup-step outlier ages out) while rates stay exact.
  - Percentiles use the nearest-rank method (the same convention as
    ndtimeline/parser_handler.py — int(n*q) would report the max at small n).
  - Thread-safe: handlers may observe from io/streamer threads.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Union

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry"]

Number = Union[int, float]


class Counter:
    """Monotonic counter (Prometheus 'counter' semantics)."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: Number = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-value gauge (may go up or down)."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v: Number) -> None:
        self._value = float(v)

    def inc(self, n: Number = 1) -> None:
        self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Rolling-window histogram: percentiles over the last ``window``
    observations, exact monotonic count/sum over the whole run."""

    __slots__ = ("name", "help", "window", "_values", "_pos", "_filled", "_count", "_sum", "_lock")

    def __init__(self, name: str, help: str = "", window: int = 1024):
        if window < 1:
            raise ValueError(f"histogram {name}: window must be >= 1")
        self.name = name
        self.help = help
        self.window = window
        self._values: List[float] = [0.0] * window  # preallocated ring
        self._pos = 0
        self._filled = 0
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: Number) -> None:
        v = float(v)
        with self._lock:
            self._values[self._pos] = v
            self._pos = (self._pos + 1) % self.window
            self._filled = min(self._filled + 1, self.window)
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _sorted_window(self):
        """(sorted recent values, count, sum) under the lock."""
        with self._lock:
            n = self._filled
            xs = sorted(self._values[:n] if n < self.window else self._values)
            return xs, self._count, self._sum

    @staticmethod
    def _nearest_rank(xs: List[float], q: float) -> float:
        return xs[max(0, math.ceil(len(xs) * q) - 1)]

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile of the rolling window; None when empty."""
        xs, _, _ = self._sorted_window()
        return self._nearest_rank(xs, q) if xs else None

    def snapshot(self) -> Dict[str, float]:
        xs, count, total = self._sorted_window()
        out: Dict[str, float] = {"count": count, "sum": total, "window": len(xs)}
        if xs:
            for label, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
                out[label] = self._nearest_rank(xs, q)
            out["min"] = xs[0]
            out["max"] = xs[-1]
            out["mean"] = (total / count) if count else 0.0
        return out


class MetricsRegistry:
    """Get-or-create registry of named metrics.  A name is bound to one
    metric kind for the registry's lifetime — re-requesting it with another
    kind raises instead of silently shadowing."""

    def __init__(self, default_window: int = 1024):
        self.default_window = default_window
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}, "
                    f"requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "", window: Optional[int] = None) -> Histogram:
        return self._get_or_create(
            name, Histogram, help=help, window=window or self.default_window
        )

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, Dict]:
        """Consistent read of every metric, grouped by kind."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Dict] = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][name] = m.snapshot()
        return out
