"""Straggler detection over cross-rank merged ndtimeline spans.

A ``StragglerDetector`` is a span handler (the ``NDtimelineStreamer``
handler interface: ``handler(List[Span])``) that accumulates per-(metric,
rank) durations and flags ranks whose latency exceeds a configurable
multiple of the cross-rank MEDIAN for that metric.  Median (not mean): one
slow rank must not drag the baseline toward itself — on an 8-rank job a
2x-slow rank shifts the mean by 12.5% but the median not at all.

It also consumes the offline shape: ``update_from_merged`` takes the
``parser_handler.merge_ranks`` rollup, so post-hoc analysis of raw span
dumps uses the same thresholds as the live collector path.

Clock skew: duration-based flagging is skew-immune (a duration is one
host's clock differenced against itself), but START-time comparisons —
"rank 3 enters every step 8 ms after everyone else", the upstream-cause
view of a straggler — are only meaningful after skew correction.  Feed the
detector the offsets from ``telemetry.trace.estimate_clock_offsets`` via
:meth:`set_clock_offsets`; :meth:`lag_report` then compares SKEW-CORRECTED
per-(metric, step) start times across ranks instead of assuming
synchronized host clocks, and refuses to flag lags smaller than the
estimate's own residual (a lag claim below the measurement noise floor is
not a signal).
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

__all__ = ["StragglerDetector"]


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class StragglerDetector:
    """Flags per-metric slow ranks.

    ``threshold``: a rank is a straggler for a metric when its mean recent
    duration exceeds ``threshold * median`` of all ranks' means (and the
    absolute excess tops ``min_excess_ms`` — microsecond-scale jitter on
    microsecond-scale metrics is not a health signal).
    ``window``: per-(metric, rank) rolling sample count.
    ``min_ranks``: below this many reporting ranks there is no population to
    compare against; nothing is flagged.
    ``lag_threshold_ms``: minimum mean start-time lag behind the cross-rank
    median for :meth:`lag_report` to flag a rank (raised to the clock-sync
    residual when that is larger).
    """

    def __init__(
        self,
        threshold: float = 1.5,
        window: int = 256,
        min_ranks: int = 2,
        min_excess_ms: float = 0.0,
        lag_threshold_ms: float = 1.0,
    ):
        if threshold <= 1.0:
            raise ValueError(f"threshold must be > 1.0, got {threshold}")
        self.threshold = float(threshold)
        self.window = int(window)
        self.min_ranks = int(min_ranks)
        self.min_excess_ms = float(min_excess_ms)
        self.lag_threshold_ms = float(lag_threshold_ms)
        # metric -> rank -> rolling durations (ms)
        self._samples: Dict[str, Dict[int, collections.deque]] = {}
        # metric -> step -> rank -> earliest skew-corrected start (s);
        # bounded to the last `window` steps per metric
        self._starts: Dict[str, "collections.OrderedDict" ] = {}
        self._offsets_s: Dict[int, float] = {}  # rank -> clock offset vs rank 0
        self._residual_ms = 0.0
        self._lock = threading.Lock()
        self.spans_seen = 0

    # ---------------------------------------------------------- clock sync
    def set_clock_offsets(self, clock) -> None:
        """Arm skew correction with cross-rank clock offsets: a
        ``telemetry.trace.ClockSync`` or a ``{rank: offset_seconds}``
        mapping (offsets relative to rank 0, as ``estimate_clock_offsets``
        reports them).  Spans already ingested are NOT re-aligned — arm the
        offsets before attaching the detector to a streamer."""
        from .trace import ClockSync

        with self._lock:
            if isinstance(clock, ClockSync):
                self._offsets_s = {r: clock.offset_s(r) for r in range(len(clock.offsets_us))}
                self._residual_ms = clock.residual_us / 1e3
            else:
                self._offsets_s = {int(r): float(o) for r, o in dict(clock).items()}
                self._residual_ms = 0.0

    def _aligned_start(self, span) -> float:
        return span.start - self._offsets_s.get(span.rank, 0.0)

    # -------------------------------------------------------------- feeds
    def __call__(self, spans) -> None:
        """Streamer/flush handler: ingest a span batch."""
        with self._lock:
            for s in spans:
                dq = self._samples.setdefault(s.metric, {}).setdefault(
                    s.rank, collections.deque(maxlen=self.window)
                )
                dq.append(s.duration * 1e3)
                self.spans_seen += 1
                steps = self._starts.setdefault(s.metric, collections.OrderedDict())
                cell = steps.setdefault(int(s.step), {})
                t = self._aligned_start(s)
                cell[s.rank] = min(cell.get(s.rank, t), t)
                while len(steps) > self.window:
                    steps.popitem(last=False)
        self.publish_alerts()

    def update_from_merged(self, merged: Dict[tuple, Dict]) -> None:
        """Ingest a ``parser_handler.merge_ranks`` rollup: ``{(step, metric):
        {"per_rank_ms": {rank: total_ms}, ...}}`` — each (step, rank) total
        counts as one sample."""
        with self._lock:
            for (_step, metric), row in merged.items():
                for rank, ms in row.get("per_rank_ms", {}).items():
                    dq = self._samples.setdefault(metric, {}).setdefault(
                        int(rank), collections.deque(maxlen=self.window)
                    )
                    dq.append(float(ms))
                    self.spans_seen += 1
        self.publish_alerts()

    # ------------------------------------------------------------ queries
    def rank_means(self, metric: str) -> Dict[int, float]:
        with self._lock:
            per_rank = self._samples.get(metric, {})
            return {r: sum(dq) / len(dq) for r, dq in per_rank.items() if dq}

    def report(self, metric: Optional[str] = None) -> List[Dict]:
        """Flagged stragglers, worst ratio first.  Each entry:
        ``{metric, rank, mean_ms, median_ms, ratio}``."""
        with self._lock:
            metrics = [metric] if metric is not None else list(self._samples)
        out: List[Dict] = []
        for m in metrics:
            means = self.rank_means(m)
            if len(means) < self.min_ranks:
                continue
            med = _median(list(means.values()))
            if med <= 0.0:
                continue
            for rank, mean in means.items():
                if mean > self.threshold * med and (mean - med) >= self.min_excess_ms:
                    out.append(
                        {
                            "metric": m,
                            "rank": rank,
                            "mean_ms": mean,
                            "median_ms": med,
                            "ratio": mean / med,
                        }
                    )
        out.sort(key=lambda e: e["ratio"], reverse=True)
        return out

    def lag_report(self, metric: Optional[str] = None) -> List[Dict]:
        """Start-time stragglers: ranks that ENTER a metric's region late
        relative to the cross-rank median of SKEW-CORRECTED start times,
        averaged over the retained steps.  A rank busy exactly as long as
        its peers but consistently starting late points at an upstream
        cause (slow input pipeline, late collective exit) that duration
        ratios cannot see.  Flags mean lags above ``lag_threshold_ms`` OR
        the clock-sync residual, whichever is larger — below the residual
        the 'lag' is indistinguishable from clock noise.  Entries:
        ``{metric, rank, mean_lag_ms, steps}``, worst first."""
        floor = max(self.lag_threshold_ms, self._residual_ms)
        with self._lock:
            metrics = [metric] if metric is not None else list(self._starts)
            snap = {
                m: {step: dict(cell) for step, cell in self._starts.get(m, {}).items()}
                for m in metrics
            }
        out: List[Dict] = []
        for m, steps in snap.items():
            lags: Dict[int, List[float]] = {}
            for cell in steps.values():
                if len(cell) < self.min_ranks:
                    continue
                med = _median(list(cell.values()))
                for rank, t in cell.items():
                    lags.setdefault(rank, []).append((t - med) * 1e3)
            for rank, ls in lags.items():
                mean_lag = sum(ls) / len(ls)
                if mean_lag > floor:
                    out.append(
                        {"metric": m, "rank": rank, "mean_lag_ms": mean_lag, "steps": len(ls)}
                    )
        out.sort(key=lambda e: e["mean_lag_ms"], reverse=True)
        return out

    def publish_alerts(self) -> None:
        """Route the straggler findings through the alert engine (one
        lifecycle, /alerts visibility, ALERT timeline span) — the
        previously-silent watcher's migration.  Only acts while the engine
        is live: a dormant run keeps the old report()/summary() pull
        model, no new warnings."""
        from . import alerts as _alerts

        if not _alerts.is_active():
            return
        flagged = self.report()
        lagged = self.lag_report()
        if flagged or lagged:
            worst = (
                flagged[0]["ratio"] if flagged else lagged[0]["mean_lag_ms"]
            )
            _alerts.raise_alert(
                "straggler-lag", message=self.summary(), severity="warning",
                value=float(worst),
            )
        else:
            _alerts.resolve("straggler-lag")

    def healthy(self) -> bool:
        # both straggler shapes gate health: duration outliers AND
        # skew-corrected start-time lags (summary() prints both; automation
        # reacting to healthy() must see what summary() names)
        return not self.report() and not self.lag_report()

    def summary(self) -> str:
        flagged = self.report()
        lagged = self.lag_report()
        if not flagged and not lagged:
            return "stragglers: none"
        lines = ["stragglers:"]
        for e in flagged:
            lines.append(
                f"  rank {e['rank']:<4} {e['metric']:<28} "
                f"{e['mean_ms']:.3f} ms vs median {e['median_ms']:.3f} ms "
                f"({e['ratio']:.2f}x)"
            )
        for e in lagged:
            lines.append(
                f"  rank {e['rank']:<4} {e['metric']:<28} "
                f"starts {e['mean_lag_ms']:.3f} ms late (skew-corrected, "
                f"{e['steps']} steps)"
            )
        return "\n".join(lines)
