"""Straggler detection over cross-rank merged ndtimeline spans.

A ``StragglerDetector`` is a span handler (the ``NDtimelineStreamer``
handler interface: ``handler(List[Span])``) that accumulates per-(metric,
rank) durations and flags ranks whose latency exceeds a configurable
multiple of the cross-rank MEDIAN for that metric.  Median (not mean): one
slow rank must not drag the baseline toward itself — on an 8-rank job a
2x-slow rank shifts the mean by 12.5% but the median not at all.

It also consumes the offline shape: ``update_from_merged`` takes the
``parser_handler.merge_ranks`` rollup, so post-hoc analysis of raw span
dumps uses the same thresholds as the live collector path.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

__all__ = ["StragglerDetector"]


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class StragglerDetector:
    """Flags per-metric slow ranks.

    ``threshold``: a rank is a straggler for a metric when its mean recent
    duration exceeds ``threshold * median`` of all ranks' means (and the
    absolute excess tops ``min_excess_ms`` — microsecond-scale jitter on
    microsecond-scale metrics is not a health signal).
    ``window``: per-(metric, rank) rolling sample count.
    ``min_ranks``: below this many reporting ranks there is no population to
    compare against; nothing is flagged.
    """

    def __init__(
        self,
        threshold: float = 1.5,
        window: int = 256,
        min_ranks: int = 2,
        min_excess_ms: float = 0.0,
    ):
        if threshold <= 1.0:
            raise ValueError(f"threshold must be > 1.0, got {threshold}")
        self.threshold = float(threshold)
        self.window = int(window)
        self.min_ranks = int(min_ranks)
        self.min_excess_ms = float(min_excess_ms)
        # metric -> rank -> rolling durations (ms)
        self._samples: Dict[str, Dict[int, collections.deque]] = {}
        self._lock = threading.Lock()
        self.spans_seen = 0

    # -------------------------------------------------------------- feeds
    def __call__(self, spans) -> None:
        """Streamer/flush handler: ingest a span batch."""
        with self._lock:
            for s in spans:
                dq = self._samples.setdefault(s.metric, {}).setdefault(
                    s.rank, collections.deque(maxlen=self.window)
                )
                dq.append(s.duration * 1e3)
                self.spans_seen += 1

    def update_from_merged(self, merged: Dict[tuple, Dict]) -> None:
        """Ingest a ``parser_handler.merge_ranks`` rollup: ``{(step, metric):
        {"per_rank_ms": {rank: total_ms}, ...}}`` — each (step, rank) total
        counts as one sample."""
        with self._lock:
            for (_step, metric), row in merged.items():
                for rank, ms in row.get("per_rank_ms", {}).items():
                    dq = self._samples.setdefault(metric, {}).setdefault(
                        int(rank), collections.deque(maxlen=self.window)
                    )
                    dq.append(float(ms))
                    self.spans_seen += 1

    # ------------------------------------------------------------ queries
    def rank_means(self, metric: str) -> Dict[int, float]:
        with self._lock:
            per_rank = self._samples.get(metric, {})
            return {r: sum(dq) / len(dq) for r, dq in per_rank.items() if dq}

    def report(self, metric: Optional[str] = None) -> List[Dict]:
        """Flagged stragglers, worst ratio first.  Each entry:
        ``{metric, rank, mean_ms, median_ms, ratio}``."""
        with self._lock:
            metrics = [metric] if metric is not None else list(self._samples)
        out: List[Dict] = []
        for m in metrics:
            means = self.rank_means(m)
            if len(means) < self.min_ranks:
                continue
            med = _median(list(means.values()))
            if med <= 0.0:
                continue
            for rank, mean in means.items():
                if mean > self.threshold * med and (mean - med) >= self.min_excess_ms:
                    out.append(
                        {
                            "metric": m,
                            "rank": rank,
                            "mean_ms": mean,
                            "median_ms": med,
                            "ratio": mean / med,
                        }
                    )
        out.sort(key=lambda e: e["ratio"], reverse=True)
        return out

    def healthy(self) -> bool:
        return not self.report()

    def summary(self) -> str:
        flagged = self.report()
        if not flagged:
            return "stragglers: none"
        lines = ["stragglers:"]
        for e in flagged:
            lines.append(
                f"  rank {e['rank']:<4} {e['metric']:<28} "
                f"{e['mean_ms']:.3f} ms vs median {e['median_ms']:.3f} ms "
                f"({e['ratio']:.2f}x)"
            )
        return "\n".join(lines)
