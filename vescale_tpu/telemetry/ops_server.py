"""Live ops endpoints — a tiny stdlib HTTP thread for one serve replica.

The serve loop is observable after the fact (steps.jsonl, Perfetto
traces), but a fleet dispatcher and a liveness probe need answers DURING
the run.  This module serves three read-only endpoints from one
``ThreadingHTTPServer`` daemon thread:

  ``/metrics``   Prometheus text exposition of the live registry (the
                 exact ``exporters.prometheus_text`` output ``/metrics``
                 scrapers expect); 503 while telemetry is dormant.
  ``/healthz``   liveness JSON from the registered health provider
                 (serve/obs.py: watchdog last-beat age, last-decode-step
                 age, queue depth, free slots/pages, drain state).
  ``/router``    the replica's dispatch feed (serve/obs.py: queue depth,
                 TTFT/ITL percentiles, shed rate, capacity, goodput) — the
                 JSON a multi-replica router polls to place requests.  The
                 schema is FROZEN (docs/serving.md): routers are written
                 against it, so fields are only ever added.

Gating matches the telemetry convention: the port knob
``VESCALE_SERVE_OPS_PORT`` is OFF by default — :func:`maybe_start`
returns ``None`` without creating a socket or a thread (the serve loop's
endpoint-off mode is a literal no-op, asserted by tests).  ``0`` binds an
OS-assigned free port (read it back from ``OpsServer.port`` /
``active_server()``); any other value binds that port.  The server binds
localhost only — fleet exposure is a deployment concern (port-forward or
sidecar), not something a library should default to.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

__all__ = ["OpsServer", "maybe_start", "active_server"]

Provider = Callable[[], Dict]

_ACTIVE: Optional["OpsServer"] = None
_LOCK = threading.Lock()


class _Handler(BaseHTTPRequestHandler):
    # the server instance injects itself as .ops on the handler class
    server_version = "vescale-ops/1"

    def log_message(self, fmt, *args):  # no per-request stderr spam
        pass

    def do_GET(self):  # noqa: N802 (stdlib naming)
        ops: "OpsServer" = self.server.ops  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self._metrics()
        elif path in ("/healthz", "/router"):
            self._json(ops.providers.get(path.lstrip("/")))
        else:
            self._send(404, "text/plain; charset=utf-8",
                       "not found (endpoints: /metrics /healthz /router)\n")

    # ------------------------------------------------------------ bodies
    def _metrics(self) -> None:
        from . import api as _tel
        from .exporters import prometheus_text

        reg = _tel.get_registry()
        if reg is None:
            self._send(503, "text/plain; charset=utf-8",
                       "telemetry dormant (call telemetry.init())\n")
            return
        self._send(200, "text/plain; version=0.0.4; charset=utf-8",
                   prometheus_text(reg))

    def _json(self, provider: Optional[Provider]) -> None:
        if provider is None:
            self._send(503, "text/plain; charset=utf-8",
                       "no provider registered for this endpoint\n")
            return
        try:
            body = json.dumps(provider(), sort_keys=True)
        except Exception as e:  # a probe must see the failure, not a hang
            self._send(500, "text/plain; charset=utf-8", f"provider error: {e}\n")
            return
        self._send(200, "application/json", body + "\n")

    def _send(self, code: int, ctype: str, body: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class OpsServer:
    """One replica's ops endpoints on a daemon thread.

        srv = OpsServer(port=0).start()          # 0 = OS-assigned
        srv.register("healthz", health_fn)       # fn() -> JSON-able dict
        srv.register("router", router_fn)
        ... GET http://127.0.0.1:{srv.port}/healthz ...
        srv.stop()
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self.host = host
        self.providers: Dict[str, Provider] = {}
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.ops = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def register(self, endpoint: str, provider: Provider) -> "OpsServer":
        if endpoint not in ("healthz", "router"):
            raise ValueError(f"unknown ops endpoint {endpoint!r}")
        self.providers[endpoint] = provider
        return self

    def start(self) -> "OpsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="vescale-ops-server",
                kwargs={"poll_interval": 0.05}, daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        global _ACTIVE
        t, self._thread = self._thread, None
        if t is not None:
            self._httpd.shutdown()
            t.join(timeout=5.0)
        self._httpd.server_close()
        with _LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    def __enter__(self) -> "OpsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def maybe_start(
    health: Optional[Provider] = None,
    router: Optional[Provider] = None,
    port: Optional[int] = None,
) -> Optional[OpsServer]:
    """The serve loop's gate: start an :class:`OpsServer` when
    ``VESCALE_SERVE_OPS_PORT`` is set (``port`` overrides), else do
    NOTHING — no socket, no thread, return ``None``.  The started server
    is registered as the process's :func:`active_server` so pollers
    launched elsewhere (tests, smoke scripts) can find the bound port."""
    global _ACTIVE
    if port is None:
        from ..analysis import envreg

        port = envreg.get_int("VESCALE_SERVE_OPS_PORT")
    if port is None:
        return None
    srv = OpsServer(port=int(port))
    if health is not None:
        srv.register("healthz", health)
    if router is not None:
        srv.register("router", router)
    srv.start()
    with _LOCK:
        _ACTIVE = srv
    return srv


def active_server() -> Optional[OpsServer]:
    """The most recent :func:`maybe_start` server still running, if any."""
    return _ACTIVE
