"""Live ops endpoints — a tiny stdlib HTTP thread for one serve replica.

The serve loop is observable after the fact (steps.jsonl, Perfetto
traces), but a fleet dispatcher and a liveness probe need answers DURING
the run.  This module serves the replica's network surface from one
``ThreadingHTTPServer`` daemon thread:

  ``/metrics``   Prometheus text exposition of the live registry (the
                 exact ``exporters.prometheus_text`` output ``/metrics``
                 scrapers expect); 503 while telemetry is dormant.
  ``/healthz``   liveness JSON from the registered health provider
                 (serve/obs.py: watchdog last-beat age, last-decode-step
                 age, queue depth, free slots/pages, drain state).
  ``/router``    the replica's dispatch feed (serve/obs.py: queue depth,
                 TTFT/ITL percentiles, shed rate, capacity, goodput) — the
                 JSON the fleet router (serve/router.py) polls to place
                 requests.  The schema is FROZEN (docs/serving.md):
                 routers are written against it, so fields are only ever
                 added (v2 added ``replica_id`` and ``accepting``).
  ``/outcomes``  the replica's terminal-outcome ledger snapshot
                 (serve/fleet.py registers it) — how the fleet router
                 learns completions without a push channel.  Rows linger
                 after drain and echo the dispatch ``tag`` (epoch-fenced
                 since /fleet v5), which is what makes post-crash harvest
                 by a recovered/standby router idempotent: a row the dead
                 leader already journaled terminal, or one from a stale
                 epoch's placement, fails the exact-tag gate and is never
                 double-resolved (serve/journal.py, docs/serving.md
                 router HA).
  ``/submit``    POST: one request into the replica's inbox
                 (serve/fleet.py) — the fleet router's dispatch hop.
  ``/alerts``    the alert-engine lifecycle snapshot (telemetry/alerts.py
                 ``payload`` — FROZEN schema v1: rule states, firing/
                 pending sets, bounded transition history; serves the
                 same schema with ``active: false`` while the engine is
                 dormant, so probes need no gate awareness).
  ``/fleet``     (router-side) the aggregated fleet rollup —
                 ``FleetRouter.start_ops`` registers
                 ``serve/obs.py::FleetObservability.fleet`` on the
                 ROUTER process's own server (frozen schema
                 ``FLEET_FIELDS``, gated by ``VESCALE_FLEET_OPS_PORT``).
                 v5 added ``ha`` — the fenced leader epoch, journal
                 stats, and post-recovery audit a failed-over router
                 re-announces itself with.

Hardening (the fleet front-end depends on it):

  * **Atomic replies.**  Status line, headers and body are assembled into
    ONE buffer and written with a single ``wfile.write`` — a poller
    racing a server shutdown sees either a complete response or a closed
    connection, never a half-written body (regression-tested by a
    concurrent poller hammering ``/router`` across restarts).
  * **Retry-After.**  ``/healthz`` and ``/router`` replies carry a
    ``Retry-After`` header whenever the replica is draining or its
    admission control is currently shedding, so even header-only HTTP
    clients get the backpressure hint (the value is the same
    ``retry_after_s`` the JSON carries, rounded up to whole seconds).
  * **poll_blackhole.**  The faultsim kind of the same name makes a due
    ``/router``/``/healthz`` GET close the connection without writing a
    byte — a deterministic network partition for the fleet router's
    breaker tests (disarmed, the hook is the usual no-op reference).

Gating matches the telemetry convention: the port knob
``VESCALE_SERVE_OPS_PORT`` is OFF by default — :func:`maybe_start`
returns ``None`` without creating a socket or a thread (the serve loop's
endpoint-off mode is a literal no-op, asserted by tests).  ``0`` binds an
OS-assigned free port (read it back from ``OpsServer.port`` /
``active_server()``); any other value binds that port.  The server binds
localhost only — fleet exposure is a deployment concern (port-forward or
sidecar), not something a library should default to.
"""

from __future__ import annotations

import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

__all__ = ["OpsServer", "maybe_start", "active_server"]

Provider = Callable[[], Dict]

_ACTIVE: Optional["OpsServer"] = None
_LOCK = threading.Lock()

# GET endpoints a provider may be registered for.  /fleet is the
# ROUTER-side aggregate feed (serve/obs.py FleetObservability — the fleet
# router's own OpsServer registers it); /alerts is the alert-engine
# lifecycle snapshot (telemetry/alerts.py ``payload`` — frozen schema v1,
# served dormant too).  POSTs: /submit enqueues a request into the serve
# loop's inbox; /control is the rollout channel (``reload``/``status``
# ops — serve/fleet.py registers the provider, serve/autoscale.py's
# RolloutController is the caller).
_GET_ENDPOINTS = ("healthz", "router", "outcomes", "fleet", "alerts")
_POST_ENDPOINTS = ("submit", "control")

_STATUS_TEXT = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    500: "Internal Server Error",
    503: "Service Unavailable",
}


class _Handler(BaseHTTPRequestHandler):
    # the server instance injects itself as .ops on the handler class
    server_version = "vescale-ops/2"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # no per-request stderr spam
        pass

    def do_GET(self):  # noqa: N802 (stdlib naming)
        ops: "OpsServer" = self.server.ops  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path in ("/healthz", "/router"):
            # injected network partition: the poller's GET dies without a
            # byte on the wire (breaker fodder; no-op ref while disarmed)
            from ..resilience import faultsim as _fs

            if _fs.fires("poll_blackhole", ctx=path):
                self.close_connection = True
                return
        if path == "/metrics":
            self._metrics()
        elif path.lstrip("/") in _GET_ENDPOINTS:
            self._json(ops.providers.get(path.lstrip("/")))
        else:
            self._send(404, "text/plain; charset=utf-8",
                       "not found (endpoints: /metrics /healthz /router "
                       "/outcomes /fleet /alerts /submit)\n")

    def do_POST(self):  # noqa: N802 (stdlib naming)
        ops: "OpsServer" = self.server.ops  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/")
        provider = ops.providers.get(path.lstrip("/"))
        if path.lstrip("/") not in _POST_ENDPOINTS or provider is None:
            self._send(404, "text/plain; charset=utf-8",
                       "no POST provider registered for this endpoint\n")
            return
        try:
            n = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(n).decode() or "{}")
        except (ValueError, UnicodeDecodeError) as e:
            self._send(400, "text/plain; charset=utf-8", f"bad request body: {e}\n")
            return
        try:
            body = json.dumps(provider(payload), sort_keys=True)
        except Exception as e:  # the submitter must see the failure
            self._send(500, "text/plain; charset=utf-8", f"provider error: {e}\n")
            return
        self._send(200, "application/json", body + "\n")

    # ------------------------------------------------------------ bodies
    def _metrics(self) -> None:
        from . import api as _tel
        from .exporters import prometheus_text

        reg = _tel.get_registry()
        if reg is None:
            self._send(503, "text/plain; charset=utf-8",
                       "telemetry dormant (call telemetry.init())\n")
            return
        self._send(200, "text/plain; version=0.0.4; charset=utf-8",
                   prometheus_text(reg))

    def _json(self, provider: Optional[Provider]) -> None:
        if provider is None:
            self._send(503, "text/plain; charset=utf-8",
                       "no provider registered for this endpoint\n")
            return
        try:
            payload = provider()
            body = json.dumps(payload, sort_keys=True)
        except Exception as e:  # a probe must see the failure, not a hang
            self._send(500, "text/plain; charset=utf-8", f"provider error: {e}\n")
            return
        self._send(200, "application/json", body + "\n",
                   extra_headers=_retry_after_headers(payload))

    def _send(self, code: int, ctype: str, body: str,
              extra_headers: Optional[Dict[str, str]] = None) -> None:
        """One-buffer response write: the whole reply (status line,
        headers, body) leaves in a single ``write`` so a concurrent
        shutdown can never strand a poller mid-body."""
        data = body.encode()
        head_lines = [
            f"HTTP/1.1 {code} {_STATUS_TEXT.get(code, 'Unknown')}",
            f"Server: {self.server_version}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(data)}",
        ]
        for k, v in (extra_headers or {}).items():
            head_lines.append(f"{k}: {v}")
        head_lines.append("Connection: close")
        head = ("\r\n".join(head_lines) + "\r\n\r\n").encode("latin-1")
        try:
            self.wfile.write(head + data)
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # the poller hung up first; nothing to salvage
        self.close_connection = True


def _retry_after_headers(payload) -> Optional[Dict[str, str]]:
    """The backpressure header contract: a draining or shedding replica's
    `/healthz` and `/router` replies say so in the HTTP layer too."""
    if not isinstance(payload, dict):
        return None
    draining = bool(payload.get("draining"))
    shedding = bool(payload.get("shedding")) or payload.get("accepting") is False
    if not (draining or shedding):
        return None
    try:
        retry = float(payload.get("retry_after_s") or 1.0)
    except (TypeError, ValueError):
        retry = 1.0
    return {"Retry-After": str(max(1, math.ceil(retry)))}


class OpsServer:
    """One replica's ops endpoints on a daemon thread.

        srv = OpsServer(port=0).start()          # 0 = OS-assigned
        srv.register("healthz", health_fn)       # fn() -> JSON-able dict
        srv.register("router", router_fn)
        srv.register("submit", submit_fn)        # fn(payload) -> dict
        ... GET http://127.0.0.1:{srv.port}/healthz ...
        srv.stop()
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self.host = host
        self.providers: Dict[str, Callable] = {}
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.ops = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def register(self, endpoint: str, provider: Callable) -> "OpsServer":
        if endpoint not in _GET_ENDPOINTS + _POST_ENDPOINTS:
            raise ValueError(f"unknown ops endpoint {endpoint!r}")
        self.providers[endpoint] = provider
        return self

    def start(self) -> "OpsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="vescale-ops-server",
                kwargs={"poll_interval": 0.05}, daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        global _ACTIVE
        t, self._thread = self._thread, None
        if t is not None:
            self._httpd.shutdown()
            t.join(timeout=5.0)
        self._httpd.server_close()
        with _LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    def __enter__(self) -> "OpsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def maybe_start(
    health: Optional[Provider] = None,
    router: Optional[Provider] = None,
    port: Optional[int] = None,
    extra: Optional[Dict[str, Callable]] = None,
) -> Optional[OpsServer]:
    """The serve loop's gate: start an :class:`OpsServer` when
    ``VESCALE_SERVE_OPS_PORT`` is set (``port`` overrides), else do
    NOTHING — no socket, no thread, return ``None``.  ``extra`` maps
    additional endpoint names (``outcomes``, ``submit``) to providers.
    The started server is registered as the process's
    :func:`active_server` so pollers launched elsewhere (tests, smoke
    scripts) can find the bound port."""
    global _ACTIVE
    if port is None:
        from ..analysis import envreg

        port = envreg.get_int("VESCALE_SERVE_OPS_PORT")
    if port is None:
        return None
    srv = OpsServer(port=int(port))
    if health is not None:
        srv.register("healthz", health)
    if router is not None:
        srv.register("router", router)
    for name, provider in (extra or {}).items():
        srv.register(name, provider)
    srv.start()
    with _LOCK:
        _ACTIVE = srv
    return srv


def active_server() -> Optional[OpsServer]:
    """The most recent :func:`maybe_start` server still running, if any."""
    return _ACTIVE
