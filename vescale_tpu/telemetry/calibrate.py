"""Measured collective-cost calibration — replace guessed bandwidth factors
with wall-clock data.

Every planning decision in the framework is priced by a cost model: the
redistribution planner's Dijkstra weights (redistribute_plan.py), the
VSC127/128 quant-vs-dense edge competition, ``simulate_schedule``'s stage
costs, and shardcheck's VSC101 materialization pricing all bottom out in the
bandwidth-factor functions of ``collectives.py`` — constants tuned for a TPU
ICI link that have never been checked against a measured step.  Mesh-
TensorFlow (arXiv:1811.02084) and "On Optimizing the Communication of Model
Parallelism" (arXiv:2211.05322) both frame layout search as optimization
over a communication cost model; a cost model nobody has measured cannot
anchor a search.

This module is the measurement half:

  * :class:`CalibrationTable` — per ``(op, mesh-axis size, byte bucket)``
    measured wall-times (microseconds), plus the mesh it was measured on,
    a matmul-throughput sample (FLOPs -> us conversion for stage costs) and
    a content digest so perf records can name the cost model that priced
    them.
  * :func:`calibrate` — a targeted sweep: run each collective over each
    mesh axis at a ladder of byte buckets, ``block_until_ready``-timed,
    recording ndtimeline spans tagged with the measurement (so the sweep
    itself is trace-visible and :meth:`CalibrationTable.ingest_spans` can
    harvest ANY span stream carrying the same tag contract).
  * ``collective_calibration.json`` persistence (:meth:`save` /
    :func:`load_table`).
  * The consumption contract: ``VESCALE_COST_CALIBRATION=<path>`` (or
    :func:`set_active`) arms calibrated mode; :func:`collective_cost_us`
    answers lookups with log-log interpolation between byte buckets and
    returns ``None`` — after a ONE-TIME warning per (op, axis size) — when
    a bucket is missing, so every caller keeps its analytic fallback.  A
    table measured on a different mesh shape is STALE: it warns once and
    behaves as absent.  An EMPTY table (or no table) leaves every consumer
    bit-identical to the analytic model — calibration can only be additive.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CalibrationTable",
    "calibrate",
    "load_table",
    "set_active",
    "reset_active",
    "active_table",
    "table_for",
    "collective_cost_us",
    "table_cost_us",
    "compute_cost_us",
    "active_digest",
    "hop_latency_us",
    "device_peak_flops",
    "clear_warned",
    "TABLE_FILENAME",
    "CALIBRATION_OPS",
]

TABLE_FILENAME = "collective_calibration.json"
FORMAT_VERSION = 1

# the ops the sweep measures — the vocabulary of the planner's edge kinds
# (collective_permute prices as all_to_all's wire pattern; ppermute is the
# p2p hop simulate_schedule's comm term reads)
CALIBRATION_OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all", "ppermute")

# span tag contract: any span carrying these tags is a calibration sample
# (the sweep emits them; a runtime wrapper may too)
SPAN_TAGS = ("collective_op", "axis_size", "bytes")

# flat per-hop dispatch/launch overhead in calibrated (us-denominated) mode —
# the analytic model's _HOP_LATENCY analog.  Overridable per table
# (meta["launch_us"], measured by the sweep's smallest bucket residual).
DEFAULT_LAUNCH_US = 2.0


def _bucket(nbytes: int) -> int:
    """Canonical byte bucket: the power of two at or below ``nbytes``
    (bucket 1 for anything sub-byte).  Buckets key measurements; lookups
    interpolate between them in log-log space."""
    n = max(1, int(nbytes))
    return 1 << (n.bit_length() - 1)


@dataclasses.dataclass
class CalibrationTable:
    """Measured ``(op, axis_size, byte bucket) -> wall microseconds``.

    ``entries`` values are ``{"us": float, "samples": int}`` running means —
    harvesting more spans refines, never replaces, a bucket.  ``meta`` holds
    the provenance the staleness check reads: the mesh (dim names + shape)
    the measurements ran on, the platform, and optional ``matmul_gflops``
    (device compute throughput, for FLOPs -> us stage-cost conversion)."""

    entries: Dict[Tuple[str, int, int], Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )
    meta: Dict = dataclasses.field(default_factory=dict)
    # memoized content hash — digest() is consulted on EVERY plan-cache
    # lookup (_cal_key), so it must not re-serialize the table each time
    _digest: Optional[str] = dataclasses.field(default=None, repr=False, compare=False)

    # ------------------------------------------------------------- build
    def add_sample(self, op: str, axis_size: int, nbytes: int, seconds: float,
                   decay: Optional[float] = None) -> None:
        """Fold one measurement into its bucket.  Default: the plain
        running mean (every sample weighs ``1/n`` — the sweep's batch
        semantics).  ``decay``: a fixed EWMA weight for the ONLINE harvest
        (costaudit.py) — recent wall-clock outweighs history, so a table
        skewed by stale measurements converges back to reality instead of
        averaging it away."""
        key = (str(op), int(axis_size), _bucket(nbytes))
        cell = self.entries.get(key)
        us = float(seconds) * 1e6
        self._digest = None  # content changed: drop the memoized hash
        if cell is None:
            self.entries[key] = {"us": us, "samples": 1}
        elif decay is not None:
            a = min(1.0, max(0.0, float(decay)))
            cell["us"] += a * (us - cell["us"])
            cell["samples"] += 1
        else:
            n = cell["samples"] + 1
            cell["us"] += (us - cell["us"]) / n
            cell["samples"] = n

    def ingest_spans(self, spans, decay: Optional[float] = None) -> int:
        """Harvest calibration samples from a span stream: any span whose
        tags carry ``collective_op``/``axis_size``/``bytes`` (the sweep's
        own spans, or runtime instrumentation honoring the contract).
        ``decay`` forwards to :meth:`add_sample` (the online harvest's
        EWMA weight).  Returns the number of samples absorbed."""
        n = 0
        for s in spans:
            tags = getattr(s, "tags", None) or {}
            if not all(t in tags for t in SPAN_TAGS):
                continue
            try:
                self.add_sample(
                    tags["collective_op"], int(tags["axis_size"]),
                    int(tags["bytes"]), float(s.duration), decay=decay,
                )
                n += 1
            except (TypeError, ValueError):
                continue
        return n

    # ``harvest`` is the contract name the audit layer and docs use for
    # span-stream ingestion; same semantics as ingest_spans
    harvest = ingest_spans

    # ------------------------------------------------------------ lookup
    def lookup_us(self, op: str, axis_size: int, nbytes: int) -> Optional[float]:
        """Measured wall time for ``op`` over a mesh axis of ``axis_size``
        moving ``nbytes``: log-log interpolation between measured byte
        buckets, per-byte-rate extrapolation beyond the measured range,
        ``None`` when this (op, axis size) has no buckets at all."""
        pts = sorted(
            (k[2], v["us"])
            for k, v in self.entries.items()
            if k[0] == op and k[1] == int(axis_size)
        )
        if not pts:
            return None
        n = max(1, int(nbytes))
        if len(pts) == 1 or n <= pts[0][0]:
            b, us = pts[0]
            return us * (n / b) if n != b else us
        if n >= pts[-1][0]:
            b, us = pts[-1]
            return us * (n / b) if n != b else us
        for (b0, u0), (b1, u1) in zip(pts, pts[1:]):
            if b0 <= n <= b1:
                if b0 == b1:
                    return u0
                t = (math.log(n) - math.log(b0)) / (math.log(b1) - math.log(b0))
                return math.exp(math.log(u0) * (1 - t) + math.log(u1) * t)
        return pts[-1][1]  # unreachable; defensive

    def op_estimate_us(self, op: str) -> Optional[float]:
        """Sample-weighted mean wall time over EVERY bucket of ``op`` —
        the coarse single-number seed for consumers that know the op but
        not the payload (the serve scheduler's audited ``retry_after_s``
        seed reads ``serve_decode``).  None when the op was never
        measured."""
        total = weight = 0.0
        for k, v in self.entries.items():
            if k[0] == op:
                total += v["us"] * v["samples"]
                weight += v["samples"]
        return total / weight if weight else None

    def matches_mesh(self, mesh) -> bool:
        """Staleness check: the table speaks for the mesh it measured.
        Compares dim names + shape (a ``DeviceMesh`` or anything exposing
        ``mesh_dim_names``/``shape``); a table without mesh provenance
        matches nothing."""
        want = self.meta.get("mesh")
        if not want:
            return False
        try:
            return tuple(want.get("dim_names", ())) == tuple(mesh.mesh_dim_names) and tuple(
                want.get("shape", ())
            ) == tuple(mesh.shape)
        except AttributeError:
            return False

    def __len__(self) -> int:
        return len(self.entries)

    # ------------------------------------------------------- persistence
    def to_json(self) -> Dict:
        return {
            "format": FORMAT_VERSION,
            "meta": self.meta,
            "entries": [
                {"op": k[0], "axis_size": k[1], "bucket_bytes": k[2], **v}
                for k, v in sorted(self.entries.items())
            ],
        }

    @classmethod
    def from_json(cls, data: Dict) -> "CalibrationTable":
        if int(data.get("format", 0)) != FORMAT_VERSION:
            raise ValueError(
                f"calibration table format {data.get('format')!r} unsupported "
                f"(this build reads format {FORMAT_VERSION})"
            )
        t = cls(meta=dict(data.get("meta") or {}))
        for e in data.get("entries", ()):
            t.entries[(str(e["op"]), int(e["axis_size"]), int(e["bucket_bytes"]))] = {
                "us": float(e["us"]),
                "samples": int(e.get("samples", 1)),
            }
        return t

    def digest(self) -> str:
        """Stable short content hash — BENCH lines and plan-cache keys
        record it so a perf number names the cost model that priced it.
        Memoized until the next ``add_sample``/``ingest_spans``."""
        if self._digest is None:
            blob = json.dumps(self.to_json(), sort_keys=True).encode()
            self._digest = hashlib.sha256(blob).hexdigest()[:12]
        return self._digest

    def save(self, path: str) -> str:
        """Atomic persist (tmp + rename): the online harvest rewrites the
        table on a cadence while planners may re-read it mid-write via the
        ``VESCALE_COST_CALIBRATION`` mtime reload — a torn read must be
        impossible."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        data = self.to_json()
        data["digest"] = self.digest()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    def launch_us(self) -> float:
        return float(self.meta.get("launch_us", DEFAULT_LAUNCH_US))


def load_table(path: str) -> CalibrationTable:
    with open(path) as f:
        return CalibrationTable.from_json(json.load(f))


# --------------------------------------------------------------- sweep
def _timed(fn, *args) -> float:
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    return time.perf_counter() - t0


def calibrate(
    mesh,
    ops: Sequence[str] = CALIBRATION_OPS,
    byte_buckets: Sequence[int] = (1 << 12, 1 << 16, 1 << 20),
    reps: int = 3,
    measure_matmul: bool = True,
) -> CalibrationTable:
    """Targeted measurement sweep: for each mesh axis, each op and each byte
    bucket, run the collective ``reps`` times (after one untimed warmup that
    eats the compile) and record the median wall time.  Every measured rep
    also emits an ndtimeline span tagged with the sample (when the profiler
    is active), so the sweep shows up on the trace timeline and
    ``ingest_spans`` can re-harvest it from a raw span dump.

    All processes of a multi-process mesh must call this together (the
    collectives are, well, collective)."""
    import numpy as np
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .. import collectives as C
    from ..ndtimeline.api import ndtimeit

    table = CalibrationTable(
        meta={
            "mesh": {
                "dim_names": list(mesh.mesh_dim_names),
                "shape": list(mesh.shape),
            },
            "platform": jax.devices()[0].platform,
            "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "launch_us": DEFAULT_LAUNCH_US,
        }
    )

    def run(op: str, dim: int, x):
        if op == "all_reduce":
            return C.mesh_all_reduce(x, mesh, mesh_dim=dim, stacked=False)
        if op == "all_gather":
            return C.mesh_all_gather(x, mesh, mesh_dim=dim, stacked=False)
        if op == "reduce_scatter":
            return C.mesh_reduce_scatter(x, mesh, mesh_dim=dim)
        if op == "all_to_all":
            return C.mesh_all_to_all(x, mesh, mesh_dim=dim)
        if op == "ppermute":
            return C.mesh_ppermute(x, mesh, mesh_dim=dim)
        raise ValueError(f"unknown calibration op {op!r}")

    def make_input(op: str, dim: int, nbytes: int):
        # GLOBAL arrays by construction (make_array_from_callback over the
        # mesh sharding) so the sweep runs unchanged on a process-spanning
        # mesh — jnp.ones would build process-local arrays there
        ax = mesh.dim_name(dim)
        n = int(mesh.shape[dim])
        elems = max(1, int(nbytes) // 4)  # f32 payloads
        if op in ("reduce_scatter", "all_to_all", "ppermute"):
            # stacked convention: dim0 carries per-rank operands, and
            # chunking needs divisibility by n
            per = max(n, (elems // n) * n)
            shape, spec = (n, per), P(ax)
        else:
            shape, spec = (elems,), P()
        sh = NamedSharding(mesh.jax_mesh, spec)

        def cb(idx):
            return np.ones(
                [len(range(*sl.indices(shape[i]))) for i, sl in enumerate(idx)],
                np.float32,
            )

        return jax.make_array_from_callback(shape, sh, cb)

    for dim in range(len(mesh.shape)):
        n = int(mesh.shape[dim])
        if n <= 1:
            continue
        for op in ops:
            for nbytes in byte_buckets:
                x = make_input(op, dim, int(nbytes))
                _timed(run, op, dim, x)  # warmup: compile + first dispatch
                samples = []
                for _ in range(max(1, int(reps))):
                    with ndtimeit(
                        "calibrate-collective",
                        tags={"collective_op": op, "axis_size": n, "bytes": int(nbytes)},
                    ):
                        samples.append(_timed(run, op, dim, x))
                table.add_sample(op, n, int(nbytes), float(np.median(samples)))

    if measure_matmul:
        # device compute throughput sample: FLOPs -> us conversion for
        # calibrated stage costs (pipe/schedules.estimate_stage_costs)
        import jax.numpy as jnp

        k = 256
        a = jnp.ones((k, k), jnp.float32)
        mm = jax.jit(lambda a: a @ a)
        _timed(mm, a)
        dt = float(np.median([_timed(mm, a) for _ in range(max(1, int(reps)))]))
        flops = 2.0 * k * k * k
        table.meta["matmul_gflops"] = flops / dt / 1e9
    return table


# ------------------------------------------------------- active table gate
_LOCK = threading.Lock()
_ACTIVE: Optional[CalibrationTable] = None          # programmatic override
_ACTIVE_EXPLICIT = False
_LOADED: Dict[str, Tuple[float, Optional[CalibrationTable]]] = {}  # path -> (mtime, table)
_WARNED: set = set()  # one-time fallback warnings, keyed by reason


def set_active(table: Optional[CalibrationTable]) -> None:
    """Programmatically arm (or, with ``None``, disarm) calibrated mode for
    this process, overriding ``VESCALE_COST_CALIBRATION``.  Call
    ``reset_active()`` to return control to the env knob."""
    global _ACTIVE, _ACTIVE_EXPLICIT
    with _LOCK:
        _ACTIVE = table
        _ACTIVE_EXPLICIT = True


def reset_active() -> None:
    global _ACTIVE, _ACTIVE_EXPLICIT
    with _LOCK:
        _ACTIVE = None
        _ACTIVE_EXPLICIT = False
        _LOADED.clear()
        _WARNED.clear()


def clear_warned() -> None:
    """Re-arm the one-time fallback warnings (test hook)."""
    with _LOCK:
        _WARNED.clear()


def _warn_once(key: str, message: str) -> None:
    """Stale/missing-table signals route through the alert engine when it
    is live (rule ``calibration-<reason>``: one lifecycle, /alerts
    visibility); the dormant path keeps the legacy per-key one-shot
    warning so analytic fallbacks stay visible without telemetry."""
    with _LOCK:
        if key in _WARNED:
            return
        _WARNED.add(key)
    from . import alerts as _alerts

    if _alerts.is_active():
        _alerts.raise_alert(
            f"calibration-{key.split(':', 1)[0]}", message=message,
            severity="warning",
        )
        return
    # dormant-engine legacy fallback; live runs route through the
    # telemetry.alerts branch above
    warnings.warn(message, stacklevel=3)  # vescale-lint: disable=VSC207


def active_table() -> Optional[CalibrationTable]:
    """The armed calibration table, or None (analytic mode).  Resolution:
    an explicit :func:`set_active` wins; else ``VESCALE_COST_CALIBRATION``
    names a JSON path, loaded lazily and re-read when its mtime changes
    (live env semantics, envreg contract).  An unreadable path warns once
    and behaves as absent — a typo'd knob must not crash planning."""
    with _LOCK:
        if _ACTIVE_EXPLICIT:
            return _ACTIVE
    from ..analysis import envreg

    path = envreg.get_str("VESCALE_COST_CALIBRATION")
    if not path:
        return None
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        _warn_once(
            f"missing:{path}",
            f"VESCALE_COST_CALIBRATION={path!r}: table not readable — "
            "falling back to the analytic cost model",
        )
        return None
    with _LOCK:
        cached = _LOADED.get(path)
        if cached is not None and cached[0] == mtime:
            return cached[1]
    try:
        table = load_table(path)
    except (OSError, ValueError, KeyError) as e:
        _warn_once(
            f"unparseable:{path}",
            f"VESCALE_COST_CALIBRATION={path!r}: failed to load ({e}) — "
            "falling back to the analytic cost model",
        )
        table = None
    with _LOCK:
        _LOADED[path] = (mtime, table)
    return table


def table_for(mesh) -> Optional[CalibrationTable]:
    """The armed NON-EMPTY table when it speaks for ``mesh`` (or when no
    mesh is given), else None.  A stale table — measured on a different
    mesh shape, or on a different BACKEND than the one now running — warns
    once and resolves to None, so every consumer degrades to its analytic
    model identically.  The platform check covers mesh-less consumers
    (the ``collectives.py`` cost functions keep their signatures): gloo-CPU
    wall times must never silently price a TPU plan."""
    t = active_table()
    if t is None or len(t) == 0:
        return None
    want_platform = t.meta.get("platform")
    if want_platform:
        import jax

        have = jax.devices()[0].platform
        if have != want_platform:
            _warn_once(
                f"platform:{t.digest()}",
                f"VESCALE_COST_CALIBRATION: table was measured on platform "
                f"{want_platform!r} but this process runs on {have!r} — "
                "stale table; falling back to the analytic cost model "
                "(re-run telemetry.calibrate.calibrate() on this backend)",
            )
            return None
    if mesh is not None and not t.matches_mesh(mesh):
        _warn_once(
            f"stale:{t.digest()}",
            "VESCALE_COST_CALIBRATION: table was measured on mesh "
            f"{t.meta.get('mesh')} but is being consulted for {mesh!r} — "
            "stale table; falling back to the analytic cost model "
            "(re-run telemetry.calibrate.calibrate() on this mesh)",
        )
        return None
    return t


def active_digest() -> Optional[str]:
    """Digest of the armed NON-EMPTY table, else None.  The signature the
    planner's cache key and bench lines embed: an empty table is
    cost-model-identical to no table and must key identically."""
    t = active_table()
    if t is None or len(t) == 0:
        return None
    return t.digest()


def table_cost_us(
    table: Optional[CalibrationTable], op: str, axis_size: int, nbytes: float
) -> Optional[float]:
    """Measured lookup against an ALREADY-RESOLVED table — the planner's
    hot path resolves the table once per edge set and must not pay the
    env-read + mtime-stat + platform-probe of :func:`table_for` again per
    wire op.  Same one-time missing-bucket warning as
    :func:`collective_cost_us`.  ``nbytes`` is the per-rank OPERAND
    payload (the sweep's own key), never ring-scaled wire bytes."""
    if table is None or int(axis_size) <= 1:
        return None
    us = table.lookup_us(op, int(axis_size), int(nbytes))
    if us is None:
        _warn_once(
            f"bucket:{op}:{axis_size}",
            f"cost calibration: no measured bucket for op={op!r} over a mesh "
            f"axis of {axis_size} — using the analytic model for this op "
            "(extend the calibrate() sweep to cover it)",
        )
        return None
    return us


def collective_cost_us(
    op: str, axis_size: int, nbytes: float, mesh=None
) -> Optional[float]:
    """Measured cost of one collective in microseconds, or None (caller
    falls back to its analytic model).  ``mesh`` (when the caller has one)
    arms the mesh-shape staleness check on top of the always-on platform
    check; a stale table warns once and is treated as absent."""
    if int(axis_size) <= 1:
        return None
    return table_cost_us(table_for(mesh), op, axis_size, nbytes)


# assumed elementwise-pass bandwidth for pricing quantize/dequantize compute
# in calibrated (us-denominated) mode; deliberately conservative so a quant
# hop must win on WIRE time, as in the analytic model
_COMPUTE_GBPS = 10.0


def compute_cost_us(nbytes: float) -> float:
    """Calibrated-mode price of an elementwise pass touching ``nbytes``
    (quantize/dequantize terms of the planner's quant edge)."""
    return float(nbytes) / 1e9 / _COMPUTE_GBPS * 1e6


def hop_latency_us() -> float:
    """Per-hop dispatch overhead in calibrated mode (the analytic model's
    flat ``_HOP_LATENCY`` byte term, re-denominated in microseconds)."""
    t = active_table()
    return t.launch_us() if t is not None else DEFAULT_LAUNCH_US


def device_peak_flops(device) -> float:
    """Peak (bf16 matmul) FLOP/s of one accelerator chip — the MFU
    denominator shared by the bench harness and the serve MFU gauge.  TPU
    generations come from the datasheet table; any other platform prefers
    the active calibration table's MEASURED ``matmul_gflops`` (an honest
    achievable-peak on CPU rigs) and falls back to 1e12 so an MFU line
    still prints rather than dividing by an unknown."""
    kind = getattr(device, "device_kind", "").lower()
    plat = getattr(device, "platform", "").lower()
    if "v6" in kind:
        return 918e12  # v6e (Trillium) bf16
    if "v5p" in kind:
        return 459e12
    if "v5" in kind or "lite" in kind:
        return 197e12  # v5e bf16
    if "v4" in kind:
        return 275e12
    if plat == "tpu":
        return 197e12
    t = active_table()
    if t is not None:
        g = t.meta.get("matmul_gflops")
        if g:
            return float(g) * 1e9
    return 1e12
