"""Plan-vs-reality cost auditing — the predict→measure→recalibrate loop.

Every planner in the stack prices decisions in microseconds — the
redistribution Dijkstra (redistribute_plan.py), the VSC127/128 quant-edge
competition, ``simulate_schedule``'s stage costs, the serve loop's retry
hints and the AOT memory budget — but a price nobody checks against a
measured run mis-ranks plans silently forever.  This module closes the
loop the measured-cost planning literature (Mesh-TensorFlow,
arXiv:1811.02084; "On Optimizing the Communication of Model Parallelism",
arXiv:2211.05322) assumes but never instruments:

  * **Prediction ledger** — :func:`record_prediction` appends a structured
    prediction (plan id, predicted µs/bytes, cost-model digest, unit) to a
    bounded ring; :func:`record_measurement` joins the measured outcome by
    plan id and folds the divergence ratio ``max(m/p, p/m)`` into per-kind
    decayed running means.
  * **Per-step auditor** — :func:`audit_step` (called by
    ``telemetry.record_step`` before the timeseries sample) publishes the
    divergence ratios as ``cost_model_*`` registry gauges — which the
    history store and the ``cost-model-drift`` alert rule
    (:func:`costaudit_rule_pack`) then see for free — and returns the
    joined summary that lands as the ``cost_audit`` field of a steps.jsonl
    line.
  * **Online calibration** — the auditor continuously harvests tagged span
    streams (the :data:`calibrate.SPAN_TAGS` contract: the calibrate
    sweep, the instrumented redistribute hops, the serve decode/prefill
    spans) into the active :class:`~.calibrate.CalibrationTable` with a
    decayed running mean and cadenced atomic persistence.  The digest in
    the planner's cache key makes re-planning automatic on rotation, so
    measured drift self-heals instead of warning.
  * **Per-layer roofline attribution** — :func:`layer_attribution` maps
    HLO op metadata (``op_name`` scopes) onto per-fused-region FLOPs/bytes
    estimates, classifies each layer compute- vs memory-bound against the
    device roofline, and :func:`attach_roofline_tracks` renders the result
    as Perfetto counter tracks.
  * **What-if scorer** — :func:`score_candidates` re-prices candidate
    (dp, tp, pp) meshes against the live audited table with per-bucket
    audit-backed confidence (``python -m vescale_tpu.analysis whatif``).

Gating contract (memtrack-style): ``record_prediction`` /
``record_measurement`` / ``audit_step`` / ``harvest`` are module-level
no-op function references while dormant — a run that never activates the
auditor pays one attribute load per call site and allocates nothing.
``telemetry.init()`` activates (``VESCALE_COSTAUDIT``), ``shutdown()``
restores the no-ops.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "record_prediction",
    "record_measurement",
    "audit_step",
    "harvest",
    "activate",
    "deactivate",
    "is_active",
    "get_auditor",
    "audit_summary",
    "costaudit_rule_pack",
    "CostAudit",
    "layer_attribution",
    "roofline_counter_events",
    "attach_roofline_tracks",
    "device_mem_gbps",
    "mesh_candidates",
    "score_candidates",
    "PLAN_ID_TAG",
]

# span tag naming the prediction a measured span belongs to (rides next to
# the calibrate SPAN_TAGS contract on instrumented redistribute hops)
PLAN_ID_TAG = "plan_id"


# ------------------------------------------------------- dormant no-op hooks
# Named module-level functions (never lambdas — VSC203): the planners call
# these unconditionally and an un-audited run must pay only the attribute
# load.  activate()/deactivate() swap the module attributes, memtrack-style.

def _noop_record_prediction(kind, predicted_us=None, predicted_bytes=None,
                            digest=None, unit="us", detail=None):
    return None


def _noop_record_measurement(plan_id, measured_us=None, measured_bytes=None):
    return None


def _noop_audit_step(kind=None):
    return None


def _noop_harvest(spans=None):
    return 0


record_prediction = _noop_record_prediction
record_measurement = _noop_record_measurement
audit_step = _noop_audit_step
harvest = _noop_harvest


# plan ids are PROCESS-monotonic, not per-auditor: plans cached in the
# planner outlive telemetry init/shutdown cycles, and a stale id must fall
# off the new ledger as unknown — never collide with a fresh prediction
_ID_LOCK = threading.Lock()
_NEXT_ID = 1


def _new_id() -> int:
    global _NEXT_ID
    with _ID_LOCK:
        i = _NEXT_ID
        _NEXT_ID += 1
        return i


class CostAudit:
    """The live auditor: bounded prediction ledger + divergence aggregates
    + the online-calibration harvester.  Exists only between
    :func:`activate` and :func:`deactivate` — its absence IS the off
    state."""

    def __init__(self, registry, *, depth: int = 256, threshold: float = 3.0,
                 decay: float = 0.25, cadence_s: float = 30.0,
                 harvest_spans: bool = True):
        self.registry = registry
        self.depth = max(1, int(depth))
        self.threshold = float(threshold)
        self.decay = float(decay)
        self.cadence_s = float(cadence_s)
        self.harvest_spans = bool(harvest_spans)
        self._lock = threading.Lock()
        self._ledger: "OrderedDict[int, Dict]" = OrderedDict()
        self._predicted = 0
        self._matched = 0
        self._divergence: Optional[float] = None          # decayed mean ratio
        self._by_kind: Dict[str, Dict[str, Any]] = {}
        self._bucket_div: Dict[Tuple[str, int, int], Dict[str, float]] = {}
        self._harvested = 0
        self._harvest_hwm = 0.0      # span-start high-water mark (no re-ingest)
        self._last_persist = time.monotonic()
        self._digest_rotations = 0

    # -------------------------------------------------------------- ledger
    def record_prediction(self, kind: str, predicted_us: Optional[float] = None,
                          predicted_bytes: Optional[float] = None,
                          digest: Optional[str] = None, unit: str = "us",
                          detail: Optional[Dict] = None) -> int:
        """Append one priced decision; returns the plan id the producer
        threads through its spans/measurement."""
        pid = _new_id()
        with self._lock:
            self._ledger[pid] = {
                "plan_id": pid,
                "kind": str(kind),
                "predicted_us": None if predicted_us is None else float(predicted_us),
                "predicted_bytes": None if predicted_bytes is None else float(predicted_bytes),
                "digest": digest,
                "unit": str(unit),
                "detail": detail,
                "ts": time.time(),
                "measured_us": None,
                "measured_bytes": None,
                "divergence": None,
            }
            while len(self._ledger) > self.depth:
                self._ledger.popitem(last=False)
            self._predicted += 1
            k = self._by_kind.setdefault(
                str(kind), {"predictions": 0, "matched": 0, "divergence": None}
            )
            k["predictions"] += 1
        if self.registry is not None:
            self.registry.counter("cost_model_predictions_total").inc()
        return pid

    def record_measurement(self, plan_id, measured_us: Optional[float] = None,
                           measured_bytes: Optional[float] = None) -> Optional[float]:
        """Join a measured outcome to its prediction.  Returns the
        divergence ratio ``max(m/p, p/m)`` when both sides are µs-priced
        and positive, else None.  Unknown/expired plan ids are ignored —
        the ring is bounded and the producer may outlive it."""
        if plan_id is None:
            return None
        ratio = None
        with self._lock:
            rec = self._ledger.get(plan_id)
            if rec is None:
                return None
            first = rec["measured_us"] is None and rec["measured_bytes"] is None
            rec["measured_us"] = None if measured_us is None else float(measured_us)
            rec["measured_bytes"] = (
                None if measured_bytes is None else float(measured_bytes)
            )
            if first:
                self._matched += 1
                self._by_kind[rec["kind"]]["matched"] += 1
            p, m = rec["predicted_us"], rec["measured_us"]
            if rec["unit"] == "bytes":  # byte-denominated (AOT memory budget)
                p, m = rec["predicted_bytes"], rec["measured_bytes"]
            if rec["unit"] in ("us", "bytes") and p and m and p > 0 and m > 0:
                ratio = max(m / p, p / m)
                rec["divergence"] = ratio
                self._divergence = self._fold(self._divergence, ratio)
                k = self._by_kind[rec["kind"]]
                k["divergence"] = self._fold(k["divergence"], ratio)
        if self.registry is not None:
            self.registry.counter("cost_model_matched_total").inc()
        return ratio

    def _fold(self, mean: Optional[float], ratio: float) -> float:
        """Decayed running mean of divergence ratios (same decay constant
        the calibration harvest uses)."""
        if mean is None:
            return float(ratio)
        a = min(1.0, max(0.0, self.decay))
        return mean + a * (ratio - mean)

    # ------------------------------------------------------------- auditor
    def audit_step(self, kind: Optional[str] = None) -> Optional[Dict]:
        """The per-step join: harvest fresh tagged spans, publish the
        divergence gauges (which the timeseries sample taken right after
        and the ``cost-model-drift`` rule read), and return the summary
        dict for the steps.jsonl ``cost_audit`` field — None when nothing
        has ever been priced or harvested (the jsonl line stays
        bit-identical to an un-audited run)."""
        if self.harvest_spans:
            self.harvest(None)
        with self._lock:
            predicted, matched = self._predicted, self._matched
            overall = self._divergence
            by_kind = {
                k: dict(v) for k, v in self._by_kind.items()
            }
            harvested = self._harvested
        if predicted == 0 and harvested == 0:
            return None
        reg = self.registry
        if reg is not None:
            if overall is not None:
                reg.gauge("cost_model_divergence").set(overall)
            for k, v in by_kind.items():
                if v["divergence"] is not None:
                    reg.gauge(f"cost_model_divergence_{k}").set(v["divergence"])
            reg.gauge("cost_model_unmatched").set(predicted - matched)
        out: Dict[str, Any] = {
            "predictions": predicted,
            "matched": matched,
            "divergence": overall,
            "harvested_spans": harvested,
        }
        if by_kind:
            out["by_kind"] = by_kind
        return out

    # -------------------------------------------------- online calibration
    def harvest(self, spans=None) -> int:
        """Fold tagged spans into the active CalibrationTable with the
        decayed running mean, note per-bucket divergence against the
        table's prior estimate, and persist atomically on cadence to the
        ``VESCALE_COST_CALIBRATION`` path.  ``spans=None`` peeks the live
        ndtimeline ring (high-water-marked by span start time, so repeated
        peeks never double-ingest).  Returns samples absorbed."""
        from ..ndtimeline import api as _nd
        from . import calibrate as _cal

        if spans is None:
            if not _nd.is_active():
                return 0
            spans = _nd.get_manager().tail(4096)
        fresh = []
        for s in spans:
            tags = getattr(s, "tags", None) or {}
            if not all(t in tags for t in _cal.SPAN_TAGS):
                continue
            start = float(getattr(s, "start", 0.0) or 0.0)
            if start <= self._harvest_hwm:
                continue
            fresh.append((start, s, tags))
        if not fresh:
            return 0
        hwm = max(f[0] for f in fresh)
        table = _cal.active_table()
        if table is None:
            self._harvest_hwm = hwm
            return 0
        old_digest = table.digest() if len(table) else None
        n = 0
        for _, s, tags in fresh:
            try:
                op = str(tags["collective_op"])
                ax = int(tags["axis_size"])
                nb = int(tags["bytes"])
                dur = float(s.duration)
            except (TypeError, ValueError):
                continue
            prior = table.lookup_us(op, ax, nb)
            table.add_sample(op, ax, nb, dur, decay=self.decay)
            us = dur * 1e6
            if prior and prior > 0 and us > 0:
                self._note_bucket(op, ax, nb, max(us / prior, prior / us))
            n += 1
        self._harvest_hwm = hwm
        if n == 0:
            return 0
        with self._lock:
            self._harvested += n
        reg = self.registry
        if reg is not None:
            reg.counter("cost_model_harvested_spans_total").inc(n)
        if old_digest is not None and table.digest() != old_digest:
            self._digest_rotations += 1
            if reg is not None:
                reg.counter("cost_model_digest_rotations_total").inc()
        self._maybe_persist(table)
        return n

    def _note_bucket(self, op: str, axis_size: int, nbytes: int, ratio: float) -> None:
        from .calibrate import _bucket

        key = (op, int(axis_size), _bucket(nbytes))
        cell = self._bucket_div.get(key)
        if cell is None:
            self._bucket_div[key] = {"ratio": float(ratio), "samples": 1}
        else:
            cell["ratio"] = self._fold(cell["ratio"], ratio)
            cell["samples"] += 1

    def _maybe_persist(self, table) -> None:
        from ..analysis import envreg

        path = envreg.get_str("VESCALE_COST_CALIBRATION")
        if not path:
            return
        now = time.monotonic()
        if now - self._last_persist < self.cadence_s:
            return
        try:
            table.save(path)  # atomic (tmp + os.replace) since the audit PR
            self._last_persist = now
            if self.registry is not None:
                self.registry.counter("cost_model_table_persists_total").inc()
        except OSError:
            pass  # a read-only path must not fail a step

    def persist_now(self, path: Optional[str] = None) -> Optional[str]:
        """Cadence-bypassing persist (shutdown flush / test hook)."""
        from . import calibrate as _cal
        from ..analysis import envreg

        table = _cal.active_table()
        target = path or envreg.get_str("VESCALE_COST_CALIBRATION")
        if table is None or not target:
            return None
        try:
            table.save(target)
        except OSError:
            return None
        self._last_persist = time.monotonic()
        return target

    # ------------------------------------------------------------ readouts
    def bucket_divergence(self) -> Dict[Tuple[str, int, int], Dict[str, float]]:
        """Audit history per (op, axis_size, byte bucket) — the what-if
        scorer's confidence input."""
        with self._lock:
            return {k: dict(v) for k, v in self._bucket_div.items()}

    def ledger(self) -> List[Dict]:
        with self._lock:
            return [dict(r) for r in self._ledger.values()]

    def summary(self) -> Dict[str, Any]:
        """The bench ``audit`` block: predicted-vs-measured rollup for the
        run's own plans."""
        with self._lock:
            return {
                "predictions": self._predicted,
                "matched": self._matched,
                "divergence": self._divergence,
                "by_kind": {k: dict(v) for k, v in self._by_kind.items()},
                "harvested_spans": self._harvested,
                "digest_rotations": self._digest_rotations,
                "ledger_depth": len(self._ledger),
            }


# ------------------------------------------------------------- activation
_AUDIT: Optional[CostAudit] = None


def _active_record_prediction(kind, predicted_us=None, predicted_bytes=None,
                              digest=None, unit="us", detail=None):
    a = _AUDIT
    if a is None:
        return None
    return a.record_prediction(kind, predicted_us=predicted_us,
                               predicted_bytes=predicted_bytes, digest=digest,
                               unit=unit, detail=detail)


def _active_record_measurement(plan_id, measured_us=None, measured_bytes=None):
    a = _AUDIT
    if a is None:
        return None
    return a.record_measurement(plan_id, measured_us=measured_us,
                                measured_bytes=measured_bytes)


def _active_audit_step(kind=None):
    a = _AUDIT
    if a is None:
        return None
    return a.audit_step(kind)


def _active_harvest(spans=None):
    a = _AUDIT
    if a is None:
        return 0
    return a.harvest(spans)


def costaudit_rule_pack(threshold: float = 3.0) -> List:
    """The ``cost-model-drift`` rule: sustained predicted-vs-measured
    divergence beyond ``threshold`` (a ratio — 3.0 means the cost model is
    off by 3x in either direction) over the gauge the auditor publishes
    every step.  Self-healing context rides in the message: online
    recalibration rotates the digest, so a firing rule that later resolves
    means the table corrected itself."""
    from .alerts import ThresholdRule

    return [
        ThresholdRule(
            "cost-model-drift", "cost_model_divergence", ">", float(threshold),
            window_s=60.0, reducer="last", for_s=0.0, severity="warning",
            message=(
                "cost model predictions diverge from measured outcomes by "
                f"more than {threshold:g}x (decayed mean of max(m/p, p/m)); "
                "online recalibration is folding measured spans back into "
                "the calibration table — sustained firing means the spans "
                "the planner prices are not the spans it produces"
            ),
        )
    ]


def activate(registry=None, *, depth: Optional[int] = None,
             threshold: Optional[float] = None, decay: Optional[float] = None,
             cadence_s: Optional[float] = None,
             harvest_spans: Optional[bool] = None) -> CostAudit:
    """Swap the live hooks in (telemetry.init's job; knobs default to the
    ``VESCALE_COSTAUDIT_*`` envreg family) and arm the drift rule when the
    alert engine is live."""
    global _AUDIT, record_prediction, record_measurement, audit_step, harvest
    from ..analysis import envreg

    a = CostAudit(
        registry,
        depth=depth if depth is not None else envreg.get_int("VESCALE_COSTAUDIT_DEPTH"),
        threshold=(threshold if threshold is not None
                   else envreg.get_float("VESCALE_COSTAUDIT_THRESHOLD")),
        decay=decay if decay is not None else envreg.get_float("VESCALE_COSTAUDIT_DECAY"),
        cadence_s=(cadence_s if cadence_s is not None
                   else envreg.get_float("VESCALE_COSTAUDIT_CADENCE_S")),
        harvest_spans=(harvest_spans if harvest_spans is not None
                       else envreg.get_bool("VESCALE_COSTAUDIT_HARVEST")),
    )
    _AUDIT = a
    record_prediction = _active_record_prediction
    record_measurement = _active_record_measurement
    audit_step = _active_audit_step
    harvest = _active_harvest
    from . import alerts as _alerts

    eng = _alerts.get_engine()
    if eng is not None:
        eng.arm_pack("costaudit", costaudit_rule_pack(a.threshold))
    return a


def deactivate() -> None:
    """Restore the dormant no-op hooks (telemetry.shutdown's job)."""
    global _AUDIT, record_prediction, record_measurement, audit_step, harvest
    _AUDIT = None
    record_prediction = _noop_record_prediction
    record_measurement = _noop_record_measurement
    audit_step = _noop_audit_step
    harvest = _noop_harvest


def is_active() -> bool:
    return _AUDIT is not None


def get_auditor() -> Optional[CostAudit]:
    return _AUDIT


def audit_summary() -> Optional[Dict]:
    """Module-level summary (bench's audit block); None while dormant."""
    a = _AUDIT
    return a.summary() if a is not None else None


# ----------------------------------------------- per-layer roofline model
# HLO-text parsing: one instruction per line, `%name = dtype[dims]... opcode(
# %operand, ...)`, layer names recovered from metadata op_name scopes.  An
# ESTIMATE by construction (fused-computation bodies contribute their own
# shapes, so bytes overcount vs XLA's exact accounting) — attribution, not
# accounting.

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f32": 4, "s32": 4, "u32": 4,
    "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    "c128": 16,
}
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([A-Za-z0-9_.\-]+)\s*=\s*([a-z0-9]+)\[([0-9,]*)\]\S*\s+"
    r"([a-z0-9\-]+)\("
)
_OPNAME_RE = re.compile(r'op_name="([^"]+)"')
_OPERAND_RE = re.compile(r"%([A-Za-z0-9_.\-]+)")
_WRAPPER_SEG_RE = re.compile(r"^[\w.\-]+\(.*\)$")

# opcodes whose flops we model (2 * lhs_elems * out_last_dim — exact for a
# plain matmul, an attribution-grade estimate for batched/convolved forms)
_MATMUL_OPCODES = ("dot", "convolution")


def device_mem_gbps(device) -> float:
    """HBM bandwidth (GB/s) of one chip — the roofline's memory roof.  TPU
    generations from the datasheet; any other platform gets a host-DRAM
    ballpark so a CPU rig still classifies rather than dividing by an
    unknown."""
    kind = getattr(device, "device_kind", "").lower()
    plat = getattr(device, "platform", "").lower()
    if "v6" in kind:
        return 1640.0  # Trillium
    if "v5p" in kind:
        return 2765.0
    if "v5" in kind or "lite" in kind:
        return 819.0  # v5e
    if "v4" in kind:
        return 1228.0
    if plat == "tpu":
        return 819.0
    return 50.0


def _layer_of(op_name: str) -> str:
    """Layer key from an HLO op_name scope path: drop wrapper frames
    (``jit(step)``, ``jvp(...)``, ``transpose(...)``), keep the first two
    model-scope segments above the op itself."""
    segs = [p for p in op_name.split("/") if p and not _WRAPPER_SEG_RE.match(p)]
    if not segs:
        return "<unattributed>"
    head = segs[:-1] or segs
    return "/".join(head[:2])


def layer_attribution(hlo_text: str, device=None, peak_flops: Optional[float] = None,
                      mem_gbps: Optional[float] = None) -> Dict[str, Any]:
    """Per-layer FLOPs/bytes attribution of an HLO module, classified
    compute- vs memory-bound against the device roofline.

    Returns ``{"layers": [{layer, flops, bytes, ops, intensity, bound,
    est_us}...] (est_us-descending), "ridge_flops_per_byte", "peak_flops",
    "mem_gbps", "total_flops", "total_bytes"}``."""
    if peak_flops is None or mem_gbps is None:
        if device is None:
            import jax

            device = jax.devices()[0]
        from . import calibrate as _cal

        if peak_flops is None:
            peak_flops = _cal.device_peak_flops(device)
        if mem_gbps is None:
            mem_gbps = device_mem_gbps(device)
    bw = float(mem_gbps) * 1e9
    ridge = float(peak_flops) / bw

    shapes: Dict[str, Tuple[int, int]] = {}  # name -> (elems, bytes)
    parsed = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        name, dtype, dims, opcode = m.groups()
        if dtype not in _DTYPE_BYTES:
            continue  # tuple/token/opaque results: no payload to attribute
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        out_bytes = elems * _DTYPE_BYTES[dtype]
        shapes[name] = (elems, out_bytes)
        op_name_m = _OPNAME_RE.search(line)
        rest = line[m.end():]
        operands = [o for o in _OPERAND_RE.findall(rest.split("),", 1)[0])
                    if o != name]
        parsed.append((name, dims, opcode, elems, out_bytes,
                       op_name_m.group(1) if op_name_m else None, operands))

    per_layer: Dict[str, Dict[str, float]] = {}
    for name, dims, opcode, elems, out_bytes, op_name, operands in parsed:
        if op_name is None:
            continue  # parameters/infra ops without a model scope
        layer = _layer_of(op_name)
        acc = per_layer.setdefault(layer, {"flops": 0.0, "bytes": 0.0, "ops": 0})
        nbytes = float(out_bytes)
        for o in operands:
            sh = shapes.get(o)
            if sh is not None:
                nbytes += sh[1]
        flops = 0.0
        if opcode in _MATMUL_OPCODES and operands:
            lhs = shapes.get(operands[0])
            if lhs is not None:
                last = int(dims.split(",")[-1]) if dims else 1
                flops = 2.0 * lhs[0] * max(1, last)
        acc["flops"] += flops
        acc["bytes"] += nbytes
        acc["ops"] += 1

    layers = []
    total_flops = total_bytes = 0.0
    for layer, acc in per_layer.items():
        total_flops += acc["flops"]
        total_bytes += acc["bytes"]
        intensity = acc["flops"] / acc["bytes"] if acc["bytes"] else 0.0
        est_us = max(acc["flops"] / peak_flops, acc["bytes"] / bw) * 1e6
        layers.append({
            "layer": layer,
            "flops": acc["flops"],
            "bytes": acc["bytes"],
            "ops": int(acc["ops"]),
            "intensity": intensity,
            "bound": "compute" if intensity > ridge else "memory",
            "est_us": est_us,
        })
    layers.sort(key=lambda r: (-r["est_us"], r["layer"]))
    return {
        "layers": layers,
        "ridge_flops_per_byte": ridge,
        "peak_flops": float(peak_flops),
        "mem_gbps": float(mem_gbps),
        "total_flops": total_flops,
        "total_bytes": total_bytes,
    }


def roofline_counter_events(attribution: Dict, pid: int = 0,
                            ts0: float = 0.0) -> List[Dict]:
    """Chrome-trace ``C`` (counter) events rendering the attribution as
    per-layer roofline tracks: one ``roofline:<layer>`` counter per layer,
    laid out sequentially by estimated time so the track reads as a
    time-weighted layer walk."""
    evs = []
    ts = float(ts0)
    for lay in attribution.get("layers", ()):
        evs.append({
            "ph": "C", "pid": int(pid), "ts": ts,
            "name": f"roofline:{lay['layer']}",
            "args": {
                "est_us": round(lay["est_us"], 3),
                "flops_per_byte": round(lay["intensity"], 3),
                "bound": 1.0 if lay["bound"] == "compute" else 0.0,
            },
        })
        ts += max(1.0, lay["est_us"])
    return evs


def attach_roofline_tracks(perfetto_path: str, attribution: Dict,
                           pid: int = 0) -> int:
    """Append the roofline counter tracks to an existing Perfetto JSON
    trace (atomically), starting after its last event.  Returns the number
    of counter events added."""
    with open(perfetto_path) as f:
        data = json.load(f)
    evs = data.setdefault("traceEvents", [])
    ts0 = 0.0
    for e in evs:
        if isinstance(e, dict):
            ts0 = max(ts0, float(e.get("ts", 0) or 0) + float(e.get("dur", 0) or 0))
    added = roofline_counter_events(attribution, pid=pid, ts0=ts0)
    evs.extend(added)
    tmp = perfetto_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, perfetto_path)
    return len(added)


# -------------------------------------------------------- what-if scoring
def mesh_candidates(num_devices: int) -> List[Tuple[int, int, int]]:
    """Every (dp, tp, pp) factorization of ``num_devices``."""
    out = []
    n = max(1, int(num_devices))
    for dp in range(1, n + 1):
        if n % dp:
            continue
        rest = n // dp
        for tp in range(1, rest + 1):
            if rest % tp:
                continue
            out.append((dp, tp, rest // tp))
    return out


def score_candidates(candidates: Sequence[Tuple[int, int, int]], *,
                     params_bytes: float, activation_bytes: float,
                     flops_per_step: float, table=None, device=None,
                     auditor: Optional[CostAudit] = None) -> List[Dict]:
    """Re-price candidate (dp, tp, pp) meshes against the live audited
    table: per-candidate predicted step time (compute + the collective
    terms its layout implies) with audit-backed confidence — the decayed
    divergence history of exactly the cost buckets the candidate depends
    on.  Analytic-fallback terms score low confidence (0.25), measured-
    but-never-audited buckets medium (0.5), audited buckets ``1/ratio``.
    Returns the candidates ranked by predicted step time."""
    from . import calibrate as _cal
    from .. import collectives as C

    if table is None:
        table = _cal.active_table()
    if auditor is None:
        auditor = _AUDIT
    if device is None:
        import jax

        device = jax.devices()[0]
    peak = _cal.device_peak_flops(device)
    bdiv = auditor.bucket_divergence() if auditor is not None else {}
    usable = table is not None and len(table) > 0
    results = []
    for dp, tp, pp in candidates:
        world = max(1, dp * tp * pp)
        compute_us = float(flops_per_step) / world / peak * 1e6
        terms: List[Tuple[str, int, float]] = []
        if dp > 1:  # data-parallel gradient reduction over the dp axis
            terms.append(("all_reduce", dp, float(params_bytes) / max(1, tp * pp)))
        if tp > 1:  # tensor-parallel activation gather + grad scatter
            shard = float(activation_bytes) / tp
            terms.append(("all_gather", tp, shard))
            terms.append(("reduce_scatter", tp, shard))
        if pp > 1:  # stage-boundary p2p per microbatch wave
            terms.append(("ppermute", pp, float(activation_bytes)))
        comm_us = 0.0
        notes = []
        scores = []
        for op, ax, nb in terms:
            us = table.lookup_us(op, ax, int(nb)) if usable else None
            if us is None:
                us = C.analytic_cost_us(op, nb / 1e9, ax)
                source, score = "analytic", 0.25
            else:
                key = (op, ax, _cal._bucket(int(nb)))
                d = bdiv.get(key)
                if d is None:
                    source, score = "measured", 0.5
                else:
                    source = "audited"
                    score = max(0.0, min(1.0, 1.0 / max(1.0, d["ratio"])))
            comm_us += us
            scores.append(score)
            notes.append({"op": op, "axis_size": ax, "bytes": int(nb),
                          "us": us, "source": source})
        results.append({
            "mesh": {"dp": dp, "tp": tp, "pp": pp},
            "predicted_step_us": compute_us + comm_us,
            "compute_us": compute_us,
            "comm_us": comm_us,
            "confidence": sum(scores) / len(scores) if scores else 1.0,
            "terms": notes,
        })
    results.sort(key=lambda r: r["predicted_step_us"])
    return results
