"""DebugLogger (reference legacy/vescale/debug/debug_log.py:40):
per-rank operation/communication logging gated by VESCALE_DEBUG_MODE."""

from __future__ import annotations

import sys
import time
from typing import Any, Iterable, Optional

__all__ = ["DebugLogger"]


class DebugLogger:
    """Env-gated structured logger.  ``VESCALE_DEBUG_MODE=1`` logs every
    record; ``VESCALE_DEBUG_MODE=rank0,rank1,...`` restricts ranks."""

    rank: int = 0
    _enabled: Optional[bool] = None
    _ranks: Optional[set] = None
    _stream = sys.stderr

    @classmethod
    def enabled(cls) -> bool:
        if cls._enabled is None:
            from ..analysis import envreg

            v = envreg.get_str("VESCALE_DEBUG_MODE") or ""
            if not v or v == "0":
                cls._enabled, cls._ranks = False, None
            elif v == "1":
                cls._enabled, cls._ranks = True, None
            else:
                cls._enabled = True
                cls._ranks = {int(x) for x in v.replace("rank", "").split(",") if x.strip().isdigit()}
        return cls._enabled

    @classmethod
    def update_vescale_debug_mode_from_env(cls) -> None:
        cls._enabled = None

    @classmethod
    def log(cls, category: str, *parts: Any) -> None:
        if not cls.enabled():
            return
        if cls._ranks is not None and cls.rank not in cls._ranks:
            return
        msg = " ".join(str(p) for p in parts)
        print(f"[vescale_tpu:{category}:r{cls.rank}:{time.time():.3f}] {msg}", file=cls._stream)

    @classmethod
    def log_communication(cls, op: str, *detail: Any) -> None:
        """(reference _CommunicationLogger:141)"""
        cls.log("comm", op, *detail)

    @classmethod
    def log_operator(cls, op: str, *detail: Any) -> None:
        """(reference _OperatorLogger:231)"""
        cls.log("op", op, *detail)
