from .debug_log import DebugLogger
from .comm_mode import CommDebugMode, comm_counts
from . import pdb
