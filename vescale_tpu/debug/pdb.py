"""Distributed-friendly pdb (reference legacy/vescale/debug/pdb.py):
break only on a chosen process, with stdin redirected to the tty."""

from __future__ import annotations

import os
import pdb
import sys

__all__ = ["ForkedPdb", "set_trace"]


class ForkedPdb(pdb.Pdb):
    """Pdb that works from forked/spawned worker processes."""

    def interaction(self, *args, **kwargs):
        _stdin = sys.stdin
        try:
            sys.stdin = open("/dev/stdin")
            super().interaction(*args, **kwargs)
        finally:
            sys.stdin = _stdin


def set_trace(rank: int = 0, current_rank: int = 0) -> None:
    """Break only on ``rank`` (single-controller: process index)."""
    if current_rank == rank:
        ForkedPdb().set_trace(sys._getframe().f_back)
