"""CommDebugMode — count the collectives a computation performs.

Capability parity with the reference CommDebugMode
(vescale/dtensor/debug/_comm_mode.py:21), which intercepts dispatched
communication ops eagerly.  TPU-native: communication is decided by the XLA
compiler, so the ground truth is the compiled program — we lower the jitted
function and count collective ops in the (stable)HLO.  This catches comms
the eager interceptor can never see (GSPMD-inserted reshards), making it
strictly more faithful on TPU.

Quantized-collective attribution: the int8 gradient collectives
(collectives.all_reduce_q / q_psum and friends) move ONE packed byte
buffer per logical collective, with a fixed wire-dtype convention —
REDUCTION payloads are signed ``s8``, pure MOVEMENT payloads unsigned
``u8``.  ``count_collectives`` keys on that: an ``s8`` all-gather is the
wire form of a quantized logical all-reduce and counts under
``all_reduce`` with an ``all_reduce:int8`` tag (an ``s8`` all-to-all
likewise under ``reduce_scatter``); ``u8`` collectives keep their own
logical op with an ``:int8`` tag.  Step reports therefore stay comparable
across compression settings instead of quantized runs showing phantom
scatter/gather traffic.  (Within this framework only the quantized
collectives put s8/u8 payloads on the wire.)
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional

import jax

__all__ = [
    "comm_counts",
    "count_collectives",
    "collective_wire_bytes",
    "CommDebugMode",
]

# HLO/stableHLO opcodes per logical collective.  Async collectives appear
# as op-start/op-done pairs — only the start (or sync form) is counted, so
# each real collective counts once.
_COLLECTIVE_OPCODES = {
    "all_reduce": {"all-reduce", "all-reduce-start", "stablehlo.all_reduce"},
    "all_gather": {"all-gather", "all-gather-start", "stablehlo.all_gather"},
    "reduce_scatter": {"reduce-scatter", "stablehlo.reduce_scatter"},
    "all_to_all": {"all-to-all", "stablehlo.all_to_all"},
    "collective_permute": {
        "collective-permute",
        "collective-permute-start",
        "stablehlo.collective_permute",
    },
}
# applied opcodes are bare lowercase tokens immediately before '(' — operand
# references carry a '%' prefix and never precede '(' directly.  stableHLO
# additionally quotes the opcode: `"stablehlo.all_gather"(...)`.
_OPCODE_RE = re.compile(r'(?<![%\w.])"?([a-z][a-z0-9\-\._]*)"?\(')
# the instruction's RESULT dtype: first type token after '=' (HLO spelling)
_RESULT_DTYPE_RE = re.compile(r"=\s*\(?\s*([a-z][a-z0-9]*)\[")

# wire-dtype convention -> logical-op remap (module docstring)
_S8_LOGICAL = {"all_gather": "all_reduce", "all_to_all": "reduce_scatter"}


def _line_wire_dtype(line: str) -> Optional[str]:
    """'int8' when the collective's payload rides the quantized wire
    convention (s8 = packed reduction, u8 = packed movement), else None."""
    m = _RESULT_DTYPE_RE.search(line)
    if m and m.group(1) in ("s8", "u8"):
        return m.group(1)
    if "stablehlo" in line:  # stablehlo spelling: tensor<...xi8> / xui8>
        if "xui8>" in line:
            return "u8"
        if "xi8>" in line:
            return "s8"
    return None


def count_collectives(text: str) -> Dict[str, int]:
    """Count collective ops in (stable)HLO text — the shared counter behind
    ``comm_counts`` and the telemetry step reports, so the two views agree
    by construction on the same program.

    Quantized collectives (s8/u8 payloads, module docstring) count under
    their LOGICAL op plus a ``<op>:int8`` tag key; tag keys are extra
    detail and excluded from ``total`` (their instructions are already
    counted once under the logical op)."""
    out = {name: 0 for name in _COLLECTIVE_OPCODES}
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("//") or "=" not in line:
            continue
        for opcode in _OPCODE_RE.findall(line):
            matched = False
            for name, ops in _COLLECTIVE_OPCODES.items():
                if opcode in ops:
                    wire = _line_wire_dtype(line)
                    if wire is not None:
                        logical = _S8_LOGICAL.get(name, name) if wire == "s8" else name
                        out[logical] = out.get(logical, 0) + 1
                        tag = f"{logical}:int8"
                        out[tag] = out.get(tag, 0) + 1
                    else:
                        out[name] += 1
                    matched = True
                    break
            if matched:
                break  # one collective application per instruction line
    out["total"] = sum(v for k, v in out.items() if k != "total" and ":" not in k)
    return out


# ------------------------------------------------------- wire-byte model
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# stableHLO spelling: tensor<4x128xf32> / tensor<i8> (scalar)
_STABLEHLO_SHAPE_RE = re.compile(r"tensor<((?:[0-9]+x)*)(u?[a-z][a-z0-9]*)>")
_STABLEHLO_DTYPES = {
    "i1": 1, "i8": 1, "ui8": 1, "i16": 2, "ui16": 2, "f16": 2, "bf16": 2,
    "i32": 4, "ui32": 4, "f32": 4, "i64": 8, "ui64": 8, "f64": 8,
}
_GROUPS_V1_RE = re.compile(r"replica_groups=\{\{([0-9, ]*)\}")
_GROUPS_V2_RE = re.compile(r"replica_groups=\[\s*\d+\s*,\s*(\d+)\s*\]<=")
# stableHLO: replica_groups = dense<[[0, 1]]> : tensor<1x2xi64>
_GROUPS_SHLO_RE = re.compile(r"replica_groups\s*=\s*dense<\[?\[([0-9, ]+)\]")


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_V2_RE.search(line)
    if m:
        return max(1, int(m.group(1)))
    m = _GROUPS_V1_RE.search(line) or _GROUPS_SHLO_RE.search(line)
    if m:
        ids = [t for t in m.group(1).replace(" ", "").split(",") if t]
        return max(1, len(ids))
    return default


def _result_bytes(line: str, op_pos: int) -> int:
    """Sum of the instruction's RESULT buffer bytes — HLO spelling
    (``f32[4,128]``, the segment between '=' and the opcode) or stableHLO
    (``tensor<4x128xf32>``, searched over the whole line since stableHLO
    puts result types at the end).  Tuples sum their element buffers."""
    seg = line[line.index("=") + 1 : op_pos]
    total = 0
    for dtype, dims in _SHAPE_RE.findall(seg):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _stablehlo_result_bytes(lines, i: int) -> int:
    """Result bytes of the stableHLO op starting at ``lines[i]``: the type
    signature's ``-> tensor<...>`` may sit lines below (region-bearing ops
    like ``stablehlo.all_reduce`` close with ``}) : (...) -> tensor<...>``);
    scanning for the arrow also skips attribute tensors (replica_groups'
    ``dense<...> : tensor<NxMxi64>``), which are not results."""
    for j in range(i, min(i + 200, len(lines))):
        if "->" not in lines[j]:
            continue
        seg = lines[j].rsplit("->", 1)[1]
        total = 0
        for dims, dtype in _STABLEHLO_SHAPE_RE.findall(seg):
            if dtype not in _STABLEHLO_DTYPES:
                continue
            n = 1
            for d in dims.split("x"):
                if d:
                    n *= int(d)
            total += n * _STABLEHLO_DTYPES[dtype]
        return total
    return 0


def collective_wire_bytes(text: str, default_group: int = 1) -> Dict[str, float]:
    """Per-device bytes-on-the-wire estimate from compiled HLO, using the
    standard ring algorithmic volumes per collective (result buffer R,
    group size n): all-reduce ``2(n-1)/n * R``, all-gather ``(n-1)/n * R``,
    reduce-scatter ``(n-1) * R`` (its input is ``n*R``), all-to-all
    ``(n-1)/n * R``, collective-permute ``R``.  This is the measurement
    surface of the quantcomm bench: the payload DTYPE comes from the
    program, so an int8-compressed reduction shows its real packed bytes.
    Keys: logical op (quantized ops remapped per the wire convention) plus
    ``<op>:int8`` tags; ``total`` sums the logical keys only."""
    out: Dict[str, float] = {name: 0.0 for name in _COLLECTIVE_OPCODES}
    lines = [l.strip() for l in text.splitlines()]
    for i, line in enumerate(lines):
        if line.startswith("//") or "=" not in line:
            continue
        for m in _OPCODE_RE.finditer(line):
            opcode = m.group(1)
            name = next(
                (nm for nm, ops in _COLLECTIVE_OPCODES.items() if opcode in ops), None
            )
            if name is None:
                continue
            n = _group_size(line, default_group)
            r = _result_bytes(line, m.start())
            if r == 0 and "stablehlo" in line:
                r = _stablehlo_result_bytes(lines, i)
            f = (n - 1) / max(1, n)
            if name == "all_reduce":
                b = 2.0 * f * r
            elif name == "reduce_scatter":
                b = (n - 1) * r
            elif name == "collective_permute":
                b = float(r)
            else:  # all_gather / all_to_all
                b = f * r
            wire = _line_wire_dtype(line)
            if wire is not None:
                logical = _S8_LOGICAL.get(name, name) if wire == "s8" else name
                out[logical] = out.get(logical, 0.0) + b
                tag = f"{logical}:int8"
                out[tag] = out.get(tag, 0.0) + b
            else:
                out[name] += b
            break  # one collective application per instruction line
    out["total"] = sum(v for k, v in out.items() if k != "total" and ":" not in k)
    return out


def comm_counts(fn: Callable, *args, static_argnums=(), **kwargs) -> Dict[str, int]:
    """Compile ``fn(*args, **kwargs)`` and count collectives in the
    optimized HLO (after GSPMD partitioning)."""
    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*args, **kwargs)
    try:
        text = lowered.compile().as_text()
    except Exception:
        text = lowered.as_text()
    return count_collectives(text)


class CommDebugMode:
    """Context-flavored API for migration parity:

        with CommDebugMode() as comm:
            out = comm.trace(fn, *args)
        comm.get_comm_counts()
    """

    def __init__(self):
        self.counts: Dict[str, int] = {}
        self.plan_attribution: Dict[str, Any] = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def trace(self, fn: Callable, *args, **kwargs):
        """Count collectives AND execute — compiling ONCE: the lowered
        program is compiled to an executable that serves both the optimized
        HLO text (counting) and the actual run (previously this compiled
        twice: ``comm_counts``' throwaway ``lowered.compile()`` plus a fresh
        ``jax.jit(fn)(*args)``)."""
        lowered = jax.jit(fn).lower(*args, **kwargs)
        try:
            compiled = lowered.compile()
        except Exception:
            # unpartitionable on this backend: count from the unoptimized
            # text and fall back to the normal jit path for execution
            self.counts = count_collectives(lowered.as_text())
            return jax.jit(fn)(*args, **kwargs)
        self.counts = count_collectives(compiled.as_text())
        return compiled(*args, **kwargs)

    def get_comm_counts(self) -> Dict[str, int]:
        return dict(self.counts)

    def get_total_counts(self) -> int:
        return self.counts.get("total", 0)

    def attribute_plan(self, plan, compiled: bool = False) -> Dict[str, Any]:
        """Attribute collectives to the hops of a multi-hop redistribution
        plan (redistribute_plan.RedistributePlan).

        The static view comes from ``plan_comm_summary`` — the SAME
        accounting that feeds the telemetry ``redistribute.bytes_moved``
        gauge, so the two surfaces agree by construction.  With
        ``compiled=True`` each kernel hop is additionally lowered and its
        optimized HLO counted through ``count_collectives`` (the shared
        counter), attached per hop as ``hlo_collectives`` — ground truth
        for what XLA actually emits on this backend."""
        from ..redistribute_plan import plan_comm_summary

        summary = plan_comm_summary(plan)
        if compiled:
            for hop, rec in zip(plan.hops, summary["hops"]):
                if hop.fn is None or not hasattr(hop.fn, "lower"):
                    continue  # reshard/device_put: runtime-chosen pattern
                arg = jax.ShapeDtypeStruct(
                    hop.src.layout().physical_shape, hop.src.dtype
                )
                lowered = hop.fn.lower(arg)
                try:
                    text = lowered.compile().as_text()
                except Exception:
                    text = lowered.as_text()
                rec["hlo_collectives"] = count_collectives(text)
        self.plan_attribution = summary
        return summary
