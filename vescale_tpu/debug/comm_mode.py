"""CommDebugMode — count the collectives a computation performs.

Capability parity with the reference CommDebugMode
(vescale/dtensor/debug/_comm_mode.py:21), which intercepts dispatched
communication ops eagerly.  TPU-native: communication is decided by the XLA
compiler, so the ground truth is the compiled program — we lower the jitted
function and count collective ops in the (stable)HLO.  This catches comms
the eager interceptor can never see (GSPMD-inserted reshards), making it
strictly more faithful on TPU.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict

import jax

__all__ = ["comm_counts", "count_collectives", "CommDebugMode"]

# HLO/stableHLO opcodes per logical collective.  Async collectives appear
# as op-start/op-done pairs — only the start (or sync form) is counted, so
# each real collective counts once.
_COLLECTIVE_OPCODES = {
    "all_reduce": {"all-reduce", "all-reduce-start", "stablehlo.all_reduce"},
    "all_gather": {"all-gather", "all-gather-start", "stablehlo.all_gather"},
    "reduce_scatter": {"reduce-scatter", "stablehlo.reduce_scatter"},
    "all_to_all": {"all-to-all", "stablehlo.all_to_all"},
    "collective_permute": {
        "collective-permute",
        "collective-permute-start",
        "stablehlo.collective_permute",
    },
}
# applied opcodes are bare lowercase tokens immediately before '(' — operand
# references carry a '%' prefix and never precede '(' directly
_OPCODE_RE = re.compile(r"(?<![%\w.])([a-z][a-z0-9\-\._]*)\(")


def count_collectives(text: str) -> Dict[str, int]:
    """Count collective ops in (stable)HLO text — the shared counter behind
    ``comm_counts`` and the telemetry step reports, so the two views agree
    by construction on the same program."""
    out = {name: 0 for name in _COLLECTIVE_OPCODES}
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("//") or "=" not in line:
            continue
        for opcode in _OPCODE_RE.findall(line):
            matched = False
            for name, ops in _COLLECTIVE_OPCODES.items():
                if opcode in ops:
                    out[name] += 1
                    matched = True
                    break
            if matched:
                break  # one collective application per instruction line
    out["total"] = sum(v for k, v in out.items() if k != "total")
    return out


def comm_counts(fn: Callable, *args, static_argnums=(), **kwargs) -> Dict[str, int]:
    """Compile ``fn(*args, **kwargs)`` and count collectives in the
    optimized HLO (after GSPMD partitioning)."""
    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*args, **kwargs)
    try:
        text = lowered.compile().as_text()
    except Exception:
        text = lowered.as_text()
    return count_collectives(text)


class CommDebugMode:
    """Context-flavored API for migration parity:

        with CommDebugMode() as comm:
            out = comm.trace(fn, *args)
        comm.get_comm_counts()
    """

    def __init__(self):
        self.counts: Dict[str, int] = {}
        self.plan_attribution: Dict[str, Any] = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def trace(self, fn: Callable, *args, **kwargs):
        """Count collectives AND execute — compiling ONCE: the lowered
        program is compiled to an executable that serves both the optimized
        HLO text (counting) and the actual run (previously this compiled
        twice: ``comm_counts``' throwaway ``lowered.compile()`` plus a fresh
        ``jax.jit(fn)(*args)``)."""
        lowered = jax.jit(fn).lower(*args, **kwargs)
        try:
            compiled = lowered.compile()
        except Exception:
            # unpartitionable on this backend: count from the unoptimized
            # text and fall back to the normal jit path for execution
            self.counts = count_collectives(lowered.as_text())
            return jax.jit(fn)(*args, **kwargs)
        self.counts = count_collectives(compiled.as_text())
        return compiled(*args, **kwargs)

    def get_comm_counts(self) -> Dict[str, int]:
        return dict(self.counts)

    def get_total_counts(self) -> int:
        return self.counts.get("total", 0)

    def attribute_plan(self, plan, compiled: bool = False) -> Dict[str, Any]:
        """Attribute collectives to the hops of a multi-hop redistribution
        plan (redistribute_plan.RedistributePlan).

        The static view comes from ``plan_comm_summary`` — the SAME
        accounting that feeds the telemetry ``redistribute.bytes_moved``
        gauge, so the two surfaces agree by construction.  With
        ``compiled=True`` each kernel hop is additionally lowered and its
        optimized HLO counted through ``count_collectives`` (the shared
        counter), attached per hop as ``hlo_collectives`` — ground truth
        for what XLA actually emits on this backend."""
        from ..redistribute_plan import plan_comm_summary

        summary = plan_comm_summary(plan)
        if compiled:
            for hop, rec in zip(plan.hops, summary["hops"]):
                if hop.fn is None or not hasattr(hop.fn, "lower"):
                    continue  # reshard/device_put: runtime-chosen pattern
                arg = jax.ShapeDtypeStruct(
                    hop.src.layout().physical_shape, hop.src.dtype
                )
                lowered = hop.fn.lower(arg)
                try:
                    text = lowered.compile().as_text()
                except Exception:
                    text = lowered.as_text()
                rec["hlo_collectives"] = count_collectives(text)
        self.plan_attribution = summary
        return summary
