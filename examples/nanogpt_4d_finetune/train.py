"""nanoGPT 4D training example.

Mirrors the reference recipe (legacy/examples/nanogpt_4D_finetune/
finetune_4D.py): "zero model change" — the single-device model + a sharding
plan + the framework wrappers.  Runs on any device count (virtual CPU mesh
included):

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/nanogpt_4d_finetune/train.py --dp 2 --tp 4 --steps 20

With --data pointing at a nanoGPT-style .bin token file the native C++
loader feeds batches; otherwise a synthetic stream is used.
"""

from __future__ import annotations

import argparse
import os
import sys

# allow running straight from a repo checkout
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--n-layer", type=int, default=4)
    ap.add_argument("--n-embd", type=int, default=256)
    ap.add_argument("--n-head", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--grad-clip", type=float, default=1.0)
    ap.add_argument("--data", type=str, default=None, help="token .bin file")
    ap.add_argument("--zero2", action="store_true", help="use DistributedOptimizer")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import vescale_tpu as vt
    from vescale_tpu.dmodule import parallelize_module
    from vescale_tpu.models.nanogpt import GPT, GPTConfig, cross_entropy_loss, nanogpt_plan
    from vescale_tpu.parallel import DistributedOptimizer
    from vescale_tpu.train import make_train_step
    from vescale_tpu.ndtimeline import init_ndtimers, ndtimeit, flush, LoggingHandler

    mesh = vt.DeviceMesh(("dp", "tp"), (args.dp, args.tp))
    cfg = GPTConfig(
        block_size=args.seq,
        vocab_size=50304,
        n_layer=args.n_layer,
        n_head=args.n_head,
        n_embd=args.n_embd,
        dropout=0.0,
    )
    model = GPT(cfg)
    dm = parallelize_module(model, mesh, nanogpt_plan(mesh))
    variables = dm.init(jax.random.key(0), jnp.ones((2, args.seq), jnp.int32))
    params = variables["params"]
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    print(f"mesh {dict(zip(mesh.mesh_dim_names, mesh.shape))}, params {n_params/1e6:.1f}M")

    if args.zero2:
        pspecs = jax.tree_util.tree_map(lambda p: p.sharding.spec, params)
        dopt = DistributedOptimizer(optax.adamw(args.lr), mesh, pspecs, grad_clip=args.grad_clip)
        opt_state = dopt.init(params)

        @jax.jit
        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(
                lambda p: cross_entropy_loss(dm.apply({"params": p}, batch["input"]), batch["target"])
            )(params)
            params, opt_state = dopt.step(params, opt_state, grads)
            return params, opt_state, loss

    else:
        tx = optax.chain(optax.clip_by_global_norm(args.grad_clip), optax.adamw(args.lr))
        opt_state = tx.init(params)
        step = make_train_step(
            dm, tx, lambda lg, b: cross_entropy_loss(lg, b["target"]), donate=False
        )

    if args.data:
        from vescale_tpu.data import TokenDataLoader

        loader = TokenDataLoader(args.data, batch=args.batch, seq_len=args.seq, seed=0)
        get_batch = lambda i: loader.next()
    else:
        def get_batch(i):
            toks = jax.random.randint(jax.random.key(100 + i), (args.batch, args.seq + 1), 0, cfg.vocab_size)
            return {"input": toks[:, :-1], "target": toks[:, 1:]}

    init_ndtimers(handlers=[LoggingHandler(lambda m: None)])
    t0 = time.time()
    for i in range(args.steps):
        with ndtimeit("train-step"):
            batch = get_batch(i)
            params, opt_state, loss = step(params, opt_state, batch)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")
    flush()
    dt = time.time() - t0
    print(f"{args.steps} steps in {dt:.1f}s ({args.steps * args.batch * args.seq / dt:.0f} tok/s)")


if __name__ == "__main__":
    main()
