"""Loss-parity evidence: 4D (dp x tp, SP on) vs single-device nanoGPT.

The reference's core correctness claim is example-level: nanoGPT finetuned
4D matches the single-GPU loss curve — "negligible diff (fp32), ~1% (bf16)"
(legacy/examples/nanogpt_4D_finetune/README.md:3,38-56 + figures/).  This
script reproduces that evidence for vescale_tpu: SAME init, SAME real-text
batches (char-level tokens via the native C++ loader), two runs — a (1,1)
mesh and a (dp,tp) mesh with the full TP/SP plan — and reports per-step
train losses plus the relative difference.

Corpus: with no network egress, the default corpus is the concatenated
Python standard-library source text (real natural-language-ish text,
reproducible on any machine); pass --corpus FILE for e.g. shakespeare.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/nanogpt_4d_finetune/loss_parity.py --steps 30

Results are printed as a markdown table (committed in README.md).
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def build_corpus_bin(out_path: str, corpus_file: str | None, max_bytes: int = 4 << 20) -> int:
    """Char-level tokenize a text corpus into a nanoGPT-style uint16 .bin.
    Returns vocab size (256: raw bytes as tokens)."""
    if corpus_file:
        with open(corpus_file, "rb") as f:
            data = f.read(max_bytes)
    else:
        import sysconfig

        stdlib = sysconfig.get_paths()["stdlib"]
        chunks, total = [], 0
        for p in sorted(glob.glob(os.path.join(stdlib, "*.py"))):
            try:
                b = open(p, "rb").read()
            except OSError:
                continue
            chunks.append(b)
            total += len(b)
            if total >= max_bytes:
                break
        data = b"".join(chunks)[:max_bytes]
    toks = np.frombuffer(data, dtype=np.uint8).astype(np.uint16)
    toks.tofile(out_path)
    return 256


def run(mesh_shape, steps, batch, seq, cfg_kw, data_path, dtype_name, lr):
    """One training run; returns the per-step loss list."""
    import jax

    jax.config.update("jax_threefry_partitionable", True)
    import jax.numpy as jnp
    import optax

    import vescale_tpu as vt
    from vescale_tpu.data import TokenDataLoader
    from vescale_tpu.dmodule import parallelize_module
    from vescale_tpu.models.nanogpt import GPT, GPTConfig, cross_entropy_loss, nanogpt_plan
    from vescale_tpu.train import make_train_step

    dtype = {"fp32": jnp.float32, "bf16": jnp.bfloat16}[dtype_name]
    mesh = vt.DeviceMesh(("dp", "tp"), mesh_shape)
    cfg = GPTConfig(block_size=seq, vocab_size=256, dropout=0.0, dtype=dtype, **cfg_kw)
    dm = parallelize_module(GPT(cfg), mesh, nanogpt_plan(mesh, sequence_parallel=True))
    params = dm.init(jax.random.key(0), jnp.ones((2, seq), jnp.int32))["params"]
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(lr))
    opt = tx.init(params)
    step = make_train_step(dm, tx, lambda lg, b: cross_entropy_loss(lg, b["target"]), donate=False)

    # ONE loader stream (dp_world=1) so both runs see identical batches
    loader = TokenDataLoader(data_path, batch=batch, seq_len=seq, seed=7)
    losses = []
    for _ in range(steps):
        b = loader.next()
        params, opt, loss = step(params, opt, {"input": jnp.asarray(b["input"]), "target": jnp.asarray(b["target"])})
        losses.append(float(loss))
    return losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--n-layer", type=int, default=4)
    ap.add_argument("--n-embd", type=int, default=128)
    ap.add_argument("--n-head", type=int, default=4)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--corpus", type=str, default=None)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    data_path = os.path.join(os.path.dirname(__file__), "corpus_char.bin")
    vocab = build_corpus_bin(data_path, args.corpus)
    print(f"corpus: {os.path.getsize(data_path)//2} tokens (char-level, vocab {vocab})")

    cfg_kw = dict(n_layer=args.n_layer, n_embd=args.n_embd, n_head=args.n_head)
    rows = []
    for dtype_name in ("fp32", "bf16"):
        base = run((1, 1), args.steps, args.batch, args.seq, cfg_kw, data_path, dtype_name, args.lr)
        par4d = run((args.dp, args.tp), args.steps, args.batch, args.seq, cfg_kw, data_path, dtype_name, args.lr)
        rel = [abs(a - b) / max(abs(a), 1e-9) for a, b in zip(base, par4d)]
        rows.append((dtype_name, base, par4d, max(rel)))
        print(f"\n{dtype_name}: single-device vs dp{args.dp}xtp{args.tp} (SP on)")
        for i in range(0, args.steps, max(1, args.steps // 6)):
            print(f"  step {i:3d}: {base[i]:.6f} vs {par4d[i]:.6f}  (rel {rel[i]:.2e})")
        print(f"  final : {base[-1]:.6f} vs {par4d[-1]:.6f}  (max rel diff over run: {max(rel):.2e})")

    print("\nMarkdown table (for README):\n")
    print(f"| dtype | step 0 (1-dev / 4D) | final (1-dev / 4D) | max rel diff |")
    print(f"|---|---|---|---|")
    for name, base, par4d, mx in rows:
        print(f"| {name} | {base[0]:.4f} / {par4d[0]:.4f} | {base[-1]:.4f} / {par4d[-1]:.4f} | {mx:.2e} |")


if __name__ == "__main__":
    main()
