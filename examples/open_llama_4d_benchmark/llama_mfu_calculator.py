"""MFU calculator for llama-family runs (reference
legacy/examples/open_llama_4D_benchmark/llama_mfu_calculator.py:22)."""

from __future__ import annotations


def llama_flops_per_token(hidden: int, inter: int, layers: int, vocab: int, seq: int, kv_heads_ratio: float = 1.0) -> float:
    """Approximate train FLOPs per token (fwd+bwd = 3x fwd, PaLM convention
    6N + attention)."""
    attn_proj = 2 * hidden * hidden * (2 + 2 * kv_heads_ratio)  # q,o + k,v (GQA)
    mlp = 2 * hidden * inter * 3  # gate, up, down
    attn_scores = 2 * 2 * seq * hidden  # QK^T + PV per token
    per_layer = attn_proj + mlp + attn_scores
    head = 2 * hidden * vocab
    return 3.0 * (layers * per_layer + head)


def mfu(tokens_per_sec_per_chip: float, flops_per_token: float, peak_flops: float = 459e12) -> float:
    return tokens_per_sec_per_chip * flops_per_token / peak_flops


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=4096)
    ap.add_argument("--inter", type=int, default=11008)
    ap.add_argument("--layers", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--tok-s-chip", type=float, required=True)
    ap.add_argument("--peak", type=float, default=459e12, help="bf16 peak (v5p default)")
    a = ap.parse_args()
    f = llama_flops_per_token(a.hidden, a.inter, a.layers, a.vocab, a.seq)
    print(f"FLOPs/token: {f:.3e}  MFU: {mfu(a.tok_s_chip, f, a.peak):.3f}")
