"""OpenLlama 4D benchmark runner (reference legacy/examples/
open_llama_4D_benchmark/run_open_llama_w_vescale.py): dp x tp (+SP) llama
with optional HF checkpoint load, timed train steps, MFU report via
llama_mfu_calculator.

  # tiny smoke on a virtual 8-device CPU mesh
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/open_llama_4d_benchmark/run_open_llama.py --dp 2 --tp 4 --tiny --cpu

  # open_llama-3b on real chips (random init unless --hf-ckpt points at
  # a local HF pytorch/safetensors checkpoint — this image has no egress,
  # so there is no downloader; the reference's download_open_llama_ckpt.py
  # role is served by pointing --hf-ckpt at a pre-fetched dir)
  python examples/open_llama_4d_benchmark/run_open_llama.py --dp 1 --tp 1 --bf16 --remat
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from llama_mfu_calculator import llama_flops_per_token, mfu


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=2, help="per-dp-rank microbatch")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=3e-5)
    ap.add_argument("--tiny", action="store_true", help="tiny config (tests/CPU)")
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--remat", action="store_true", help="checkpoint each block")
    ap.add_argument("--no-sp", action="store_true", help="disable sequence parallel")
    ap.add_argument("--hf-ckpt", type=str, default=None, help="local HF checkpoint dir")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--peak-flops", type=float, default=None,
                    help="chip peak bf16 FLOP/s for MFU (default: auto)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import vescale_tpu as vt
    from vescale_tpu.dmodule import parallelize_module
    from vescale_tpu.models.llama import OPEN_LLAMA_3B, Llama, LlamaConfig, llama_plan
    from vescale_tpu.models.nanogpt import cross_entropy_loss
    from vescale_tpu.parallel.optimizer import adamw_lowmem, zero_sharded
    from vescale_tpu.train import make_train_step

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    if args.tiny:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=256, intermediate_size=512,
            num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
            max_position_embeddings=args.seq, dtype=dtype, remat=args.remat,
        )
    else:
        import dataclasses

        cfg = dataclasses.replace(
            OPEN_LLAMA_3B,
            max_position_embeddings=args.seq,
            dtype=dtype,
            remat=args.remat,
            use_flash_attention=True,
        )

    mesh = vt.DeviceMesh(("dp", "tp"), (args.dp, args.tp))
    dm = parallelize_module(Llama(cfg), mesh, llama_plan(mesh, sequence_parallel=not args.no_sp))
    params = dm.init(jax.random.key(0), jnp.ones((2, args.seq), jnp.int32))["params"]
    if args.hf_ckpt:
        from vescale_tpu.models.convert import load_hf_llama

        loaded = load_hf_llama(args.hf_ckpt, cfg)
        params = jax.tree_util.tree_map(
            lambda init, new: jax.device_put(jnp.asarray(new, init.dtype), init.sharding),
            params, loaded,
        )
        print(f"loaded HF checkpoint from {args.hf_ckpt}")
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    print(f"mesh {dict(zip(mesh.mesh_dim_names, mesh.shape))}, params {n_params/1e6:.1f}M")

    pspecs = jax.tree_util.tree_map(lambda p: p.sharding.spec, params)
    tx = zero_sharded(adamw_lowmem(args.lr), mesh, pspecs, dp_dims=("dp",))
    opt_state = tx.init(params)
    step = make_train_step(dm, tx, lambda lg, b: cross_entropy_loss(lg, b["target"]), donate=True)

    B = args.batch * args.dp
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, args.seq + 1)), jnp.int32)
    batch = {"input": toks[:, :-1], "target": toks[:, 1:]}

    key = jax.random.key(1)
    for _ in range(2):  # warmup/compile
        params, opt_state, loss = step(params, opt_state, batch, key)
        float(loss)  # host fetch forces execution (axon tunnel)
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, batch, key)
    float(loss)
    dt = (time.perf_counter() - t0) / args.steps

    n_chips = args.dp * args.tp
    tok_s_chip = B * args.seq / dt / n_chips
    fpt = llama_flops_per_token(
        cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers,
        cfg.vocab_size, args.seq, cfg.num_key_value_heads / cfg.num_attention_heads,
    )
    if args.peak_flops:
        peak = args.peak_flops
    else:
        from bench import peak_flops_per_chip  # repo root is on sys.path

        peak = peak_flops_per_chip(jax.devices()[0])
    print(
        f"step {dt*1e3:.1f} ms  tokens/sec/chip {tok_s_chip:.0f}  "
        f"MFU {mfu(tok_s_chip, fpt, peak):.4f}  (loss {float(loss):.4f})"
    )


if __name__ == "__main__":
    main()
