"""Mixtral expert-parallel benchmark (reference
legacy/examples/mixtral_4D_benchmark/mixtral_train.py: --bsz/--seqlen with
dp x ep/tp mesh).

  python examples/mixtral_4d_benchmark/mixtral_train.py --dp 2 --ep 4 \\
      --bsz 8 --seqlen 256 --layers 2 --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

# allow running straight from a repo checkout
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--ep", type=int, default=4)
    ap.add_argument("--bsz", type=int, default=8)
    ap.add_argument("--seqlen", type=int, default=256)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--inter", type=int, default=1024)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import vescale_tpu as vt
    from vescale_tpu.dmodule import parallelize_module
    from vescale_tpu.models.mixtral import Mixtral, MixtralConfig, mixtral_plan
    from vescale_tpu.models.nanogpt import cross_entropy_loss

    mesh = vt.DeviceMesh(("dp", "ep"), (args.dp, args.ep))
    cfg = MixtralConfig(
        vocab_size=32000,
        hidden_size=args.hidden,
        intermediate_size=args.inter,
        num_hidden_layers=args.layers,
        num_attention_heads=8,
        num_key_value_heads=4,
        num_local_experts=args.experts,
        num_experts_per_tok=2,
        dtype=jnp.float32 if args.cpu else jnp.bfloat16,
    )
    model = Mixtral(cfg)
    dm = parallelize_module(model, mesh, mixtral_plan(mesh))
    v = dm.init(jax.random.key(0), jnp.ones((2, args.seqlen), jnp.int32))
    params = v["params"]
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    print(f"mesh {dict(zip(mesh.mesh_dim_names, mesh.shape))}, params {n_params/1e6:.1f}M")
    tx = optax.adamw(3e-4)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        def lf(p):
            logits, aux = dm.apply({"params": p}, batch["input"], mutable=["losses"])
            return cross_entropy_loss(logits, batch["target"]) + sum(
                jax.tree_util.tree_leaves(aux["losses"])
            )

        loss, grads = jax.value_and_grad(lf)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    toks = jax.random.randint(jax.random.key(1), (args.bsz, args.seqlen + 1), 0, cfg.vocab_size)
    batch = {"input": toks[:, :-1], "target": toks[:, 1:]}
    params, opt, loss = step(params, opt, batch)  # compile
    float(loss)
    t0 = time.time()
    for i in range(args.steps):
        params, opt, loss = step(params, opt, batch)
    float(loss)
    dt = (time.time() - t0) / args.steps
    print(f"loss {float(loss):.4f}, {dt*1e3:.1f} ms/step, {args.bsz*args.seqlen/dt:.0f} tok/s")


if __name__ == "__main__":
    main()
