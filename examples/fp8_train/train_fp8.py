"""fp8 (delayed-scaling) Llama training example (round 5; SURVEY.md:17
new-gen quantized-training scope).

Demonstrates the MODULE path: ``LlamaConfig(use_fp8=True)`` adds an
``_overwrite_with_gradient`` variable collection (per-matmul amax
histories + scales); pass the two-collection bundle to ``make_train_step``
and everything else — DistributedOptimizer dynamic loss scaling, grad
accumulation, checkpointing the bundle — just composes.  The functional
path for custom loops is ``vescale_tpu.quant.fp8_dot`` (see
docs/parallel_overview.md).

Run (CPU demo):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/fp8_train/train_fp8.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

# demo-safe default: run on CPU unless explicitly asked for the real chip
# (probing the default backend first would hang forever on a sick TPU
# plugin — the round-2 failure mode bench.py guards against)
from vescale_tpu.analysis import envreg  # noqa: E402

if not envreg.get_bool("VESCALE_FP8_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax

import vescale_tpu as vt
from vescale_tpu.dmodule import parallelize_module
from vescale_tpu.models.llama import Llama, LlamaConfig, llama_plan
from vescale_tpu.models.nanogpt import cross_entropy_loss
from vescale_tpu.parallel.optimizer import DistributedOptimizer
from vescale_tpu.train import make_train_step

OWG = "_overwrite_with_gradient"


def main():
    n = len(jax.devices())
    tp = 2 if n % 2 == 0 else 1
    mesh = vt.DeviceMesh(("dp", "tp"), (n // tp, tp))
    on_tpu = jax.devices()[0].platform == "tpu"

    cfg = LlamaConfig(
        vocab_size=256,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=4,
        num_attention_heads=8,
        num_key_value_heads=4,
        max_position_embeddings=128,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        use_flash_attention=on_tpu,
        use_fp8=True,
    )
    dm = parallelize_module(Llama(cfg), mesh, llama_plan(mesh))
    variables = dm.init(jax.random.key(0), jnp.ones((2, 64), jnp.int32))
    bundle = {"params": variables["params"], OWG: variables[OWG]}

    pspecs = jax.tree_util.tree_map(lambda p: p.sharding.spec, variables["params"])
    dopt = DistributedOptimizer(
        optax.adamw(3e-4), mesh, pspecs, loss_scale="dynamic", init_scale=2.0**10
    )
    opt_state = dopt.init(variables["params"])  # optimizer sees params ONLY

    step = make_train_step(
        dm, dopt, lambda lg, b: cross_entropy_loss(lg, b["target"]), donate=False
    )

    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 65)), jnp.int32)
    batch = {"input": toks[:, :-1], "target": toks[:, 1:]}

    for i in range(10):
        bundle, opt_state, loss = step(bundle, opt_state, batch)
        if i % 2 == 0:
            scale = float(dopt.current_scale(opt_state))
            print(f"step {i}: loss {float(loss):.4f}  loss_scale {scale:g}")

    # the delayed-scaling state advanced with training
    amax0 = jax.tree_util.tree_leaves(bundle[OWG])[0]
    print("fp8 amax history head:", np.asarray(amax0)[:3])
    print("done")


if __name__ == "__main__":
    main()
