"""Auto-split pipeline training: arbitrary model -> balanced stages.

The reference needs an fx tracer to stage models that are not block lists
(legacy/vescale/pipe/pipe_parser.py).  Here the model function is traced to
a jaxpr and cut by FLOP cost (`vescale_tpu.pipe.split_graph`); the eager
PipeEngine then runs any schedule (1F1B below; pass --zero-bubble for the
dgrad/wgrad-split zero-bubble schedule).

Run (CPU is fine):
    python examples/autosplit_pipeline/train.py [--stages 4] [--zero-bubble]
"""

import argparse
import sys

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn
import optax

sys.path.insert(0, ".")

from vescale_tpu.pipe import PipeEngine, split_graph
from vescale_tpu.plan import PipelineParallelPlan, PipelineScheduleType


class TangledLM(nn.Module):
    """Tied embedding + long skip: not stageable as a plain block list."""

    vocab: int = 512
    width: int = 128
    depth: int = 6

    @nn.compact
    def __call__(self, idx):
        emb = nn.Embed(self.vocab, self.width, name="emb")
        x = emb(idx)
        skip = x
        for i in range(self.depth):
            h = nn.Dense(self.width * 4, name=f"up{i}")(nn.LayerNorm(name=f"ln{i}")(x))
            x = x + nn.Dense(self.width, name=f"down{i}")(nn.gelu(h))
        return emb.attend(nn.LayerNorm(name="lnf")(x + skip))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--zero-bubble", action="store_true")
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    model = TangledLM()
    B, T = 8, 32
    micro = jnp.ones((B // args.microbatches, T), jnp.int32)
    params = model.init(jax.random.key(0), micro)["params"]

    def fn(p, x):
        return model.apply({"params": p}, x)

    plan = PipelineParallelPlan(
        num_stages=args.stages,
        schedule_type=PipelineScheduleType.SIMPLE_1F1B,
        use_zero_bubble=args.zero_bubble,
    )
    gm = split_graph(fn, params, micro, plan)  # trace at MICROBATCH shape
    print(f"{gm.num_groups} groups; tied groups: {list(gm.shared_groups)}")
    for g in range(gm.num_groups):
        print(f"  stage {g}: {len(gm.group_param_names(g))} param leaves")

    def loss_fn(logits, tgt):
        oh = jax.nn.one_hot(tgt, logits.shape[-1])
        return -jnp.mean(jnp.sum(oh * jax.nn.log_softmax(logits), axis=-1))

    engine = PipeEngine(gm, plan, loss_fn)
    tx = optax.adamw(3e-3)
    full = params
    opt = tx.init(full)
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        toks = jnp.asarray(rng.integers(0, model.vocab, (B, T + 1)), jnp.int32)
        loss, grads_pg = engine.forward_backward(
            gm.partition_params(full),
            {"input": toks[:, :-1], "target": toks[:, 1:]},
            num_microbatches=args.microbatches,
        )
        grads = gm.merge_params([dict(g) for g in grads_pg])
        updates, opt = tx.update(grads, opt, full)
        full = optax.apply_updates(full, updates)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:3d}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
