"""Resilient bf16 training example: crash-resume + dynamic loss scaling.

Demonstrates the round-4 recovery/mixed-precision surfaces together (the
MegaScale-style recovery recipe the reference's checkpoint README
describes, legacy/vescale/checkpoint/README.md:37-49):

  * ``CheckpointManager`` — step-named saves, keep-K rotation, resume from
    the newest COMMITTED checkpoint (torn saves are invisible);
  * fire-and-forget async saves (training never blocks on io; chunk writes
    ride the native C++ pool when available);
  * ``DistributedOptimizer(loss_scale="dynamic")`` — found-inf detection
    with bitwise skip-step and scale backoff for bf16 training.

Kill it mid-run and start it again: it continues from the last committed
step.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/resilient_train/train.py --steps 40 --save-every 10
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--save-every", type=int, default=10)
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--ckpt-dir", type=str, default="/tmp/vescale_tpu_resilient_ckpts")
    ap.add_argument("--crash-at", type=int, default=None,
                    help="simulate a crash (os._exit) after this step")
    args = ap.parse_args()

    import jax

    # site hooks may pin jax_platforms before the env var is read (see
    # README "Running tests"); honor an explicit JAX_PLATFORMS=cpu here
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    if not jax.config.jax_threefry_partitionable:
        jax.config.update("jax_threefry_partitionable", True)
    import jax.numpy as jnp
    import optax

    import vescale_tpu as vt
    from vescale_tpu.checkpoint import CheckpointManager
    from vescale_tpu.dmodule import parallelize_module
    from vescale_tpu.models.nanogpt import GPT, GPTConfig, cross_entropy_loss, nanogpt_plan
    from vescale_tpu.parallel import DistributedOptimizer

    mesh = vt.DeviceMesh(("dp", "tp"), (args.dp, args.tp))
    cfg = GPTConfig(block_size=128, vocab_size=512, n_layer=4, n_head=8,
                    n_embd=256, dropout=0.0, dtype=jnp.bfloat16)
    dm = parallelize_module(GPT(cfg), mesh, nanogpt_plan(mesh))
    idx0 = jnp.ones((2, cfg.block_size), jnp.int32)
    variables = dm.init(jax.random.key(0), idx0)
    params = variables["params"]
    pspecs = jax.tree_util.tree_map(lambda p: p.sharding.spec, params)

    dopt = DistributedOptimizer(
        optax.adamw(3e-4), mesh, pspecs, grad_clip=1.0, loss_scale="dynamic"
    )
    opt_state = dopt.init(params)

    mgr = CheckpointManager(args.ckpt_dir, keep=args.keep)
    start = 0
    latest = mgr.latest_step()
    if latest is not None:
        restored = mgr.restore({"model": params, "optimizer": opt_state})
        params, opt_state = restored["model"], restored["optimizer"]
        start = latest + 1
        print(f"[resume] continuing from committed step {latest}")

    @jax.jit
    def step_fn(params, opt_state, batch):
        def lf(p):
            logits = dm.apply({"params": p}, batch["input"])
            return dopt.scale_loss(cross_entropy_loss(logits, batch["target"]), opt_state)

        loss, grads = jax.value_and_grad(lf)(params)
        # unscale with the PRE-step scale (the one lf multiplied by) — the
        # post-step scale differs on backoff/growth steps
        unscaled = loss / dopt.current_scale(opt_state)
        params, opt_state = dopt.step(params, opt_state, grads)
        return params, opt_state, unscaled

    # optional hang watchdog (VESCALE_WATCHDOG_TIMEOUT=30 arms it): a step
    # that stops making progress dumps all-thread stacks and aborts so a
    # supervisor restart resumes from the last committed step — see
    # docs/resilience.md "Multi-host: coordinated recovery"
    from vescale_tpu.resilience import Watchdog

    wd = Watchdog.from_env()
    if wd is not None:
        wd.start()

    rng = np.random.default_rng(0)
    handle = None
    for i in range(start, args.steps):
        if wd is not None:
            wd.beat(i)
        toks = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (args.dp * 4, cfg.block_size + 1)), jnp.int32
        )
        batch = {"input": toks[:, :-1], "target": toks[:, 1:]}
        params, opt_state, loss = step_fn(params, opt_state, batch)
        scale = float(dopt.current_scale(opt_state))
        print(f"step {i:4d}  loss {float(loss):.4f}  loss_scale {scale:.0f}")
        if i % args.save_every == 0 or i == args.steps - 1:
            # fire-and-forget: training continues while chunks write
            handle = mgr.save(i, {"model": params, "optimizer": opt_state}, async_checkpoint=True)
        if args.crash_at is not None and i == args.crash_at:
            print(f"[crash] simulating SIGKILL at step {i}")
            os._exit(137)
    if handle is not None:
        handle.wait()  # only the LAST save is worth blocking the exit for
    if wd is not None:
        wd.stop()
    print(f"done; latest committed checkpoint: step {mgr.latest_step()}")


if __name__ == "__main__":
    main()
