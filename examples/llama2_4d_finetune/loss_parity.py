"""Loss-parity evidence: 4D (dp x tp, SP on, ZeRO-2) llama vs single device.

The reference publishes llama-2-3b 4D-finetune loss curves overlapping the
single-GPU run (legacy/examples/llama2_4D_finetune/README.md:24-29 +
figures/llama2_3b_train_losses.jpg).  This reproduces that evidence for the
llama family in vescale_tpu — GQA attention, SwiGLU, RMSNorm — with the
SAME init and SAME batches on a (1,1) mesh vs a (dp,tp) mesh with the full
TP/SP plan AND the ZeRO-2 sharded optimizer in the loop.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/llama2_4d_finetune/loss_parity.py --steps 30 --cpu

Results are printed as a markdown table (committed in README.md).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from examples.nanogpt_4d_finetune.loss_parity import build_corpus_bin


def run(mesh_shape, steps, batch, seq, cfg_kw, data_path, dtype_name, lr):
    import jax

    jax.config.update("jax_threefry_partitionable", True)
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec

    import vescale_tpu as vt
    from vescale_tpu.data import TokenDataLoader
    from vescale_tpu.dmodule import parallelize_module
    from vescale_tpu.models.llama import Llama, LlamaConfig, llama_plan
    from vescale_tpu.models.nanogpt import cross_entropy_loss
    from vescale_tpu.parallel.optimizer import zero_sharded
    from vescale_tpu.train import make_train_step

    dtype = {"fp32": jnp.float32, "bf16": jnp.bfloat16}[dtype_name]
    mesh = vt.DeviceMesh(("dp", "tp"), mesh_shape)
    cfg = LlamaConfig(
        vocab_size=256, max_position_embeddings=seq, dtype=dtype,
        use_flash_attention=False,  # dense: bitwise-comparable across meshes
        **cfg_kw,
    )
    dm = parallelize_module(Llama(cfg), mesh, llama_plan(mesh, sequence_parallel=True))
    params = dm.init(jax.random.key(0), jnp.ones((2, seq), jnp.int32))["params"]
    pspecs = jax.tree_util.tree_map(
        lambda p: p.sharding.spec if isinstance(p.sharding, NamedSharding) else PartitionSpec(),
        params,
    )
    # grad clip + ZeRO-2-sharded adamw — the reference trains llama2 with
    # grad_clip 1.0 and the DistributedOptimizer (llama_train.py flags)
    tx = zero_sharded(
        optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(lr)), mesh, pspecs
    )
    opt = tx.init(params)
    step = make_train_step(dm, tx, lambda lg, b: cross_entropy_loss(lg, b["target"]), donate=False)

    loader = TokenDataLoader(data_path, batch=batch, seq_len=seq, seed=11)
    losses = []
    for _ in range(steps):
        b = loader.next()
        params, opt, loss = step(
            params, opt, {"input": jnp.asarray(b["input"]), "target": jnp.asarray(b["target"])}
        )
        losses.append(float(loss))
    return losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--corpus", type=str, default=None)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    data_path = os.path.join(os.path.dirname(__file__), "corpus_char.bin")
    build_corpus_bin(data_path, args.corpus)
    print(f"corpus: {os.path.getsize(data_path)//2} tokens (char-level)")

    cfg_kw = dict(
        hidden_size=args.hidden,
        intermediate_size=args.hidden * 2,
        num_hidden_layers=args.layers,
        num_attention_heads=args.heads,
        num_key_value_heads=args.kv_heads,
    )
    rows = []
    for dtype_name in ("fp32", "bf16"):
        base = run((1, 1), args.steps, args.batch, args.seq, cfg_kw, data_path, dtype_name, args.lr)
        par4d = run((args.dp, args.tp), args.steps, args.batch, args.seq, cfg_kw, data_path, dtype_name, args.lr)
        rel = [abs(a - b) / max(abs(a), 1e-9) for a, b in zip(base, par4d)]
        rows.append((dtype_name, base, par4d, max(rel)))
        print(f"\n{dtype_name}: single-device vs dp{args.dp}xtp{args.tp} (SP + ZeRO-2)")
        for i in range(0, args.steps, max(1, args.steps // 6)):
            print(f"  step {i:3d}: {base[i]:.6f} vs {par4d[i]:.6f}  (rel {rel[i]:.2e})")
        print(f"  final : {base[-1]:.6f} vs {par4d[-1]:.6f}  (max rel diff: {max(rel):.2e})")

    print("\nMarkdown table (for README):\n")
    print("| dtype | step 0 (1-dev / 4D) | final (1-dev / 4D) | max rel diff |")
    print("|---|---|---|---|")
    for name, base, par4d, mx in rows:
        print(f"| {name} | {base[0]:.4f} / {par4d[0]:.4f} | {base[-1]:.4f} / {par4d[-1]:.4f} | {mx:.2e} |")


if __name__ == "__main__":
    main()
