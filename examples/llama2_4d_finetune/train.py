"""Llama-2 4D finetune example (reference legacy/examples/llama2_4D_finetune/
llama_train.py): TP+SP+DP llama with ZeRO-2 optimizer and checkpointing.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/llama2_4d_finetune/train.py --dp 2 --tp 4 --tiny --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

# allow running straight from a repo checkout
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--lr", type=float, default=3e-5)
    ap.add_argument("--tiny", action="store_true", help="tiny config (tests/CPU)")
    ap.add_argument("--save", type=str, default=None, help="checkpoint path")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import vescale_tpu as vt
    import vescale_tpu.checkpoint as ckpt
    from vescale_tpu.dmodule import parallelize_module
    from vescale_tpu.models.llama import LLAMA2_7B, Llama, LlamaConfig, llama_plan
    from vescale_tpu.models.nanogpt import cross_entropy_loss
    from vescale_tpu.parallel import DistributedOptimizer

    if args.tiny:
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=256,
            intermediate_size=512,
            num_hidden_layers=4,
            num_attention_heads=8,
            num_key_value_heads=4,
            max_position_embeddings=args.seq,
            dtype=jnp.float32 if args.cpu else jnp.bfloat16,
        )
    else:
        cfg = LLAMA2_7B

    mesh = vt.DeviceMesh(("dp", "tp"), (args.dp, args.tp))
    model = Llama(cfg)
    dm = parallelize_module(model, mesh, llama_plan(mesh))
    v = dm.init(jax.random.key(0), jnp.ones((2, args.seq), jnp.int32))
    params = v["params"]
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    print(f"mesh {dict(zip(mesh.mesh_dim_names, mesh.shape))}, params {n_params/1e6:.1f}M")

    pspecs = jax.tree_util.tree_map(lambda p: p.sharding.spec, params)
    dopt = DistributedOptimizer(optax.adamw(args.lr), mesh, pspecs, grad_clip=1.0)
    opt_state = dopt.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: cross_entropy_loss(dm.apply({"params": p}, batch["input"]), batch["target"])
        )(params)
        params, opt_state = dopt.step(params, opt_state, grads)
        return params, opt_state, loss

    for i in range(args.steps):
        toks = jax.random.randint(jax.random.key(100 + i), (args.batch, args.seq + 1), 0, cfg.vocab_size)
        batch = {"input": toks[:, :-1], "target": toks[:, 1:]}
        params, opt_state, loss = step(params, opt_state, batch)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")

    if args.save:
        ckpt.save(args.save, {"model": params, "optimizer": opt_state})
        print(f"checkpoint saved to {args.save}")


if __name__ == "__main__":
    main()
